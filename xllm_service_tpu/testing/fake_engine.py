"""In-process fake engine implementing the full engine contract.

The reference ships no fake backend (SURVEY.md §4); this is the permanent
hermetic fixture for scheduler/failover/e2e tests AND a reference
implementation of the engine side of the wire contract:

- registers itself in coordination under `XLLM:INSTANCE:<TYPE>:<name>` with
  a TTL lease (+incarnation id),
- heartbeats to the master's RPC endpoint (load metrics + KV-cache events),
- serves the engine HTTP surface: enriched /v1/completions +
  /v1/chat/completions (fire-and-forget accept), /health, /rpc/link,
  /rpc/unlink, /rpc/cancel, /rpc/flip_role,
- streams canned Generations back to `source_service_addr` in configurable
  chunks with configurable delays, each stamped with this engine's
  instance/incarnation (the service's stale-incarnation guard keys on it),
- resumes a failed-over request from `resume_generated_token_ids`: the
  canned reply continues from the token after the replayed prefix, so a
  chaos drill can assert the client-visible sequence is byte-identical.

Failure drills: `pause()` (stop heartbeats + lease), `kill()` (drop
everything, refuse health), `set_unhealthy()`; plus scripted faults from
the deterministic plane (`common/faults.py`):

- ``engine.token`` action ``crash`` — hard-kill before emitting the Nth
  delta (crash-on-Nth-token, `after=N`),
- ``engine.heartbeat`` action ``silence`` — stop heartbeats AND let the
  lease lapse (process-hang simulation),
- ``engine.accept`` action ``error``/``drop`` — reject or swallow an
  incoming generation request.
"""

from __future__ import annotations

import json
import queue
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Optional

import requests as _requests
from aiohttp import web
import asyncio

from ..common.faults import FAULTS
from ..common.hashing import prefix_block_hash_hexes
from ..common import topology as topo
from ..common import tracing as _tracing
from .. import profiling as _profiling
from ..common.tracing import TRACER, TraceContext
from ..common.types import (InstanceMetaInfo, InstanceType, KvCacheEvent,
                            TpuTopology, now_ms)
from ..devtools.locks import make_lock
from ..overload.deadline import deadline_expired
from ..coordination.base import CoordinationClient
from ..rpc import instance_key
from ..rpc import wire
from ..utils import get_logger, pick_free_port

logger = get_logger(__name__)


@dataclass
class FakeEngineConfig:
    instance_type: InstanceType = InstanceType.MIX
    models: list[str] = field(default_factory=lambda: ["fake-model"])
    reply_text: str = "Hello from the fake engine!"
    chunk_size: int = 4          # characters per Generations delta
    delay_s: float = 0.0         # inter-delta delay
    heartbeat_interval_s: float = 0.5
    lease_ttl_s: float = 1.0
    block_size: int = 128
    emit_kv_events: bool = True
    host: str = "127.0.0.1"
    # Advertised port (0 = pick a free one). The autoscaler's local
    # actuator passes an explicit port so the instance NAME (host:port)
    # is known to the launcher before the process registers.
    port: int = 0
    # Deterministic capacity model for overload/scaling drills and
    # benches (replaces the old blocking-accept hack): accepts land in a
    # bounded queue and a dispatcher thread starts one generation per
    # 1/service_rate_rps seconds — the engine serves EXACTLY that rate
    # under backlog, so adding instances genuinely adds fleet
    # throughput and overload drills are reproducible. 0 = unlimited
    # (generations start immediately, the hermetic-test default).
    service_rate_rps: float = 0.0
    # Accept-queue bound (only with a service rate); a full queue 503s
    # the accept — the dispatch-failure path upstream. 0 = unbounded.
    accept_queue_limit: int = 0
    # Simulated prefill latency: sleep before the FIRST delta of each
    # generation (delay_s paces the deltas after it). Gives overload
    # benches a realistic TTFT floor so queueing delay can be measured
    # as a ratio against it.
    first_delta_delay_s: float = 0.0
    # Telemetry wiring (ISSUE 15, wire-contract mirror of
    # AgentConfig.telemetry_mode): "owner" routes heartbeats to the
    # rendezvous telemetry owner (deltas stay direct — the hermetic-test
    # default, identical delta wire to before); "mux" multiplexes
    # heartbeats AND deltas as tagged frames on one keepalive session to
    # the owner (the bench's O(engines) connection mode); "master" keeps
    # the legacy elected-master heartbeat funnel.
    telemetry_mode: str = "owner"
    # Coordination-plane static stability (mirror of
    # AgentConfig.degraded_mode): "on" keeps heartbeats flowing to the
    # last-known-good telemetry owner / elected master while the
    # coordination plane is unreachable (owner resolution comes back
    # empty); "off" restores the legacy collapse — no owner, no beats —
    # which is the outage bench's control leg.
    degraded_mode: str = "on"
    # Topology placement coordinate (mirror of AgentConfig.slice_id/
    # topo_host/topo_chip; common/topology.py). A non-empty topo_host
    # marks this instance PLACED; empty keeps the legacy synthetic
    # per-host coordinate, so existing tests/benches see zero change.
    slice_id: str = "fake-slice"
    topo_host: str = ""
    topo_chip: int = -1
    # Modeled PD KV-handoff: when > 0 and a dispatch routes decode to a
    # DIFFERENT instance, the prefill side sleeps
    # transfer_cost(bytes_per_token * prompt_tokens, link) before the
    # first delta — the link class derived from the two instances'
    # registered coordinates, the budgets below standing in for the real
    # agent's BandwidthAccountant pacing. This is what makes the topo
    # bench handoff-bandwidth-bound without real KV payloads.
    kv_handoff_bytes_per_token: int = 0
    ici_bytes_per_s: float = 0.0
    dcn_bytes_per_s: float = 0.0


class FakeEngine:
    #: Max deltas coalesced into one Generations POST (mirrors the real
    #: agent's flush-window batching).
    _PUSH_BATCH = 8

    def __init__(self, coord: CoordinationClient,
                 config: Optional[FakeEngineConfig] = None):
        self.coord = coord
        self.cfg = config or FakeEngineConfig()
        self.port = self.cfg.port or pick_free_port(self.cfg.host)
        self.name = f"{self.cfg.host}:{self.port}"
        self.incarnation_id = uuid.uuid4().hex[:12]
        self.instance_type = self.cfg.instance_type
        self.links: list[str] = []
        self.unlinks: list[str] = []
        self.cancelled: set[str] = set()
        self.accepted_requests: list[dict[str, Any]] = []
        # Raw dispatch wire as received: (content_type, body bytes) per
        # accepted request — the msgpack-failover chaos drill asserts the
        # replayed binary payload is byte-equivalent to first dispatch.
        self.accepted_wire: list[tuple[str, bytes]] = []
        # Trace-propagation headers (x-xllm-*) seen on accepted requests —
        # lets tests assert the RPC channel stamps them on the wire.
        self.accepted_trace_headers: list[dict[str, str]] = []
        self.healthy = True
        self._alive = True
        self._paused = False
        # Accept/stop log for overload drills: (reason, sid) rows —
        # reason in {"deadline", "cancel", "stopped", "overload"} — so
        # tests can assert WHY token production stopped (e.g. a
        # mid-decode deadline expiry stops the engine within one pump
        # interval) without scraping logs.
        self.stop_log: list[tuple[str, str]] = []
        self.rejected_overload = 0
        # Deterministic capacity model (service_rate_rps > 0): accepts
        # queue here; the dispatcher thread starts one generation per
        # 1/rate seconds.
        self._svc_queue: Optional[queue.Queue] = None
        self._svc_thread: Optional[threading.Thread] = None
        if self.cfg.service_rate_rps > 0:
            self._svc_queue = queue.Queue(
                maxsize=max(0, self.cfg.accept_queue_limit))
        # Graceful drain (wire-contract mirror of EngineAgent.drain):
        # draining engines advertise the flag, reject new accepts, and
        # self-stop once the active generation count hits zero.
        self.draining = False
        self._active_lock = make_lock("fake_engine.active", order=66)  # lock-order: 66
        self._active_gens = 0
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._runner: Optional[web.AppRunner] = None
        self._thread: Optional[threading.Thread] = None
        self._hb_thread: Optional[threading.Thread] = None
        self._started = threading.Event()
        self._stored_hashes: list[str] = []
        self._pending_kv_stored: list[str] = []
        self._kv_lock = make_lock("fake_engine.kv_events", order=64)  # lock-order: 64
        # Heartbeat wire: msgpack w/ raw KV keys; demoted on legacy
        # master, re-probed when the master address changes.
        self._hb_wire = wire.WIRE_MSGPACK
        self._hb_master = ""
        # ONE shared, bounded keepalive session for every telemetry hop
        # (heartbeats + Generations pushes): a fresh TCP connect per
        # delta would charge connection setup to the master+wire span in
        # every bench, and per-master pools would make fan-out
        # O(engines × masters). urllib3's pool is thread-safe; we use no
        # session-level state.
        from ..rpc.channel import make_keepalive_session

        self._push_session = make_keepalive_session(pool_connections=4,
                                                    pool_maxsize=8)
        # Rendezvous owner resolution for the sharded telemetry plane
        # (mirrors the real agent).
        from ..multimaster import TelemetryOwnerResolver

        self.telemetry_owner = TelemetryOwnerResolver(
            coord, self.name,
            hold_last_owner=self.cfg.degraded_mode != "off")
        self._telemetry_mode = self.cfg.telemetry_mode
        # Last master address that resolved ("master" funnel mode): the
        # degraded-mode fallback target while the plane is unreachable.
        self._last_master = ""
        self.mux_sends = 0
        self.direct_sends = 0
        # Modeled PD KV-handoff bookkeeping (topo bench evidence): per
        # completed handoff (link, modeled_seconds). Appended from
        # generation threads, read by /admin/topology — deque appends
        # are atomic and the reader copies.
        self.handoff_log: deque[tuple[str, float]] = deque(maxlen=4096)
        # Peer-name -> effective Coord, resolved once from coordination
        # (bench fleets are static; a missing peer is retried on the
        # next handoff, not cached).
        self._peer_coords: dict[str, topo.Coord] = {}

    # ------------------------------------------------------------ lifecycle
    def start(self, register: bool = True) -> "FakeEngine":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"fake-engine-{self.port}")
        self._thread.start()
        if not self._started.wait(10):
            raise RuntimeError("fake engine failed to start")
        if register:
            self.register()
        self._hb_thread = threading.Thread(target=self._heartbeat_loop,
                                           daemon=True,
                                           name=f"fake-hb-{self.port}")
        self._hb_thread.start()
        if self._svc_queue is not None:
            self._svc_thread = threading.Thread(
                target=self._service_loop, daemon=True,
                name=f"fake-svc-{self.port}")
            self._svc_thread.start()
        return self

    def meta(self) -> InstanceMetaInfo:
        return InstanceMetaInfo(
            name=self.name, rpc_address=self.name, type=self.instance_type,
            draining=self.draining,
            dp_size=1,
            topology=TpuTopology(slice_id=self.cfg.slice_id,
                                 host=self.cfg.topo_host,
                                 chip=self.cfg.topo_chip,
                                 mesh_shape=[1],
                                 axis_names=["data"],
                                 host_addrs=[self.name]),
            incarnation_id=self.incarnation_id,
            register_ts_ms=int(time.time() * 1000),
            models=list(self.cfg.models),
            ttft_profiling_data=[[128, 10.0], [512, 30.0], [2048, 100.0]],
            tpot_profiling_data=[[1, 100, 5.0], [8, 1000, 10.0],
                                 [32, 8000, 20.0]],
            # Wire-contract reference impl: accepts the binary dispatch
            # wire, like the real agent.
            wire_formats=[wire.WIRE_MSGPACK, wire.WIRE_JSON],
        )

    def register(self) -> None:
        self.coord.set(instance_key(self.instance_type.value, self.name),
                       self.meta().to_json(), ttl_s=self.cfg.lease_ttl_s)

    def _run(self) -> None:
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)
        app = web.Application()
        app.router.add_post("/v1/completions", self._h_completion)
        app.router.add_post("/v1/chat/completions", self._h_chat)
        app.router.add_get("/v1/models", self._h_models)
        app.router.add_get("/health", self._h_health)
        # Minimal engine /metrics (wire-contract reference: the fleet
        # scrape — /metrics/fleet on any frontend — collects every
        # engine's exposition and re-labels it by instance/role).
        app.router.add_get("/metrics", self._h_metrics)
        app.router.add_post("/rpc/link", self._h_link)
        app.router.add_post("/rpc/unlink", self._h_unlink)
        app.router.add_post("/rpc/cancel", self._h_cancel)
        app.router.add_post("/rpc/flip_role", self._h_flip)
        app.router.add_post("/rpc/drain", self._h_drain)
        # Same per-process trace surface the real agent serves — useful
        # when the fake engine runs out-of-process
        # (examples/run_fake_engine.py).
        app.router.add_get("/admin/topology", self._h_topology)
        app.router.add_get("/admin/trace", _tracing.handle_admin_trace)
        app.router.add_get("/admin/trace/recent",
                           _tracing.handle_admin_trace_recent)
        app.router.add_get("/admin/profile", _profiling.handle_admin_profile)

        async def _start():
            self._runner = web.AppRunner(app)
            await self._runner.setup()
            site = web.TCPSite(self._runner, self.cfg.host, self.port)
            await site.start()

        self._loop.run_until_complete(_start())
        self._started.set()
        try:
            self._loop.run_forever()
        finally:
            self._loop.run_until_complete(self._runner.cleanup())
            self._loop.close()

    # ------------------------------------------------------- failure drills
    def pause(self) -> None:
        """Stop heartbeats + let the lease lapse (process-hang simulation)."""
        self._paused = True
        self.coord.release(instance_key(self.instance_type.value, self.name))

    def resume(self) -> None:
        self._paused = False
        self.register()

    def set_unhealthy(self) -> None:
        self.healthy = False

    def kill(self) -> None:
        """Hard death: lease lapses, health probe fails, no heartbeats."""
        self._alive = False
        self._paused = True
        self.healthy = False
        self.coord.release(instance_key(self.instance_type.value, self.name))
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)

    def stop(self) -> None:
        self._alive = False
        self._paused = True
        self.coord.rm(instance_key(self.instance_type.value, self.name))
        if self._loop is not None and self._loop.is_running():
            self._loop.call_soon_threadsafe(self._loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._push_session.close()

    # ---------------------------------------------------------- heartbeats
    def _heartbeat_loop(self) -> None:
        while self._alive:
            time.sleep(self.cfg.heartbeat_interval_s)
            if self._paused or not self._alive:
                continue
            rule = FAULTS.fire("engine.heartbeat", instance=self.name)
            if rule is not None and rule.action in ("silence", "drop"):
                # Full silence (process-hang model): no heartbeat AND the
                # lease stops being refreshed, so the master's three-state
                # detector walks DELETE → probe → LEASE_LOST/SUSPECT.
                self.coord.release(
                    instance_key(self.instance_type.value, self.name))
                continue
            self.register()  # refresh registration (lease keepalive path)
            # Sharded telemetry (ISSUE 15): beats route to the OWNING
            # master under the rendezvous shard map; "master" mode keeps
            # the legacy elected-master funnel.
            if self._telemetry_mode == "master":
                target = self.coord.get("XLLM:SERVICE:MASTER") or ""
                if target:
                    self._last_master = target
                elif self.cfg.degraded_mode != "off":
                    # Static stability: an unreachable plane resolves no
                    # master — keep beating at the last one that did
                    # (the owner path holds inside the resolver).
                    target = self._last_master
            else:
                target = self.telemetry_owner()
            if not target:
                continue
            with self._kv_lock:
                stored = self._pending_kv_stored
                self._pending_kv_stored = []
            # Wire-contract reference: heartbeats ride msgpack with raw
            # 16-byte KV-event keys (mirror of EngineAgent._heartbeat_loop,
            # including the legacy-master JSON demotion).
            ev = KvCacheEvent(stored=stored)
            payload = {
                "name": self.name,
                "incarnation_id": self.incarnation_id,
                "load_metrics": {
                    # Capacity-model backlog (0 without a service rate):
                    # the planner's pressure heuristic and scale-in
                    # victim picks read the waiting depth.
                    "waiting_requests_num":
                        self._svc_queue.qsize()
                        if self._svc_queue is not None else 0,
                    # Live streams, not the accept log: drain-completion
                    # checks and scale-in victim picks read this.
                    "running_requests_num": self._active_gens,
                    "hbm_cache_usage_perc": 0.1,
                },
                "latency_metrics": {"recent_max_ttft": 12.0,
                                    "recent_max_tbt": 4.0},
            }
            if not self._post_heartbeat(target, payload, ev):
                # Owner died mid-heartbeat-stream: exclude it and hand
                # THIS beat to the rendezvous successor now — the
                # takeover drill asserts no SUSPECT transit, which a
                # full-interval gap could trip.
                self.telemetry_owner.note_failure(target)
                successor = self.telemetry_owner() \
                    if self._telemetry_mode != "master" else ""
                if successor and successor != target:
                    self._post_heartbeat(successor, payload, ev)

    def _post_heartbeat(self, target: str, payload: dict, ev) -> bool:
        """One heartbeat delivery (mux = tagged telemetry frame on the
        shared session; otherwise the legacy wire with msgpack->JSON
        demotion per master)."""
        try:
            if target != self._hb_master:
                self._hb_master = target
                self._hb_wire = wire.WIRE_MSGPACK
            if self._telemetry_mode == "mux":
                payload = dict(payload)
                payload["kv_cache_event"] = ev.to_wire_dict()
                body, ctype = wire.encode_telemetry(
                    [{"t": wire.TELEMETRY_HB, "d": payload}])
                r = self._push_session.post(
                    f"http://{target}/rpc/telemetry", data=body,
                    headers={"Content-Type": ctype}, timeout=2)
                if r.status_code not in (404, 405):
                    if r.status_code == 200:
                        self._adopt_owner_hint(r, target)
                        return True
                    return False
                # Legacy (pre-sharding) master: only the ELECTED master
                # uploads load metrics there, so fall back to the full
                # reference funnel, not just per-endpoint wires.
                self._telemetry_mode = "master"
            fmt = self._hb_wire
            payload = dict(payload)
            payload["kv_cache_event"] = (
                ev.to_wire_dict() if fmt == wire.WIRE_MSGPACK
                else ev.to_dict())
            body, ctype = wire.encode_dispatch(payload, fmt)
            r = self._push_session.post(f"http://{target}/rpc/heartbeat",
                                        data=body,
                                        headers={"Content-Type": ctype},
                                        timeout=2)
            if r.status_code in (400, 415) \
                    and fmt == wire.WIRE_MSGPACK:
                self._hb_wire = wire.WIRE_JSON
                payload["kv_cache_event"] = ev.to_dict()
                body, ctype = wire.encode_dispatch(payload,
                                                   wire.WIRE_JSON)
                r = self._push_session.post(
                    f"http://{target}/rpc/heartbeat", data=body,
                    headers={"Content-Type": ctype}, timeout=2)
            if r.status_code == 200:
                self._adopt_owner_hint(r, target)
                return True
            return False
        except _requests.RequestException:
            return False

    def _adopt_owner_hint(self, r, target: str) -> None:
        """Adopt the receiving master's `owner` hint (its shard-map view
        is fresher than our mirrored membership on a race) so the NEXT
        beat re-routes without waiting a resolver cache window out."""
        if self._telemetry_mode == "master":
            return
        try:
            owner = (r.json() or {}).get("owner", "")
        except ValueError:
            return
        if owner and owner != target:
            self.telemetry_owner.pin(owner)

    # ------------------------------------------------------------ handlers
    async def _h_health(self, req: web.Request) -> web.Response:
        if not self.healthy:
            return web.Response(status=503, text="unhealthy")
        return web.json_response({"status": "ok"})

    async def _h_models(self, req: web.Request) -> web.Response:
        return web.json_response({"object": "list", "data": [
            {"id": m, "object": "model"} for m in self.cfg.models]})

    async def _h_metrics(self, req: web.Request) -> web.Response:
        from ..rpc.channel import session_connection_stats

        conn = session_connection_stats(self._push_session)
        lines = [
            "# TYPE engine_running_requests gauge",
            f"engine_running_requests {len(self.accepted_requests)}",
            "# TYPE engine_cached_prefix_blocks gauge",
            f"engine_cached_prefix_blocks {len(self._stored_hashes)}",
            # Multiplexed-session fan-out evidence (ISSUE 15 bench):
            # distinct master pools + TCP connects this engine ever made
            # on its one telemetry session, plus the mux/direct split.
            "# TYPE engine_telemetry_session_hosts gauge",
            f"engine_telemetry_session_hosts {conn['hosts']}",
            "# TYPE engine_telemetry_connections_created counter",
            f"engine_telemetry_connections_created "
            f"{conn['connections_created']}",
            "# TYPE engine_telemetry_mux_sends_total counter",
            f"engine_telemetry_mux_sends_total {self.mux_sends}",
            "# TYPE engine_telemetry_direct_sends_total counter",
            f"engine_telemetry_direct_sends_total {self.direct_sends}",
        ]
        return web.Response(text="\n".join(lines) + "\n",
                            content_type="text/plain")

    async def _h_link(self, req: web.Request) -> web.Response:
        body = await req.json()
        self.links.append(body.get("peer", {}).get("name", ""))
        return web.json_response({"ok": True})

    async def _h_unlink(self, req: web.Request) -> web.Response:
        body = await req.json()
        self.unlinks.append(body.get("peer_name", ""))
        return web.json_response({"ok": True})

    async def _h_cancel(self, req: web.Request) -> web.Response:
        body = await req.json()
        self.cancelled.add(body.get("service_request_id", ""))
        return web.json_response({"ok": True})

    async def _h_flip(self, req: web.Request) -> web.Response:
        body = await req.json()
        self.instance_type = InstanceType.parse(body.get("type"))
        return web.json_response({"ok": True})

    async def _h_drain(self, req: web.Request) -> web.Response:
        """Graceful retirement (mirror of EngineAgent.drain, on the
        wire-contract reference impl): advertise `draining` on the next
        registration refresh, reject new accepts, and self-stop once the
        in-flight generations finish — the master's lease-lapse handler
        then deregisters the instance as cleanly drained."""
        if not self.draining:
            self.draining = True
            # register() is a blocking coordination write — it runs on
            # the drain thread, never this event loop (the async-blocking
            # bug class PR 8's rule caught in the real agent's /rpc/flip).
            threading.Thread(target=self._drain_then_stop,
                             name=f"fake-drain-{self.port}",
                             daemon=True).start()
        return web.json_response({"ok": True, "draining": True})

    def _drain_then_stop(self, timeout_s: float = 60.0) -> None:
        self.register()   # advertise draining now, not at the next beat
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._active_lock:
                idle = self._active_gens == 0
            if idle:
                break
            time.sleep(0.05)
        self.stop()


    async def _h_completion(self, req: web.Request) -> web.Response:
        return await self._accept(req, chat=False)

    async def _h_chat(self, req: web.Request) -> web.Response:
        return await self._accept(req, chat=True)

    async def _accept(self, req: web.Request, chat: bool) -> web.Response:
        raw = await req.read()
        try:
            body = wire.decode_body(req.content_type, raw)
        except ValueError:
            return web.json_response({"error": "invalid request body"},
                                     status=400)
        if self.draining:
            # A draining engine takes no new work; a request that raced
            # the drain (routed from a pre-drain snapshot) fails over to
            # a surviving instance via the 503 dispatch-failure path.
            return web.json_response({"error": "draining"}, status=503)
        self.accepted_wire.append((req.content_type or "", raw))
        self.accepted_trace_headers.append(
            {k.lower(): v for k, v in req.headers.items()
             if k.lower().startswith("x-xllm-")})
        # Header fallback: a control-plane forward (EngineChannel) carries
        # the sender's active span as x-xllm-* headers; the enriched body
        # key wins when both are present.
        if "trace_context" not in body:
            hctx = TraceContext.from_headers(req.headers)
            if hctx is not None:
                body["trace_context"] = hctx.to_dict()
        rule = FAULTS.fire("engine.accept", instance=self.name,  # xlint: allow-async-blocking(test double: a delay rule on engine.accept deliberately models a stalled engine loop, serialized accepts included)
                           sid=body.get("service_request_id", ""))
        if rule is not None and rule.action == "error":
            return web.Response(status=503, text="fault injected")
        self.accepted_requests.append(body)
        sid = body.get("service_request_id", "")
        # A (re)dispatch supersedes any earlier cancellation of the same
        # request (failover replays may land after a best-effort cancel).
        self.cancelled.discard(sid)
        source = body.get("source_service_addr", "")
        token_ids = body.get("token_ids", [])
        if rule is not None and rule.action == "drop":
            # Accept then swallow: the request hangs until the service
            # times it out or fails it over.
            return web.json_response({"ok": True})
        if self.cfg.emit_kv_events and token_ids:
            with self._kv_lock:
                self._pending_kv_stored.extend(
                    prefix_block_hash_hexes(token_ids, self.cfg.block_size))
        # Already past its deadline on arrival (queued upstream too
        # long): ack with 504 instead of burning service slots — the
        # master's dispatch-failure path surfaces it as non-retryable.
        if deadline_expired(int(body.get("deadline_ms") or 0)):
            self.stop_log.append(("deadline", sid))
            return web.json_response({"error": "deadline exceeded"},
                                     status=504)
        if self._svc_queue is not None:
            # Deterministic capacity model: enqueue for the dispatcher
            # (one generation starts per 1/service_rate_rps s); a full
            # queue is the engine saying "overloaded" — a fast 503 the
            # upstream admission/failover layers handle.
            try:
                self._svc_queue.put_nowait((sid, source, body))
            except queue.Full:
                self.rejected_overload += 1
                self.stop_log.append(("overload", sid))
                return web.json_response(
                    {"error": "engine accept queue full"}, status=503)
            return web.json_response({"ok": True, "queued": True})
        # Fire-and-forget: accept now, stream Generations from a thread.
        threading.Thread(target=self._generate, daemon=True,
                         args=(sid, source, body)).start()
        return web.json_response({"ok": True})

    def _service_loop(self) -> None:
        """Dispatcher for the capacity model: starts at most one
        accepted generation per 1/service_rate_rps seconds (token-bucket
        pacing — an idle engine dispatches immediately with NO added
        latency; under backlog dispatches are spaced exactly one
        interval apart, so the engine serves EXACTLY its configured
        rate, fleet capacity is additive, and overload drills
        reproduce)."""
        interval = 1.0 / self.cfg.service_rate_rps
        next_at = 0.0
        while self._alive:
            try:
                sid, source, body = self._svc_queue.get(timeout=0.2)
            except queue.Empty:
                continue
            now = time.monotonic()
            if next_at > now:
                time.sleep(next_at - now)
                now = time.monotonic()
            threading.Thread(target=self._generate, daemon=True,
                             args=(sid, source, body)).start()
            next_at = max(next_at, now) + interval

    def _push_gens_mux(self, session: "_requests.Session", owner: str,
                       dest: str, gens: list) -> Optional[bool]:
        """One tagged-frame push via the owning master (every delta
        batch here belongs to one request/dest pair — the fake engine
        flushes per generation thread). True/False = the dest's alive
        verdict for this request; None = owner unreachable or relay
        failed (caller excludes the owner and falls back direct)."""
        sid = gens[0].get("service_request_id", "") if gens else ""
        body, ctype = wire.encode_telemetry(
            [{"t": wire.TELEMETRY_GENS, "dest": dest,
              "d": {"gens": gens}}])
        try:
            r = session.post(f"http://{owner}/rpc/telemetry", data=body,
                             headers={"Content-Type": ctype}, timeout=5)
            if r.status_code in (404, 405):
                self._telemetry_mode = "owner"   # legacy master
                return None
            r.raise_for_status()
            payload = r.json()
        except (_requests.RequestException, ValueError) as e:
            logger.warning("fake engine: mux gens push via %s failed: %s",
                           owner, e)
            return None
        self.mux_sends += 1
        dest_ok = payload.get("dest_ok") or {}
        if not dest_ok.get(dest, False):
            return None   # relay to the dest failed; retry direct
        return bool((payload.get("alive") or {}).get(sid, True))

    # ----------------------------------------------------------- generation
    def _generate(self, sid: str, source: str, body: dict[str, Any]) -> None:
        # Active-generation accounting gates the drain self-stop: a
        # draining engine only exits once every stream it accepted has
        # finished (or been cancelled).
        with self._active_lock:
            self._active_gens += 1
        try:
            self._generate_stream(sid, source, body)
        finally:
            with self._active_lock:
                self._active_gens -= 1

    # ------------------------------------------------- modeled KV handoff
    def own_coord(self) -> topo.Coord:
        return topo.effective_coord(
            TpuTopology(slice_id=self.cfg.slice_id, host=self.cfg.topo_host,
                        chip=self.cfg.topo_chip), self.name)

    _PEER_TYPE_ORDER = (InstanceType.DECODE, InstanceType.MIX,
                        InstanceType.DEFAULT, InstanceType.PREFILL,
                        InstanceType.ENCODE)

    def _resolve_coord(self, name: str) -> Optional[topo.Coord]:
        """Effective coordinate of a peer, from its coordination
        registration (cached — bench fleets are static; unresolvable
        peers are retried on the next handoff, not negatively cached)."""
        c = self._peer_coords.get(name)
        if c is not None:
            return c
        for t in self._PEER_TYPE_ORDER:
            try:
                raw = self.coord.get(instance_key(t.value, name))
            except Exception:  # noqa: BLE001  # xlint: allow-broad-except(plane outage = no coordinate; the handoff just goes unmodeled)
                return None
            if raw:
                try:
                    meta = InstanceMetaInfo.from_json(raw)
                except (ValueError, TypeError):
                    continue
                c = topo.effective_coord(meta.topology, name)
                self._peer_coords[name] = c
                return c
        return None

    def _modeled_handoff(self, body: dict[str, Any],
                         prompt_tokens: int) -> tuple[str, float]:
        """(link, modeled seconds) for this dispatch's prefill→decode KV
        handoff; ("", 0.0) when unmodeled (no bytes-per-token knob, no
        split PD pair, or peer coordinate unresolvable)."""
        bpt = self.cfg.kv_handoff_bytes_per_token
        decode_name = (body.get("routing") or {}).get("decode_name") or ""
        if bpt <= 0 or not decode_name or decode_name == self.name:
            return "", 0.0
        peer = self._resolve_coord(decode_name)
        if peer is None:
            return "", 0.0
        link = topo.link_class(self.own_coord(), peer)
        nbytes = bpt * max(1, prompt_tokens)
        return link, topo.transfer_cost(nbytes, link,
                                        self.cfg.ici_bytes_per_s,
                                        self.cfg.dcn_bytes_per_s)

    async def _h_topology(self, req: web.Request) -> web.Response:
        """Topo bench evidence: own coordinate + the modeled-handoff log
        (link class and modeled wire ms per completed handoff)."""
        mine = self.own_coord()
        rows = list(self.handoff_log)
        counts: dict[str, int] = {}
        for link, _s in rows:
            counts[link] = counts.get(link, 0) + 1
        return web.json_response({
            "name": self.name,
            "coord": {"slice_id": mine.slice_id, "host": mine.host,
                      "chip": mine.chip, "placed": mine.placed},
            "handoff_counts": counts,
            "handoffs": [{"link": link, "ms": s * 1000.0}
                         for link, s in rows],
        })

    def _generate_stream(self, sid: str, source: str,
                         body: dict[str, Any]) -> None:
        session = self._push_session
        text = self.cfg.reply_text
        max_tokens = int(body.get("max_tokens", 1 << 30))
        chunks = [text[i:i + self.cfg.chunk_size]
                  for i in range(0, len(text), self.cfg.chunk_size)]
        chunks = chunks[:max_tokens] or [""]
        n = len(chunks)
        # Failover resume: `resume_generated_token_ids` is the prefix the
        # client already received (the service appended it to token_ids);
        # continue the canned reply from the next token. Token ids stay
        # position-stable across the resume so a chaos drill can assert
        # the assembled sequence is byte-identical to a no-fault run.
        resume = list(body.get("resume_generated_token_ids") or ())
        start = min(len(resume), n)
        prompt_tokens = len(body.get("token_ids", [])) - len(resume)
        total_tokens = n
        seq = 0
        if start >= n:
            # Everything was already delivered before the failover: emit
            # just the terminal delta.
            chunks = chunks + [""]
            n += 1
        # Trace propagation: parent this engine's stage spans under the
        # carried context (the frontend root, or the scheduler's failover
        # span on a replayed dispatch). The MIX fake engine serves both
        # stages in one process, so the PD KV-handoff hop is modeled as a
        # zero-work span to keep traces shaped like the real pipeline.
        ctx = TraceContext.from_dict(body.get("trace_context"))
        # require_ctx: direct engine hits (no carried context) must not
        # root orphan single-span traces.
        span_kw: dict[str, Any] = dict(
            ctx=ctx, require_ctx=True, request_id=sid, instance=self.name,
            incarnation=self.incarnation_id)
        with TRACER.span("engine.prefill", prompt_tokens=prompt_tokens,
                         resumed_tokens=len(resume), **span_kw):
            pass
        # Modeled PD KV handoff (topo bench): when the dispatch routed
        # decode to a different instance, charge the link-classed wire
        # time for the prompt's KV payload before the first delta —
        # prefill→decode handoff gates TTFT exactly like the real
        # stream pull does.
        handoff_link, handoff_s = self._modeled_handoff(body, prompt_tokens)
        with TRACER.span("kv_transfer.offer", simulated=True,
                         link=handoff_link or "none",
                         modeled_ms=handoff_s * 1000.0, **span_kw):
            pass
        # Deltas are BATCHED per push like the real agent's streamer
        # (GenerationStreamer flush window): the first delta flushes
        # immediately (TTFT-critical), later ones coalesce up to
        # _PUSH_BATCH per POST. Fault semantics are preserved — tokens
        # emitted before a crash point are flushed BEFORE the kill, so
        # crash-after-N drills still deliver exactly N tokens.
        pending: list[dict[str, Any]] = []

        def flush() -> Optional[bool]:
            """POST pending deltas; True = delivered & request alive,
            False = service said stop, None = push failed. Mux mode
            rides the owner-routed telemetry session (one connection
            regardless of which master dispatched this request); owner
            failure falls back to the direct wire for THIS flush after
            excluding the dead owner."""
            if not pending:
                return True
            gens = list(pending)
            pending.clear()
            if self._telemetry_mode == "mux":
                owner = self.telemetry_owner()
                if owner:
                    verdict = self._push_gens_mux(session, owner, source,
                                                  gens)
                    if verdict is not None:
                        return verdict
                    self.telemetry_owner.note_failure(owner)
            data, ctype = wire.encode_dispatch(
                {"gens": gens}, wire.WIRE_MSGPACK)
            self.direct_sends += 1
            try:
                r = session.post(f"http://{source}/rpc/generations",
                                 data=data,
                                 headers={"Content-Type": ctype},
                                 timeout=5)
                return bool(r.json().get("alive", {}).get(sid, True))
            except (_requests.RequestException, ValueError) as e:
                logger.warning("fake engine: generations push failed: %s", e)
                return None

        deadline_ms = int(body.get("deadline_ms") or 0)
        if self.cfg.first_delta_delay_s:
            time.sleep(self.cfg.first_delta_delay_s)   # simulated prefill
        if handoff_s > 0:
            time.sleep(handoff_s)                      # modeled KV handoff
            self.handoff_log.append((handoff_link, handoff_s))
        with TRACER.span("engine.decode", **span_kw) as dsp:
            for i in range(start, n):
                chunk = chunks[i]
                if sid in self.cancelled or not self._alive:
                    self.stop_log.append(("cancel", sid))
                    dsp.end("CANCELLED")
                    return
                if deadline_ms and now_ms() > deadline_ms:
                    # Deadline enforcement at the engine (overload
                    # plane): stop producing tokens within ONE pump
                    # interval of expiry — the service side 504s the
                    # client and cancels; this side just stops burning
                    # decode capacity. Tokens already pending are
                    # flushed (they were produced inside the budget).
                    flush()
                    self.stop_log.append(("deadline", sid))
                    dsp.end("DEADLINE")
                    return
                rule = FAULTS.fire("engine.token", instance=self.name,
                                   sid=sid, n=i)
                if rule is not None and rule.action == "crash":
                    logger.info("fault: engine %s crashing before token %d "
                                "of %s", self.name, i, sid)
                    flush()   # tokens before the crash point were emitted
                    dsp.end("CRASHED")
                    self.kill()
                    return
                if rule is not None and rule.action == "delay":
                    alive = flush()
                    if alive is False:
                        dsp.end("STOPPED")
                        return
                    if alive is None:
                        dsp.end("PUSH_FAILED")
                        return
                    time.sleep(rule.delay_s)
                last = i == n - 1
                seq += 1
                gen: dict[str, Any] = {
                    "request_id": body.get("request_id", sid),
                    "service_request_id": sid,
                    "status": {"code": 0, "message": ""},
                    "outputs": [{"index": 0, "text": chunk,
                                 "token_ids": [i] if i < total_tokens else [],
                                 "finish_reason": "stop" if last else "",
                                 "logprobs": []}],
                    "finished": last,
                    "delta_seq": seq,
                    "instance": self.name,
                    "incarnation": self.incarnation_id,
                }
                if last:
                    gen["usage"] = {"num_prompt_tokens": prompt_tokens,
                                    "num_generated_tokens": total_tokens}
                pending.append(gen)
                # First delta (TTFT) and terminal delta flush immediately;
                # a configured inter-delta delay means per-delta pushes
                # (timing-sensitive drills); otherwise coalesce.
                if last or i == start or self.cfg.delay_s \
                        or len(pending) >= self._PUSH_BATCH:
                    alive = flush()
                    if alive is False:
                        self.stop_log.append(("stopped", sid))
                        dsp.end("STOPPED")
                        return  # service told us to stop
                    if alive is None:
                        dsp.end("PUSH_FAILED")
                        return
                if self.cfg.delay_s and not last:
                    time.sleep(self.cfg.delay_s)
            dsp.set(generated_tokens=total_tokens - start)
