"""Paired-effect lifecycle discipline: registry + opt-in leak verifier.

The service's whole job is lifecycle bookkeeping — admission/release,
instance register/evict, lease grant/lapse — and the single most
recurrent bug class in review history is an *unbalanced pair*: the
PR-12 admission-slot leak (any raising path between ``try_admit`` and
``record_new_request`` leaked a slot forever), the PR-9 context-provider
leak (providers never deregistered on cleanup), and the PR-12 gauge
resurrection (a post-deregister ``set(0)`` revived an evicted
``circuit_breaker_open`` series). This module is the machine check,
following the ``locks``/``rcu``/``ownership`` pattern exactly:

**Registry** (statically cross-checked by xlint's pair rules):

- :data:`EFFECT_PAIRS` — every acquire→release effect pair in the tree,
  ``"name": "Acq.meth -> Rel.meth @ scope[; opt]*"``. The scope declares
  HOW the release is guaranteed, which is what the static rules check:

  - ``finally`` — the acquiring function (or every one of its callers,
    for an acquire wrapped in a helper) must hold a ``try/finally`` that
    reaches the release, unless ownership is transferred to the declared
    ``transfer=`` method (whose ``sink=`` method then owns the release).
    ``pair-release`` enforces this; ``pair-once`` flags a path that
    releases twice or releases after the transfer.
  - ``owner`` — the release lives in the owning object's teardown; only
    registry staleness is checked statically, the runtime half checks
    the balance.
  - ``gc`` — released by TTL/background gc; statically staleness-only.
  - ``budget`` — a token bucket (withdraw/deposit), intentionally
    non-zero-summing; balance counters only, no violation checks.
  - ``evict`` — a labeled metric series: created by ``.labels(...)``,
    released ONLY through the blessed ``helper=`` function in
    ``common/metrics.py``. ``pair-evict`` flags direct ``.remove()``
    call sites and the lexical write-after-evict resurrection shape.

  ``strict`` marks pairs whose balance must be ZERO at test teardown
  (the conftest guard enforces it); ``idempotent`` marks pairs whose
  instrumented release only fires when something was actually removed
  (pop-style), so a zero-balance release is not a double-release.

**Runtime** (``XLLM_LEAK_DEBUG=1``): the wrapped acquire/release sites
call :func:`note_acquire`/:func:`note_release`; per-(pair, key) balances
carry the acquisition call stacks (same bookkeeping shape as
``locks.thread_holds``), a release with zero balance on a non-idempotent
pair records a double-release, and :func:`note_series_created` against a
tombstone left by the blessed evict helper records a resurrected metric
series. Violations are recorded, never raised — ``tests/conftest.py``
fails any test that recorded one (or left a nonzero strict balance)
while debug mode is on, so the chaos / multimaster / overload drills
double as a resource-leak detector.

**Escape hatch**: :func:`escape` suppresses leak bookkeeping for a
calling-thread region and requires a reason string, exactly like
``ownership.escape`` / ``rcu.thaw``.
"""

from __future__ import annotations

import os
import threading
import traceback
from dataclasses import dataclass, field
from typing import Any, Optional

from ..utils import get_logger

logger = get_logger(__name__)

#: Acquire→release effect pairs. Key = pair name (the identifier used in
#: ``note_acquire``/``note_release`` calls and violation messages);
#: value = ``"AcqClass.meth -> RelClass.meth @ scope[; option]*"``.
#: xlint's pair rules parse this registry (via :func:`parse_spec`) and
#: cross-check every entry against the tree in both directions.
EFFECT_PAIRS: dict[str, str] = {
    # The PR-12 leak class: one admission-gate slot per admitted request.
    # Ownership transfers to the scheduler at record_new_request; the
    # idempotent winning exit in _remove_request releases it.
    "admission-slot":
        "AdmissionController.try_admit -> AdmissionController.release"
        " @ finally; transfer=Scheduler.record_new_request;"
        " sink=Scheduler._remove_request; strict",
    # Token bucket: deposits are fractional per request, withdrawals
    # whole — intentionally non-zero-summing.
    "retry-budget":
        "RetryBudget.try_spend -> RetryBudget.note_request @ budget",
    # HALF_OPEN probe admit resolves in record() (ok or not).
    "breaker-probe":
        "CircuitBreaker.allow -> CircuitBreaker.record @ owner",
    # Labeled series: created on first .labels(...), released only via
    # the blessed metrics.evict_series helper (PR-12 resurrection class).
    "metric-series":
        "Gauge.labels -> Gauge.remove @ evict; helper=evict_series;"
        " idempotent",
    # The PR-9 leak class: anomaly-context providers must deregister.
    "flight-context":
        "FlightRecorder.add_context_provider ->"
        " FlightRecorder.remove_context_provider @ owner; strict;"
        " idempotent",
    # Tail-sampling side buffer: pending traces promote or drop/gc.
    "span-pending":
        "SpanStore.add_pending -> SpanStore.promote @ gc; idempotent",
    # Streamed KV offers: consumed by the puller or TTL-gc'd.
    "stream-offer":
        "StreamOfferTable.offer -> StreamOfferTable.release @ gc;"
        " idempotent",
    # Exact-replay journal entries: finished by the owner, TTL-gc'd.
    "journal-session":
        "DeltaJournal.start -> DeltaJournal.finish @ gc; idempotent",
    # Leased coordination keys: keepalive stops, lease lapses naturally.
    "coord-lease":
        "CoordinationClient.set -> CoordinationClient.release @ gc;"
        " idempotent",
    # Offload-executor inflight slots (bounded transfer pump).
    "tier-inflight":
        "TieredKVStore.offload -> TieredKVStore._offload_worker @ owner",
    # Continuous-profiling sampler thread: refcounted start/stop; the
    # last stop must join the thread and drop the flight-recorder
    # context provider (idempotent: start with profile_hz=0 spawns
    # nothing, so its stop releases nothing).
    "profiler-thread":
        "SamplingProfiler.start -> SamplingProfiler.stop @ owner;"
        " strict; idempotent",
}

_SCOPES = ("finally", "owner", "gc", "budget", "evict")
_FLAGS = ("strict", "idempotent")
_OPTS = ("transfer", "sink", "helper")


@dataclass(frozen=True)
class PairSpec:
    name: str
    acquire: tuple          # (cls, meth)
    release: tuple          # (cls, meth)
    scope: str
    transfer: Optional[tuple] = None
    sink: Optional[tuple] = None
    helper: Optional[str] = None
    strict: bool = False
    idempotent: bool = False


def _dotted(text: str) -> Optional[tuple]:
    parts = text.strip().split(".")
    if len(parts) != 2 or not all(p.isidentifier() for p in parts):
        return None
    return (parts[0], parts[1])


def parse_spec(name: str, text: Any) -> tuple[Optional[PairSpec], list[str]]:
    """Parse one EFFECT_PAIRS value. Returns ``(spec, errors)`` — the
    single grammar shared by the runtime half and xlint's pair rules
    (which parse the registry out of the AST, fixture stand-ins
    included)."""
    errors: list[str] = []
    if not isinstance(text, str):
        return None, [f"pair '{name}': spec must be a string literal"]
    head, _, opt_text = text.partition(";")
    methods, at, scope = head.partition("@")
    if not at:
        return None, [f"pair '{name}': missing '@ scope'"]
    scope = scope.strip()
    if scope not in _SCOPES:
        return None, [f"pair '{name}': unknown scope '{scope}' "
                      f"(expected one of {', '.join(_SCOPES)})"]
    acq_text, arrow, rel_text = methods.partition("->")
    acq = _dotted(acq_text) if arrow else None
    rel = _dotted(rel_text) if arrow else None
    if acq is None or rel is None:
        return None, [f"pair '{name}': expected 'Cls.meth -> Cls.meth', "
                      f"got '{methods.strip()}'"]
    opts: dict[str, Any] = {}
    for raw in opt_text.split(";"):
        raw = raw.strip()
        if not raw:
            continue
        key, eq, val = raw.partition("=")
        key = key.strip()
        if key in _FLAGS and not eq:
            opts[key] = True
        elif key in ("transfer", "sink") and eq:
            ref = _dotted(val)
            if ref is None:
                errors.append(f"pair '{name}': bad {key}= target '{val}'")
            else:
                opts[key] = ref
        elif key == "helper" and eq and val.strip().isidentifier():
            opts["helper"] = val.strip()
        else:
            errors.append(f"pair '{name}': unknown option '{raw}'")
    if errors:
        return None, errors
    return PairSpec(name=name, acquire=acq, release=rel, scope=scope,
                    **opts), []


_parsed: Optional[dict[str, PairSpec]] = None


def pair_specs() -> dict[str, PairSpec]:
    """Parsed EFFECT_PAIRS (malformed entries dropped; the registry in
    this file is additionally linted, so a malformed entry is a CI
    failure, not a silent skip)."""
    global _parsed
    if _parsed is None:
        out = {}
        for name, text in EFFECT_PAIRS.items():
            spec, errs = parse_spec(name, text)
            if spec is not None:
                out[name] = spec
            else:  # pragma: no cover - registry is linted
                logger.error("malformed EFFECT_PAIRS entry: %s", errs)
        _parsed = out
    return _parsed


# ------------------------------------------------------------------ runtime
_DEBUG = os.environ.get("XLLM_LEAK_DEBUG", "") not in ("", "0")


def debug_enabled() -> bool:
    return _DEBUG


def set_debug(on: bool) -> None:
    """Test hook: arms/disarms the leak verifier for subsequent
    note_* calls."""
    global _DEBUG
    _DEBUG = on


@dataclass
class LeakViolation:
    kind: str            # "double-release" | "leak" | "resurrected-series"
    pair: str
    message: str
    thread: str
    stack: list[str] = field(default_factory=list)

    def __str__(self) -> str:
        return f"[{self.kind}:{self.pair}] {self.message} " \
               f"(thread {self.thread})"


# Detector bookkeeping; leaf locks, never held across project locks.
_lviol_lock = threading.Lock()   # lock-order: 905
_violations: list[LeakViolation] = []
# Balances + tombstones share one leaf lock (only touched under debug).
_lbal_lock = threading.Lock()   # lock-order: 906
# (pair, key) -> outstanding acquisition stacks (len == balance).
_balances: dict[tuple, list[list[str]]] = {}
# Evicted labeled-series tombstones: (metric_name, label_key_tuple).
_tombstones: set[tuple] = set()


def violations() -> list[LeakViolation]:
    with _lviol_lock:
        return list(_violations)


def reset_violations() -> None:
    with _lviol_lock:
        _violations.clear()


def _record(kind: str, pair: str, message: str) -> None:
    v = LeakViolation(kind=kind, pair=pair, message=message,
                      thread=threading.current_thread().name,
                      stack=traceback.format_stack(limit=12)[:-2])
    with _lviol_lock:
        _violations.append(v)
    logger.error("lifecycle violation: %s", v)


_tls = threading.local()


class _Escape:
    """Context manager marking a calling-thread region exempt from leak
    bookkeeping (per-thread depth counter, like ``ownership.escape``)."""

    def __enter__(self) -> "_Escape":
        _tls.escape = getattr(_tls, "escape", 0) + 1
        return self

    def __exit__(self, *exc) -> None:
        _tls.escape = getattr(_tls, "escape", 1) - 1


_ESCAPE = _Escape()


def escape(reason: str) -> _Escape:
    """Declare a region exempt from pair bookkeeping. ``reason`` is
    mandatory (the runtime mirror of an ``# xlint: allow-pair-*(reason)``
    comment; xlint flags an empty reason)."""
    if not reason or not isinstance(reason, str):
        raise ValueError("lifecycle.escape requires a non-empty reason "
                         "string")
    return _ESCAPE


def _escaped() -> bool:
    return getattr(_tls, "escape", 0) > 0


def note_acquire(pair: str, key: Any = None) -> None:
    """Record one acquisition of `pair` (optionally keyed — e.g. a
    provider name or offer uuid). Call sites gate nothing: with debug
    off this is one global check."""
    if not _DEBUG or _escaped():
        return
    stack = traceback.format_stack(limit=10)[:-1]
    with _lbal_lock:
        _balances.setdefault((pair, key), []).append(stack)


def note_release(pair: str, key: Any = None) -> None:
    """Record one release of `pair`. A release with zero balance on a
    non-idempotent pair is a double-release (the bug class where two
    exit paths both decrement)."""
    if not _DEBUG or _escaped():
        return
    with _lbal_lock:
        stacks = _balances.get((pair, key))
        if stacks:
            stacks.pop()
            return
    spec = pair_specs().get(pair)
    if spec is not None and (spec.idempotent or spec.scope == "budget"):
        return   # pop-style release or token bucket: zero balance is fine
    _record("double-release", pair,
            f"release with zero balance (key={key!r})")


def note_reset(pair: str) -> None:
    """A blessed bulk-reset of the pair's underlying counter (e.g.
    ``AdmissionController.reset()``): drop its balances so the verifier
    tracks the code's own notion of outstanding effects."""
    if not _DEBUG:
        return
    with _lbal_lock:
        for k in [k for k in _balances if k[0] == pair]:
            del _balances[k]


def balances() -> dict[tuple, int]:
    """Snapshot of nonzero (pair, key) balances — diagnostic helper."""
    with _lbal_lock:
        return {k: len(v) for k, v in _balances.items() if v}


def reset_balances() -> None:
    with _lbal_lock:
        _balances.clear()
        _tombstones.clear()


def strict_imbalances() -> list[LeakViolation]:
    """Leak verdicts for strict pairs: any nonzero balance, reported with
    the oldest outstanding acquisition stack. The conftest guard calls
    this at test teardown."""
    specs = pair_specs()
    out: list[LeakViolation] = []
    with _lbal_lock:
        snap = {k: list(v) for k, v in _balances.items() if v}
    for (pair, key), stacks in sorted(snap.items(), key=lambda kv: str(kv)):
        spec = specs.get(pair)
        if spec is None or not spec.strict:
            continue
        out.append(LeakViolation(
            kind="leak", pair=pair,
            message=f"{len(stacks)} unreleased acquisition(s) "
                    f"(key={key!r}); oldest acquired at:\n"
                    + "".join(stacks[0][-4:]),
            thread="<teardown>"))
    return out


# ------------------------------------------- labeled metric series half
def note_series_evicted(metric_name: str, key: tuple) -> None:
    """Called by the blessed ``metrics.evict_series`` helper: tombstone
    the evicted child so a later re-creation is caught as a
    resurrection."""
    if not _DEBUG or _escaped():
        return
    with _lbal_lock:
        _tombstones.add((metric_name, key))


def note_series_created(metric_name: str, key: tuple) -> None:
    """Called by ``_Metric.labels()`` when it creates a NEW child: a
    creation against a tombstone is the PR-12 gauge-resurrection bug
    (a stale writer reviving an evicted series). One report per
    tombstone."""
    if not _DEBUG or _escaped():
        return
    with _lbal_lock:
        if (metric_name, key) not in _tombstones:
            return
        _tombstones.discard((metric_name, key))
    _record("resurrected-series", "metric-series",
            f"evicted series {metric_name}{key!r} re-created by a write")


def note_series_revived(label_value: str) -> None:
    """Called by legitimate re-registration paths (an instance with the
    same name re-registers after eviction): clear tombstones carrying
    this label value so the entity's fresh series are not misreported
    as resurrections."""
    if not _DEBUG:
        return
    with _lbal_lock:
        for t in [t for t in _tombstones if label_value in t[1]]:
            _tombstones.discard(t)
