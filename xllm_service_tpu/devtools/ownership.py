"""Shared-state ownership model: discipline registry + runtime verifier.

PR 2 gave lock discipline (``devtools/locks.py``) and PR 8 gave RCU
publication discipline (``devtools/rcu.py``) — but both only govern state
somebody remembered to register. The bug class every review pass keeps
re-finding (unguarded O(fleet) rebuilds, in-place mutation of shared
containers, writes from the wrong thread, context-provider lifetime
leaks) lives in the *unregistered middle*: the mutable attributes on
Scheduler, InstanceMgr, GlobalKVCacheMgr, TieredKVStore, OwnershipRouter,
SloMonitor, … touched from the HTTP loop, the schedule executor, the sync
thread, the failover pool and agent heartbeats all at once. This module
closes it, in the spirit of Eraser-style lockset analysis and
ThreadSanitizer's happens-before checking, adapted to a
declared-discipline codebase:

**Registry** (:data:`STATE_DISCIPLINES`): ``"Class.attr"`` → a declared
discipline, seeded by an auto-inventory pass (``python -m
xllm_service_tpu.devtools.ownership --inventory``) over the
concurrency-relevant classes and then hand-curated:

========================  ====================================================
discipline                contract
========================  ====================================================
``lock:<attr>``           every write (rebind, item store, in-place mutator)
                          happens while the declared lock attribute of the
                          same class is held; ``<attr>`` is cross-checked
                          against the lock registry (``# lock-order``
                          declarations)
``rcu``                   the attribute is an RCU publication — must also be
                          registered in ``rcu.py``'s ``RCU_PUBLICATIONS``
                          (bidirectional); writes are governed by the
                          ``rcu-publish`` rule and the declared writer lock
``confined:<role>``       rebound only from the declared thread role's entry
                          functions (:data:`THREAD_ROLES`); at runtime, only
                          from threads whose name matches the role (the main
                          thread is exempt — single-threaded test drivers
                          stand in for every role)
``owner:<guard>``         sharded-plane state owned by whichever PROCESS the
                          rendezvous map elects (ISSUE 15): every write must
                          be dominated by a successful ``self.<guard>(...)``
                          check (statically: lexically inside an
                          ``if self.<guard>(...)`` body; at runtime: the
                          writing thread's most recent ``<guard>`` call —
                          noted via :func:`note_owner_guard` — returned
                          True). A non-owner writing a sharded heartbeat
                          field is a build failure AND a recorded violation
``init-only``             assigned at construction (and lifecycle teardown),
                          never rebound afterwards; the value may be
                          internally synchronized elsewhere
``immutable``             like ``init-only``, and the value itself is never
                          mutated in place — reads need no synchronization
                          at all
========================  ====================================================

Three xlint rules enforce the registry statically (``state-decl``,
``state-write``, ``state-read`` — see devtools/xlint). Methods named in
:data:`LIFECYCLE_METHODS` are declaration scope, like ``__init__``:
teardown runs after the worker threads are joined.

**Runtime** (``XLLM_STATE_DEBUG=1``): classes decorated with
:func:`verify_state` get an instrumented ``__setattr__`` that records
(thread role, locks held — read from ``locks.py``'s per-thread
acquisition stacks) for every write to a registered attribute and
cross-checks the declared discipline; ``lock:`` container values are
wrapped in raise-nothing guarded views (mutators re-check the
discipline before delegating; confinement governs rebinds only) and
``immutable`` values are deep-frozen with the PR-8 freezer
(``rcu.freeze``). Violations are
recorded, never raised — production code paths behave identically —
and ``tests/conftest.py`` fails any test that recorded one, so the full
chaos / multimaster-kill / tier-drill suites double as an
attribute-race detector. Arming state debug arms the instrumented locks
too (the lock-held check needs their per-thread stacks).

**Escape hatch**: :func:`escape` — ``with ownership.escape(reason):`` —
is the unified hatch: xlint's three state rules skip writes lexically
inside it, and the runtime verifier skips writes made while a thread is
inside one. The reason string is mandatory, exactly like
``rcu.thaw(..., reason)`` and the ``# xlint: allow-*(reason)`` comments
(which the state rules also accept).
"""

from __future__ import annotations

import os
import threading
import traceback
import weakref
from dataclasses import dataclass, field
from typing import Any, Optional

from . import locks as _locks

#: Declared per-attribute state disciplines. Key = "Class.attr" (class
#: matched by NAME, like RCU_FROZEN_TYPES: the owning modules import this
#: module, not the other way around). Value = discipline spec (table in
#: the module docstring). xlint's ``state-decl`` rule is bidirectional
#: over this registry: every post-__init__ attribute assignment in a
#: registered class must be declared here, and every entry must resolve
#: to a live class + assigned attribute (stale entries are violations).
STATE_DISCIPLINES: dict[str, str] = {
    # ----------------------------------------------------------- Scheduler
    # The in-flight request table: every exit path (normal finish, GC
    # timeout, disconnect, instance failure, failover) races the token
    # ingest on it.
    "Scheduler._requests": "lock:_req_lock",
    # Mastership flips run on the coordination watch thread (master-key
    # DELETE) and the sync thread (demotion check) — never a request path.
    "Scheduler.is_master": "confined:mastership",
    "Scheduler._master_watch_id": "confined:mastership",
    # Post-bind re-registration rebinds once, before traffic (the write
    # site carries an ownership.escape with that reason).
    "Scheduler.self_addr": "init-only",
    "Scheduler._opts": "init-only",
    "Scheduler._coord": "init-only",
    # Coordination-plane health monitor (degraded-mode serving): the
    # object is constructed once; all its mutable state lives behind its
    # own leaf lock (see CoordinationHealthMonitor below).
    "Scheduler.coordination_health": "init-only",
    # --------------------------------------------------------- InstanceMgr
    "InstanceMgr._snapshot": "rcu",
    "InstanceMgr._load_infos": "rcu",
    "InstanceMgr._request_load_view": "rcu",
    "InstanceMgr._instances": "lock:_cluster_lock",
    "InstanceMgr._pending_flips": "lock:_flip_lock",
    "InstanceMgr._pending_drains": "lock:_flip_lock",
    "InstanceMgr._load_metrics": "lock:_metrics_lock",
    "InstanceMgr._latency_metrics": "lock:_metrics_lock",
    "InstanceMgr._load_updated_ms": "lock:_metrics_lock",
    "InstanceMgr._request_loads": "lock:_metrics_lock",
    "InstanceMgr._pair_links": "lock:_metrics_lock",
    "InstanceMgr._updated_load_names": "lock:_metrics_lock",
    "InstanceMgr._removed_load_names": "lock:_metrics_lock",
    "InstanceMgr._is_master": "confined:mastership",
    "InstanceMgr._watch_ids": "confined:mastership",
    "InstanceMgr._opts": "init-only",
    "InstanceMgr._coord": "init-only",
    "InstanceMgr._health": "init-only",
    # Post-outage missed-DELETE sweep deadline: armed by the recovery
    # callback, consumed by the reconcile pass — both under the cluster
    # lock.
    "InstanceMgr._post_outage_sweep_until_ms": "lock:_cluster_lock",
    # Sharded telemetry-ingest plane (ISSUE 15). The frame inputs are
    # OWNER-GATED: only the master that owns an instance's telemetry
    # under the rendezvous shard map may coalesce its beats into the
    # published load frame or tombstone its eviction — a non-owner write
    # here would fork the fleet's converged view.
    "InstanceMgr._shard_dirty": "owner:owns_telemetry",
    "InstanceMgr._shard_gone": "owner:owns_telemetry",
    "InstanceMgr._owned_names": "lock:_cluster_lock",
    "InstanceMgr._published_owned": "lock:_metrics_lock",
    "InstanceMgr._shard_seq": "lock:_metrics_lock",
    "InstanceMgr._frames_published": "lock:_metrics_lock",
    "InstanceMgr._frames_applied": "lock:_metrics_lock",
    "InstanceMgr._foreign_heartbeats": "lock:_metrics_lock",
    "InstanceMgr._frame_watch_id": "init-only",
    "InstanceMgr._ownership": "init-only",
    "InstanceMgr._rr_prefill": "init-only",
    "InstanceMgr._rr_decode": "init-only",
    "InstanceMgr._rr_encode": "init-only",
    # ---------------------------------------------------- GlobalKVCacheMgr
    "GlobalKVCacheMgr._snapshot": "rcu",
    "GlobalKVCacheMgr._by_instance": "lock:_lock",
    "GlobalKVCacheMgr._dirty": "lock:_lock",
    "GlobalKVCacheMgr._removed": "lock:_lock",
    "GlobalKVCacheMgr._frame_seq": "lock:_lock",
    "GlobalKVCacheMgr._frames_since_full": "lock:_lock",
    "GlobalKVCacheMgr._bootstrap_buffer": "lock:_lock",
    "GlobalKVCacheMgr._is_master": "confined:mastership",
    "GlobalKVCacheMgr._watch_id": "confined:mastership",
    "GlobalKVCacheMgr._block_size": "immutable",
    "GlobalKVCacheMgr._weights": "immutable",
    "GlobalKVCacheMgr._compact_every": "immutable",
    # ------------------------------------------------------- TieredKVStore
    "TieredKVStore._dram": "lock:_lock",
    "TieredKVStore._ssd": "lock:_lock",
    "TieredKVStore._sums": "lock:_lock",
    "TieredKVStore._pending": "lock:_lock",
    "TieredKVStore._superseded": "lock:_lock",
    "TieredKVStore._free_dram": "lock:_lock",
    "TieredKVStore._free_ssd": "lock:_lock",
    "TieredKVStore._offloaded": "lock:_lock",
    "TieredKVStore._removed": "lock:_lock",
    "TieredKVStore.offload_total": "lock:_lock",
    "TieredKVStore.offload_dropped": "lock:_lock",
    "TieredKVStore.onload_total": "lock:_lock",
    "TieredKVStore.demote_total": "lock:_lock",
    "TieredKVStore.corrupt_total": "lock:_lock",
    "TieredKVStore.bytes_offloaded": "lock:_lock",
    "TieredKVStore.bytes_onloaded": "lock:_lock",
    "TieredKVStore.block_shape": "immutable",
    "TieredKVStore.block_nbytes": "immutable",
    "TieredKVStore.dram_capacity_blocks": "immutable",
    "TieredKVStore.ssd_capacity_blocks": "immutable",
    # ----------------------------------------------------- OwnershipRouter
    "OwnershipRouter._members": "rcu",
    "OwnershipRouter._addrs": "lock:_lock",
    # Rebound once by the post-bind re-registration (escaped write site,
    # same as Scheduler.self_addr); read lock-free on every owner_of.
    "OwnershipRouter.self_addr": "init-only",
    # Mining stat counters: GIL-atomic int adds on the accept path; the
    # write sites carry ownership.escape(reason) — losing a rare
    # increment is acceptable, taking a lock per accept is not.
    "OwnershipRouter.mined": "lock:_lock",
    "OwnershipRouter.mine_misses": "lock:_lock",
    # Telemetry-shard verdict memo (ISSUE 19): nominally lock-guarded
    # like the mining counters, but the beat-path write sites carry
    # ownership.escape(reason) — the memo is keyed by IDENTITY of the
    # RCU-published member tuple and every racer computes the same
    # deterministic owner, so a lost fill is a re-computation, not a
    # wrong answer; taking a lock per heartbeat is the cost the memo
    # exists to remove.
    "OwnershipRouter._own_cache": "lock:_lock",
    # ---------------------------------------------------------- SloMonitor
    "SloMonitor._objectives": "lock:_lock",
    "SloMonitor.ttft_target_ms": "lock:_lock",
    "SloMonitor.tpot_target_ms": "lock:_lock",
    "SloMonitor.alert": "lock:_lock",
    # ------------------------------------------------------ FlightRecorder
    # The context-provider table: registered at owner startup (HTTP
    # service / engine agent threads), iterated by record() on request
    # exit threads — the PR-9 leak/race surface.
    "FlightRecorder._context": "lock:_lock",
    "FlightRecorder._ring": "lock:_lock",
    "FlightRecorder._file": "lock:_file_lock",
    "FlightRecorder._path": "lock:_file_lock",
    # ------------------------------------------------------------- Planner
    "Planner.last_decision": "confined:sync-thread",
    # ------------------------------------------------- AutoscalerController
    # The decision loop's private state: kernel state swapped by tick
    # (sync thread) and the spawn-backoff update (enactment), flip
    # proposals arriving from schedule-path threads, the retiring set,
    # and the bounded decision log — all behind one leaf lock.
    "AutoscalerController._state": "lock:_lock",
    "AutoscalerController._flip_proposals": "lock:_lock",
    "AutoscalerController._retiring": "lock:_lock",
    "AutoscalerController._log": "lock:_lock",
    "AutoscalerController._last_decision_ms": "lock:_lock",
    "AutoscalerController._ticks": "lock:_lock",
    # Topology plane (docs/topology.md): per-slice capacity census and
    # the recently-lost-slice map that targets replacement spawns.
    "AutoscalerController._slice_census": "lock:_lock",
    "AutoscalerController._lost_slices": "lock:_lock",
    "AutoscalerController._opts": "init-only",
    "AutoscalerController._mgr": "init-only",
    "AutoscalerController._actuator": "init-only",
    "AutoscalerController._planner": "init-only",
    "AutoscalerController._is_master_fn": "init-only",
    "AutoscalerController._degraded_fn": "init-only",
    "AutoscalerController._slo": "init-only",
    "AutoscalerController._cfg": "init-only",
    "AutoscalerController._enabled": "init-only",
    # ------------------------------------------------------ FleetActuators
    "HintActuator._seq": "lock:_lock",
    "HintActuator._last_publish": "lock:_lock",
    "HintActuator._coord": "init-only",
    "LocalProcessActuator._procs": "lock:_lock",
    "LocalProcessActuator._spawned_at": "lock:_lock",
    "LocalProcessActuator.launched_total": "lock:_lock",
    "LocalProcessActuator.spawn_failures_total": "lock:_lock",
    "LocalProcessActuator._opts": "init-only",
    "LocalProcessActuator._spawn_cmd": "init-only",
    "LocalProcessActuator._max_procs": "init-only",
    # ------------------------------------------------- AdmissionController
    # The overload-admission gate (overload/admission.py): pending
    # count + shed buckets written from every request-accept thread and
    # the scheduler's exit paths; config rebinds from configure().
    "AdmissionController._per_instance_limit": "lock:_lock",
    "AdmissionController._batch_watermark": "lock:_lock",
    "AdmissionController._retry_after_s": "lock:_lock",
    "AdmissionController._pending": "lock:_lock",
    "AdmissionController._admitted_total": "lock:_lock",
    "AdmissionController._shed_total": "lock:_lock",
    "AdmissionController._shed_window": "lock:_lock",
    # -------------------------------------------------- BrownoutController
    # Degradation state (overload/brownout.py): flipped by the sync
    # thread's tick, read lock-free by the request paths (active() is
    # one GIL-atomic bool load).
    "BrownoutController._enabled": "lock:_lock",
    "BrownoutController._batch_max_tokens": "lock:_lock",
    "BrownoutController._recover_ticks": "lock:_lock",
    "BrownoutController._trace_sample_rate": "lock:_lock",
    "BrownoutController._restore_rate_fn": "lock:_lock",
    "BrownoutController._active": "lock:_lock",
    "BrownoutController._since_s": "lock:_lock",
    "BrownoutController._recover_streak": "lock:_lock",
    "BrownoutController._entered_total": "lock:_lock",
    "BrownoutController._log": "lock:_lock",
    # ------------------------------------- CoordinationHealthMonitor (ISSUE 16)
    # Degraded-mode plane classifier (coordination/health.py): state
    # machine stepped by the sync thread's tick, queried (degraded()) and
    # fed (hold()/note_frozen()) from the reconcile and watch-dispatch
    # threads — all behind one leaf lock (order 26). The held-action log
    # shares that lock. `_entity` follows the post-bind re-registration
    # (escaped write site, same as Scheduler.self_addr).
    "CoordinationHealthMonitor._state": "lock:_lock",
    "CoordinationHealthMonitor._consec_failures": "lock:_lock",
    "CoordinationHealthMonitor._outage_started_mono": "lock:_lock",
    "CoordinationHealthMonitor._outage_started_unix": "lock:_lock",
    "CoordinationHealthMonitor._recover_at_mono": "lock:_lock",
    "CoordinationHealthMonitor._last_tick_mono": "lock:_lock",
    "CoordinationHealthMonitor._outages_total": "lock:_lock",
    "CoordinationHealthMonitor._frozen_events": "lock:_lock",
    "CoordinationHealthMonitor._entity": "init-only",
    "CoordinationHealthMonitor._coord": "init-only",
    "CoordinationHealthMonitor._enabled": "init-only",
    "CoordinationHealthMonitor._after_ticks": "init-only",
    "CoordinationHealthMonitor._jitter_window_s": "init-only",
    "CoordinationHealthMonitor.held": "init-only",
    "CoordinationHealthMonitor.on_degraded": "init-only",
    "CoordinationHealthMonitor.on_recovered": "init-only",
    "HeldActionLog._items": "lock:_lock",
    "HeldActionLog._order": "lock:_lock",
    "HeldActionLog._dropped": "lock:_lock",
    "HeldActionLog._capacity": "init-only",
    # --------------------------------------------------------- RetryBudget
    # Global retry token bucket (overload/retry_budget.py): deposits
    # from accept threads, withdrawals from failover/relay threads.
    "RetryBudget._ratio": "lock:_lock",
    "RetryBudget._cap": "lock:_lock",
    "RetryBudget._tokens": "lock:_lock",
    "RetryBudget._spent_total": "lock:_lock",
    "RetryBudget._denied_total": "lock:_lock",
    # ------------------------------------------------------ CircuitBreaker
    # Per-channel breaker (rpc/breaker.py): outcome recording from every
    # channel-calling thread; state transitions under the same leaf lock.
    "CircuitBreaker._events": "lock:_lock",
    "CircuitBreaker._state": "lock:_lock",
    "CircuitBreaker._opened_at": "lock:_lock",
    "CircuitBreaker._probe_inflight": "lock:_lock",
    "CircuitBreaker._open_total": "lock:_lock",
    # ------------------------------------------------------- EngineChannel
    # The negotiated dispatch-wire slot: set at registration, demoted
    # (one-way, to JSON) on an HTTP 415 — every write site carries an
    # ownership.escape documenting the GIL-atomic benign-race contract.
    "EngineChannel.wire_format": "init-only",
    # ----------------------------------------------------- InferenceEngine
    # Decode-loop telemetry counters: written only by the engine pump
    # (tests drive step() from the main thread, which is role-exempt).
    "InferenceEngine.total_generated": "confined:engine-pump",
    # Decaying latency maxima: pump writes race the heartbeat drain —
    # both go through the telemetry leaf lock (the bare read-then-reset
    # window race was this registry's first runtime catch).
    "InferenceEngine.recent_max_ttft_ms": "lock:_telemetry_lock",
    "InferenceEngine.recent_max_tbt_ms": "lock:_telemetry_lock",
    "InferenceEngine.preemption_count": "confined:engine-pump",
    "InferenceEngine.sarathi_rides": "confined:engine-pump",
    # ---------------------------------------------------- SamplingProfiler
    # Continuous profiler (profiling/sampler.py): refcounted lifecycle +
    # window aggregates behind one leaf lock (order 824); the sampler
    # thread merges each tick under it, /admin/profile reads under it.
    "SamplingProfiler._refs": "lock:_lock",
    "SamplingProfiler._thread": "lock:_lock",
    "SamplingProfiler._stop_evt": "lock:_lock",
    "SamplingProfiler._hz": "lock:_lock",
    "SamplingProfiler._window_s": "lock:_lock",
    "SamplingProfiler._max_stacks": "lock:_lock",
    "SamplingProfiler._max_depth": "lock:_lock",
    "SamplingProfiler._agg": "lock:_lock",
    "SamplingProfiler._ticks": "lock:_lock",
    "SamplingProfiler._window_started": "lock:_lock",
    "SamplingProfiler._prev": "lock:_lock",
    "SamplingProfiler._prev_ticks": "lock:_lock",
    "SamplingProfiler._prev_window_s": "lock:_lock",
    # Sampler-thread heartbeat: rebound only by the sampler loop itself.
    "SamplingProfiler._last_tick_mono": "confined:profiler",
    # Per-code-object label memo: only the sampler thread mutates it, and
    # GIL-atomic dict get/set makes concurrent snapshot reads benign.
    "SamplingProfiler._label_cache": "init-only",
    "SamplingProfiler._roles": "init-only",
}

#: Fully-audited classes: xlint's ``state-decl`` rule requires EVERY
#: attribute these classes assign outside __init__/lifecycle scope to
#: carry a discipline above (the completeness ratchet). Classes that
#: appear in STATE_DISCIPLINES but not here (InferenceEngine: only its
#: decode-loop telemetry counters are registered so far) get their
#: declared attributes enforced without the completeness requirement.
STATE_CLASSES: tuple = (
    "Scheduler",
    "InstanceMgr",
    "GlobalKVCacheMgr",
    "TieredKVStore",
    "OwnershipRouter",
    "SloMonitor",
    "FlightRecorder",
    "Planner",
    "AutoscalerController",
    "HintActuator",
    "LocalProcessActuator",
    "AdmissionController",
    "BrownoutController",
    "CoordinationHealthMonitor",
    "HeldActionLog",
    "RetryBudget",
    "CircuitBreaker",
    "SamplingProfiler",
)

#: Thread roles for ``confined:<role>`` disciplines. ``threads`` are
#: name prefixes matched against ``threading.current_thread().name`` at
#: runtime (the main thread is always exempt); ``entries`` are the
#: "Class.method" functions the static ``state-write`` rule accepts as
#: the role's write scope (a helper whose every resolvable call site
#: sits inside the scope inherits it — same transitive-summary idea as
#: the lock-order graph). Bidirectional: a role no confined declaration
#: references is a stale registry entry.
THREAD_ROLES: dict[str, dict] = {
    "mastership": {
        "threads": ("scheduler-sync", "coord-dispatch", "coord-reader"),
        "entries": (
            "Scheduler._on_master_event",
            "Scheduler.sync_once",
            # Post-outage recovery runs on the sync thread but is
            # reached via the health monitor's on_recovered callback, so
            # the static call-site resolution needs the explicit entry
            # (same for the takeover helper it shares with the watch).
            "Scheduler._recover_from_outage",
            "Scheduler._try_takeover",
            "InstanceMgr.set_as_master",
            "InstanceMgr.set_as_replica",
            "GlobalKVCacheMgr.set_as_master",
            "GlobalKVCacheMgr.set_as_replica",
        ),
    },
    "sync-thread": {
        "threads": ("scheduler-sync",),
        "entries": (
            "Scheduler._sync_loop",
            "Scheduler.sync_once",
            "Planner.plan_once",
            "Planner._finish",
        ),
    },
    "engine-pump": {
        # multihost primaries drive step() from the tick thread instead
        # of the single-process engine loop — both ARE the pump.
        "threads": ("engine-loop", "multihost-tick"),
        "entries": (
            "InferenceEngine._loop",
            "InferenceEngine.step",
        ),
    },
    "profiler": {
        # Continuous-profiling sampler (profiling/sampler.py): one
        # daemon thread per process, walking sys._current_frames().
        "threads": ("profiler-sampler",),
        "entries": (
            "SamplingProfiler._loop",
        ),
    },
}

#: Teardown methods that count as declaration scope (like ``__init__``):
#: they run after worker threads are joined/cancelled, so unguarded
#: rebinds there are lifecycle bookkeeping, not races.
LIFECYCLE_METHODS = ("stop", "close", "shutdown")

_DEBUG = os.environ.get("XLLM_STATE_DEBUG", "") not in ("", "0")


def debug_enabled() -> bool:
    return _DEBUG


# --------------------------------------------------------------- violations
@dataclass
class StateViolation:
    kind: str            # "state-lock" | "state-confined" | "state-reassign"
    message: str
    thread: str
    stack: list[str] = field(default_factory=list)

    def __str__(self) -> str:
        return f"[{self.kind}] {self.message} (thread {self.thread})"


# Detector bookkeeping; never held across project locks.
_sviol_lock = threading.Lock()   # lock-order: 904
_violations: list[StateViolation] = []


def violations() -> list[StateViolation]:
    with _sviol_lock:
        return list(_violations)


def reset_violations() -> None:
    with _sviol_lock:
        _violations.clear()


def _record(kind: str, message: str) -> None:
    v = StateViolation(kind=kind, message=message,
                       thread=threading.current_thread().name,
                       stack=traceback.format_stack(limit=16)[:-2])
    with _sviol_lock:
        _violations.append(v)
    # Imported lazily through locks' logger machinery would be circular;
    # keep it simple — the conftest guard surfaces the message.


# ------------------------------------------------------------- escape hatch
_tls = threading.local()


class _Escape:
    """``with ownership.escape(reason):`` — the unified static + runtime
    hatch. Static: xlint's state rules skip writes lexically inside the
    with-block (and flag an empty reason). Runtime: writes made while
    the thread is inside one are exempt from discipline checks."""

    __slots__ = ()

    def __enter__(self):
        _tls.escape = getattr(_tls, "escape", 0) + 1
        return self

    def __exit__(self, *exc):
        _tls.escape = max(0, getattr(_tls, "escape", 1) - 1)


_ESCAPE = _Escape()


def escape(reason: str) -> _Escape:
    """Declare a write site exempt from its attribute's discipline.
    ``reason`` is mandatory (the runtime mirror of an
    ``# xlint: allow-state-*(reason)`` comment)."""
    if not reason or not isinstance(reason, str):
        raise ValueError("ownership.escape requires a non-empty reason "
                         "string")
    return _ESCAPE


def _escaped() -> bool:
    return getattr(_tls, "escape", 0) > 0


# ----------------------------------------------------- owner-gated guards
def note_owner_guard(guard: str, ok: bool) -> None:
    """Record the calling thread's most recent ``<guard>()`` verdict —
    the runtime half of the ``owner:<guard>`` discipline. Called by the
    guard method itself (e.g. ``InstanceMgr.owns_telemetry``) on every
    invocation; a subsequent write to an owner-gated attribute from this
    thread is checked against this verdict. One thread-local dict store
    — cheap enough to run outside debug mode, so arming the verifier
    mid-run needs no warm-up."""
    guards = getattr(_tls, "owner_guards", None)
    if guards is None:
        guards = _tls.owner_guards = {}
    guards[guard] = ok


def _owner_guard_ok(guard: str) -> bool:
    return getattr(_tls, "owner_guards", {}).get(guard, False)


# --------------------------------------------------------- discipline model
def _parse(spec: str) -> tuple[str, str]:
    """('lock', attr) | ('confined', role) | ('rcu'|'init-only'|
    'immutable', '')."""
    kind, _, arg = spec.partition(":")
    return kind, arg


def _rcu_writer_lock(cls_name: str, attr: str) -> Optional[str]:
    from . import rcu

    spec = rcu.RCU_PUBLICATIONS.get(f"{cls_name}.{attr}")
    if not spec:
        return None
    _, _, lock = spec.partition("@")
    return lock.strip() or None


def _thread_confined_ok(role: str) -> bool:
    t = threading.current_thread()
    if t is threading.main_thread():
        # Single-threaded test drivers stand in for every role; a main-
        # thread write cannot race a role thread it is standing in for.
        return True
    prefixes = THREAD_ROLES.get(role, {}).get("threads", ())
    return any(t.name.startswith(p) for p in prefixes)


def _lock_held(obj: Any, lock_attr: str) -> Optional[bool]:
    """True/False when verifiable; None when the lock attribute is not an
    instrumented lock (plain threading lock, or not created yet)."""
    lk = obj.__dict__.get(lock_attr)
    if isinstance(lk, _locks.InstrumentedLock):
        return _locks.thread_holds(lk)
    return None


#: Construction-scope method names: writes from these frames are exempt
#: from the confined/init-only/immutable rebind checks at runtime, the
#: exact scope the static state-write rule exempts.
_DECL_SCOPE = ("__init__", "setup", "__post_init__", *LIFECYCLE_METHODS)


def _check_write(obj: Any, cls_name: str, name: str, spec: str,
                 first: bool, meth: str = "") -> None:
    # First assignment = construction scope (init writes predate any
    # lock hold; __init__ itself is single-threaded by contract).
    kind, arg = _parse(spec)
    if kind == "lock":
        if not first and _lock_held(obj, arg) is False:
            _record("state-lock",
                    f"{cls_name}.{name} (lock:{arg}) written without "
                    f"holding {arg} (held: {_locks.held_locks()})")
    elif kind == "rcu":
        wlock = _rcu_writer_lock(cls_name, name)
        if not first and wlock is not None \
                and _lock_held(obj, wlock) is False:
            _record("state-lock",
                    f"{cls_name}.{name} (rcu) swapped without the "
                    f"declared writer lock {wlock} "
                    f"(held: {_locks.held_locks()})")
    elif kind == "confined":
        if not first and meth not in _DECL_SCOPE \
                and not _thread_confined_ok(arg):
            _record("state-confined",
                    f"{cls_name}.{name} (confined:{arg}) written from "
                    f"thread {threading.current_thread().name!r}, which "
                    f"is not in role {arg!r} "
                    f"({THREAD_ROLES.get(arg, {}).get('threads', ())})")
    elif kind == "owner":
        if not first and meth not in _DECL_SCOPE \
                and not _owner_guard_ok(arg):
            _record("state-owner",
                    f"{cls_name}.{name} (owner:{arg}) written without a "
                    f"passing {arg}() check on this thread — only the "
                    f"rendezvous owner may write sharded telemetry state")
    elif kind in ("init-only", "immutable"):
        if not first and meth not in _DECL_SCOPE:
            _record("state-reassign",
                    f"{cls_name}.{name} ({kind}) rebound after "
                    f"construction")


# ----------------------------------------------------------- guarded views
class _GuardedBase:
    """Mixin state for guarded container views (one per lock:/confined:
    container value under XLLM_STATE_DEBUG=1). Mutators re-check the
    attribute's discipline, record on violation, then delegate —
    behavior is otherwise identical to the plain container."""

    __slots__ = ()

    def _chk(self) -> None:
        if not _DEBUG:
            return   # view outlived set_debug(False): go inert
        owner = self._xllm_owner()
        if owner is None or _escaped():
            return
        _check_write(owner, self._xllm_cls, self._xllm_attr,
                     self._xllm_spec, first=False)


def _guard_method(mname: str):
    def guarded(self, *a, **k):
        self._chk()
        return getattr(self._xllm_base, mname)(self, *a, **k)

    guarded.__name__ = mname
    return guarded


_MUTATORS = {
    dict: ("__setitem__", "__delitem__", "pop", "popitem", "clear",
           "update", "setdefault", "__ior__"),
    list: ("__setitem__", "__delitem__", "append", "extend", "insert",
           "remove", "sort", "reverse", "clear", "pop", "__iadd__",
           "__imul__"),
    set: ("add", "discard", "remove", "pop", "clear", "update",
          "difference_update", "intersection_update",
          "symmetric_difference_update", "__ior__", "__iand__",
          "__isub__", "__ixor__"),
}

_guarded_types: dict[type, type] = {}


def _guarded_type(base: type) -> type:
    sub = _guarded_types.get(base)
    if sub is None:
        ns: dict[str, Any] = {
            "__slots__": ("_xllm_owner", "_xllm_cls", "_xllm_attr",
                          "_xllm_spec"),
            "_xllm_base": base,
            # rcu.freeze treats guarded views as their base container
            # (the deep-freeze must still bite on a drained/published
            # guarded list — the PR-7 bug class).
            "_xllm_guarded_kind": base.__name__,
        }
        for m in _MUTATORS[base]:
            ns[m] = _guard_method(m)
        sub = type(f"Guarded{base.__name__.capitalize()}",
                   (_GuardedBase, base), ns)
        _guarded_types[base] = sub
    return sub


def _guard_container(value: Any, obj: Any, cls_name: str, attr: str,
                     spec: str) -> Any:
    base = type(value)
    if base not in _MUTATORS:
        return value
    sub = _guarded_type(base)
    out = sub(value)
    out._xllm_owner = weakref.ref(obj)
    out._xllm_cls = cls_name
    out._xllm_attr = attr
    out._xllm_spec = spec
    return out


# ----------------------------------------------------------- class hookup
#: Classes decorated with @verify_state: registered-name -> [class, ...]
#: (instrumented/restored together by set_debug).
_DECORATED: dict[str, list[type]] = {}
_original_setattr: dict[type, Any] = {}

#: Per-class discipline index derived from STATE_DISCIPLINES.
_class_specs: dict[str, dict[str, str]] = {}
for _key, _spec in STATE_DISCIPLINES.items():
    _cls, _, _attr = _key.partition(".")
    _class_specs.setdefault(_cls, {})[_attr] = _spec


def _instrument(cls: type) -> None:
    if cls in _original_setattr:
        return
    cls_name = cls.__name__
    specs = _class_specs.get(cls_name, {})
    orig = cls.__setattr__
    _original_setattr[cls] = orig

    def checking_setattr(self, name, value, *, _specs=specs,
                         _cls=cls_name, _orig=orig):
        spec = _specs.get(name)
        if spec is None or _escaped():
            return _orig(self, name, value)
        import sys

        first = name not in self.__dict__
        # The writing frame's method name: the runtime mirror of the
        # static rule's construction/lifecycle scope exemption (a
        # reaper-thread stop() rebinding a confined watch id is
        # teardown bookkeeping, not a race). Debug-mode-only cost.
        _check_write(self, _cls, name, spec, first,
                     sys._getframe(1).f_code.co_name)
        kind, _ = _parse(spec)
        if kind in ("lock", "owner"):
            # Confined containers stay unwrapped: construction may run on
            # an arbitrary thread (e2e masters build on "master-loop") and
            # confinement only governs rebinds, not in-place bookkeeping.
            # Owner-gated containers ARE wrapped: every in-place mutation
            # re-checks the thread's last guard verdict.
            value = _guard_container(value, self, _cls, name, spec)
        elif kind == "immutable":
            from . import rcu

            value = rcu.freeze(value)
        return _orig(self, name, value)

    cls.__setattr__ = checking_setattr


def _restore(cls: type) -> None:
    orig = _original_setattr.pop(cls, None)
    if orig is not None:
        cls.__setattr__ = orig


def verify_state(cls: type) -> type:
    """Class decorator opting a class into the runtime verifier. Identity
    (zero overhead) unless ``XLLM_STATE_DEBUG=1`` / :func:`set_debug` —
    instrumentation is installed and removed dynamically on the class
    object, so instances created after arming are checked."""
    _DECORATED.setdefault(cls.__name__, []).append(cls)
    if _DEBUG:
        _instrument(cls)
    return cls


def set_debug(on: bool) -> None:
    """Test hook: toggles the verifier for ALL decorated classes.
    Arming also arms the instrumented locks (the lock-held check reads
    their per-thread acquisition stacks); locks created before arming
    stay plain and their disciplines go unverified (same contract as
    ``locks.set_debug``)."""
    global _DEBUG
    _DEBUG = on
    if on:
        _locks.set_debug(True)
        for classes in _DECORATED.values():
            for cls in classes:
                _instrument(cls)
    else:
        for classes in _DECORATED.values():
            for cls in classes:
                _restore(cls)


if _DEBUG:
    # XLLM_STATE_DEBUG=1 implies instrumented locks: the per-thread
    # acquisition stacks are what the lock-held cross-check reads.
    _locks.set_debug(True)


# ------------------------------------------------------------ inventory CLI
def _inventory(roots: list[str]) -> int:
    """The seeding pass: list self-attribute assignments outside
    __init__/lifecycle scope in the registered (or --all) classes, with
    their current registry status. This is how STATE_DISCIPLINES was
    seeded; re-run it after adding threads or attributes."""
    import ast
    from pathlib import Path

    decl = {"__init__", "setup", "__post_init__", *LIFECYCLE_METHODS}
    rows: list[tuple[str, str, str, str]] = []
    for root in roots:
        for p in sorted(Path(root).rglob("*.py")):
            if "xlint_fixtures" in p.parts:
                continue
            try:
                tree = ast.parse(p.read_text())
            except (OSError, SyntaxError):
                continue
            for node in tree.body:
                if not isinstance(node, ast.ClassDef):
                    continue
                for fn in node.body:
                    if not isinstance(fn, (ast.FunctionDef,
                                           ast.AsyncFunctionDef)) \
                            or fn.name in decl:
                        continue
                    for sub in ast.walk(fn):
                        tgts: list[ast.AST] = []
                        if isinstance(sub, ast.Assign):
                            tgts = sub.targets
                        elif isinstance(sub, (ast.AnnAssign, ast.AugAssign)):
                            tgts = [sub.target]
                        for t in tgts:
                            if isinstance(t, ast.Attribute) \
                                    and isinstance(t.value, ast.Name) \
                                    and t.value.id == "self":
                                key = f"{node.name}.{t.attr}"
                                status = STATE_DISCIPLINES.get(
                                    key, "<unregistered>")
                                rows.append((key, status, fn.name,
                                             f"{p}:{sub.lineno}"))
    seen = set()
    for key, status, meth, where in rows:
        if (key, meth) in seen:
            continue
        seen.add((key, meth))
        print(f"{key:45s} {status:28s} {meth}() {where}")
    return 0


def main(argv: Optional[list[str]] = None) -> int:
    import sys

    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] == "--inventory":
        roots = argv[1:] or [os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))]
        return _inventory(roots)
    print("usage: python -m xllm_service_tpu.devtools.ownership "
          "--inventory [roots...]")
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
