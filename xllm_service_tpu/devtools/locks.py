"""Lock factory with an opt-in race/deadlock detector.

``make_lock(name, order=N)`` is the project-wide replacement for bare
``threading.Lock()`` / ``threading.RLock()`` in the orchestration plane
(scheduler, instance manager, coordination, rpc, engine-side managers).

Normal mode (``XLLM_LOCK_DEBUG`` unset): returns a plain
``threading.Lock``/``RLock`` — zero overhead, byte-identical behavior.

Debug mode (``XLLM_LOCK_DEBUG=1``): returns an :class:`InstrumentedLock`
that

- records a per-thread stack of currently-held instrumented locks with the
  acquisition call stack and timestamp;
- flags **lock-order inversions**: acquiring a lock whose declared order is
  <= the order of any lock the thread already holds (the declared order is
  the ``order=N`` passed here, mirrored by the ``# lock-order: N`` source
  annotation xlint checks statically);
- flags **holds across fault-injection yield points**: every
  ``FAULTS.check``/``FAULTS.fire`` call site marks a spot where the code
  performs (or models) blocking I/O; if a thread crosses one while holding
  an instrumented lock for longer than ``XLLM_LOCK_HOLD_THRESHOLD_S``
  (default 0 — any hold counts), a violation is recorded. Wired into the
  fault plane via :func:`xllm_service_tpu.common.faults.set_yield_hook`,
  so the chaos drills double as a blocking-under-lock detector.

Violations are recorded (never raised) so production code paths behave
identically; ``tests/conftest.py`` fails any test that produced one when
debug mode is on.
"""

from __future__ import annotations

import os
import threading
import time
import traceback
from dataclasses import dataclass, field
from typing import Union

from ..utils import get_logger

logger = get_logger(__name__)

_DEBUG = os.environ.get("XLLM_LOCK_DEBUG", "") not in ("", "0")
_HOLD_THRESHOLD_S = float(os.environ.get("XLLM_LOCK_HOLD_THRESHOLD_S", "0"))


def debug_enabled() -> bool:
    return _DEBUG


def set_debug(on: bool) -> None:
    """Test hook: toggles instrumentation for locks created AFTER the call
    (existing locks keep whatever mode they were built with)."""
    global _DEBUG
    _DEBUG = on
    if on:
        _install_yield_hook()


@dataclass
class LockViolation:
    kind: str            # "lock-order" | "held-across-yield"
    message: str
    thread: str
    stack: list[str] = field(default_factory=list)

    def __str__(self) -> str:
        return f"[{self.kind}] {self.message} (thread {self.thread})"


# Detector bookkeeping; never held across project locks.
_vlock = threading.Lock()   # lock-order: 900
_violations: list[LockViolation] = []


def violations() -> list[LockViolation]:
    with _vlock:
        return list(_violations)


def reset_violations() -> None:
    with _vlock:
        _violations.clear()


def _record(kind: str, message: str) -> None:
    v = LockViolation(kind=kind, message=message,
                      thread=threading.current_thread().name,
                      stack=traceback.format_stack(limit=16)[:-2])
    with _vlock:
        _violations.append(v)
    logger.error("lock violation: %s", v)


_tls = threading.local()


@dataclass
class _Held:
    lock: "InstrumentedLock"
    acquired_at: float
    stack: list[str]
    depth: int = 1   # re-entrant re-acquisitions bump this, not the list


def _held_list() -> list[_Held]:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


def held_locks() -> list[str]:
    """Names of instrumented locks the calling thread currently holds
    (outermost first) — diagnostic helper."""
    return [h.lock.name for h in _held_list()]


def thread_holds(lock: "InstrumentedLock") -> bool:
    """Does the calling thread currently hold this instrumented lock?
    (Identity check against the per-thread acquisition stack — the
    ownership state verifier's lock-held cross-check reads this.)"""
    return any(h.lock is lock for h in _held_list())


class InstrumentedLock:
    """Context-manager lock recording acquisition order + stacks."""

    def __init__(self, name: str, order: int, reentrant: bool = False):
        self.name = name
        self.order = order
        self.reentrant = reentrant
        self._inner: Union[threading.Lock, threading.RLock] = (
            threading.RLock() if reentrant else threading.Lock())  # lock-order: 901

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)  # xlint: allow-bare-acquire(instrumentation wrapper)
        if ok:
            held = _held_list()
            mine = next((h for h in held if h.lock is self), None)
            if mine is not None:
                # Re-entrant re-acquisition: one entry, counted depth (a
                # second entry would double-report in note_yield_point).
                mine.depth += 1
            else:
                for h in held:
                    if h.lock.order >= self.order:
                        _record(
                            "lock-order",
                            f"acquired {self.name} (order {self.order}) "
                            f"while holding {h.lock.name} "
                            f"(order {h.lock.order})")
                        break
                held.append(_Held(self, time.monotonic(),
                                  traceback.format_stack(limit=12)[:-1]))
        return ok

    def release(self) -> None:
        held = _held_list()
        for i in range(len(held) - 1, -1, -1):
            if held[i].lock is self:
                if held[i].depth > 1:
                    held[i].depth -= 1
                else:
                    del held[i]
                break
        self._inner.release()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()


def note_yield_point(point: str) -> None:
    """Called from the fault plane at every ``FAULTS.check``/``fire`` —
    i.e. at every modeled blocking-I/O site. Flags instrumented locks the
    calling thread has held longer than the threshold."""
    for h in _held_list():
        held_for = time.monotonic() - h.acquired_at
        if held_for >= _HOLD_THRESHOLD_S:
            _record(
                "held-across-yield",
                f"lock {h.lock.name} (order {h.lock.order}) held "
                f"{held_for * 1000:.1f}ms across fault point {point!r} "
                f"(blocking call under lock?)")


_hook_installed = False


def _install_yield_hook() -> None:
    global _hook_installed
    if _hook_installed:
        return
    from ..common import faults

    faults.set_yield_hook(note_yield_point)
    _hook_installed = True


def make_lock(name: str, *, order: int, reentrant: bool = False):
    """Project lock factory. ``order`` is the global acquisition rank
    (lower = acquired first / outermost); it must match the
    ``# lock-order: N`` annotation on the declaration line, which xlint
    cross-checks and uses for the static acquisition-graph rule."""
    if not _DEBUG:
        return threading.RLock() if reentrant else threading.Lock()
    _install_yield_hook()
    return InstrumentedLock(name, order, reentrant)


if _DEBUG:
    _install_yield_hook()
