"""Developer tooling: concurrency-invariant linting + instrumented locks
+ RCU publication discipline + state ownership + paired-effect
lifecycles.

One contract, repeated: a declared registry in the source, a static
xlint pass that cross-checks it bidirectionally, and an opt-in runtime
verifier that checks the dynamic paths static analysis cannot see:

- :mod:`.xlint` — an AST static-analysis pass enforcing the orchestration
  plane's concurrency and fault-plane invariants (lock discipline, lock
  ordering — threaded and ``async with`` alike, no blocking I/O under
  locks or inside coroutines, fault-point and metric registry hygiene,
  broad-except hygiene, and the RCU publication rules). Run with
  ``python -m xllm_service_tpu.devtools.xlint xllm_service_tpu`` (or the
  ``xlint`` console script; ``--support tests benchmarks`` for the
  relaxed support-code profile).
- :mod:`.locks` — a ``make_lock()`` factory the orchestration modules use
  instead of bare ``threading.Lock()``. Zero-overhead passthrough normally;
  under ``XLLM_LOCK_DEBUG=1`` every lock is instrumented so the existing
  test suite doubles as a race/deadlock detector (per-thread acquisition
  stacks, lock-order inversion detection against the statically declared
  order, held-lock detection across fault-injection yield points).
- :mod:`.rcu` — the RCU publication registry (``RCU_FROZEN_TYPES``,
  ``RCU_PUBLICATIONS``; the static authority for xlint's rcu rules) plus
  the ``publish()``/``thaw()`` runtime: passthrough normally, deep-freeze
  under ``XLLM_RCU_DEBUG=1`` so the same suite doubles as a
  snapshot-race detector.
- :mod:`.ownership` — the shared-state ownership model closing the
  unregistered middle between locks and RCU: ``STATE_DISCIPLINES``
  declares a discipline per mutable attribute (the authority for
  xlint's state rules), and under ``XLLM_STATE_DEBUG=1`` an
  instrumented ``__setattr__`` cross-checks every write at runtime.
- :mod:`.lifecycle` — the paired-effect registry (``EFFECT_PAIRS``; the
  authority for xlint's pair-release / pair-once / pair-evict rules)
  plus the ``XLLM_LEAK_DEBUG=1`` balance verifier: per-pair counters
  with acquisition stacks catch slot leaks, double-releases and
  resurrected metric series the static rules cannot reach.

The declared lock order lives in the source as ``# lock-order: N``
annotations on each lock declaration; xlint verifies the static
acquisition graph against it and ``locks`` verifies the dynamic one. The
RCU registries play the same role for publication discipline: xlint
verifies mutation/swap/read sites statically, ``rcu`` verifies the
dynamic paths static analysis cannot see (aliasing, callbacks).
"""
