"""Developer tooling: concurrency-invariant linting + instrumented locks.

Two halves, one contract:

- :mod:`.xlint` — an AST static-analysis pass enforcing the orchestration
  plane's concurrency and fault-plane invariants (lock discipline, lock
  ordering, no blocking I/O under locks, fault-point and metric registry
  hygiene, broad-except hygiene). Run with
  ``python -m xllm_service_tpu.devtools.xlint xllm_service_tpu``.
- :mod:`.locks` — a ``make_lock()`` factory the orchestration modules use
  instead of bare ``threading.Lock()``. Zero-overhead passthrough normally;
  under ``XLLM_LOCK_DEBUG=1`` every lock is instrumented so the existing
  test suite doubles as a race/deadlock detector (per-thread acquisition
  stacks, lock-order inversion detection against the statically declared
  order, held-lock detection across fault-injection yield points).

The declared lock order the two halves share lives in the source as
``# lock-order: N`` annotations on each lock declaration; xlint verifies
the static acquisition graph against it and ``locks`` verifies the dynamic
one.
"""
