"""xlint — project-specific concurrency-invariant static analysis.

An AST pass over the whole tree enforcing the invariants the orchestration
plane otherwise maintains only by convention:

=========================  ==================================================
rule id                    invariant
=========================  ==================================================
``no-blocking-under-lock`` no ``time.sleep``, RPC/channel calls, ``requests``
                           / socket I/O, or coordination-client calls
                           lexically inside a ``with <lock>`` block
``lock-discipline``        locks acquired only via ``with`` (no bare
                           ``.acquire()``); every lock attribute declared at
                           ``__init__`` (or class/module scope) with a
                           ``# lock-order: N`` annotation
``lock-order``             the static lock-acquisition graph (nested ``with``
                           blocks + one level of project-resolvable calls)
                           respects the declared order and is acyclic
``fault-point``            every ``FAULTS.check("p")``/``FAULTS.fire("p")``
                           names a point registered in ``common/faults.py``'s
                           ``FAULT_POINTS``, and no registered point is dead
``span-point``             every ``TRACER.span("p")``/``TRACER.start_span``
                           names a point registered in ``common/tracing.py``'s
                           ``SPAN_POINTS``, and no registered point is dead
``metrics-registry``       metric instruments are created only in
                           ``common/metrics.py`` and none is dead; labeled
                           instruments are written only via ``.labels(...)``
                           with exactly the declared labelnames
``hot-json``               functions registered in ``rpc/wire.py``'s
                           ``HOT_PATH_FUNCTIONS`` contain no hand-rolled
                           ``json.dumps``/``json=`` encoding — dispatch
                           bytes come from ``rpc.wire``; stale registry
                           entries are violations too
``broad-except``           no bare ``except:`` anywhere; in scheduler/rpc/
                           coordination/engine paths every ``except
                           Exception`` handler logs or re-raises
``rcu-frozen``             types registered in ``devtools/rcu.py``'s
                           ``RCU_FROZEN_TYPES`` are never mutated after
                           construction — not in their own methods, not
                           through a local holding a published value
                           (``rcu.thaw(x, reason)`` is the declared-writer
                           hatch)
``rcu-publish``            writes to attributes registered in
                           ``RCU_PUBLICATIONS`` are a
                           single reference swap of a freshly built object
                           under the declared writer lock (one level of
                           call-site summaries, like the lock-order graph)
                           — never a field-by-field update
``rcu-read``               functions registered in ``HOT_PATH_FUNCTIONS``
                           load each publication attribute at most once
                           (a double load is a torn read: the two loads
                           may observe different snapshots)
``async-blocking``         no blocking primitives (``time.sleep``,
                           ``requests.*``/session HTTP, socket I/O,
                           coordination calls, channel RPC / ``_get`` /
                           ``_post``) lexically inside ``async def`` —
                           they stall the whole event loop
``state-decl``             every attribute assigned outside ``__init__`` in
                           a class registered in ``devtools/ownership.py``'s
                           ``STATE_CLASSES`` carries a declared discipline in
                           ``STATE_DISCIPLINES``; stale registry entries
                           (missing class / never-assigned attribute /
                           unknown lock / unknown or dead thread role /
                           ``rcu`` without an ``RCU_PUBLICATIONS`` entry)
                           are violations too
``state-write``            ``lock:<attr>``-disciplined attributes are
                           written only while the declared lock is held
                           (lexically, or via transitive ``*_locked``
                           call-site summaries); ``confined:<role>``
                           attributes rebound only from the role's entry
                           functions; ``init-only``/``immutable`` never
                           rebound after construction (``immutable`` never
                           mutated in place either)
``state-read``             functions registered in ``HOT_PATH_FUNCTIONS``
                           do not read lock-guarded mutable attributes
                           without the lock — go through an RCU snapshot
                           or take it
``pair-release``           every acquire site of a ``finally``-scope pair
                           registered in ``devtools/lifecycle.py``'s
                           ``EFFECT_PAIRS`` is discharged by a try/finally
                           that reaches the declared release (in the
                           acquiring function or every resolvable caller)
                           or by the declared ownership transfer; stale /
                           malformed / dead registry entries are
                           violations too
``pair-once``              no path releases a ``finally``-scope pair twice:
                           two unconditional releases in one function, or
                           an unconditional release lexically after the
                           declared ownership transfer, are flagged —
                           guard the release with the slot-ownership flag
``pair-evict``             labeled metric series are evicted only through
                           the blessed helper the ``evict``-scope pair
                           declares (no direct ``INSTRUMENT.remove(...)``
                           outside metrics.py), and no function writes to
                           an instrument after evicting its series (the
                           gauge-resurrection shape)
=========================  ==================================================

``async with`` acquisitions of declared asyncio locks participate in the
lock-discipline and lock-order rules exactly like threaded ``with``.

Escape hatches are inline comments with a mandatory reason::

    # xlint: allow-broad-except(error is surfaced as a client status)
    # xlint: allow-blocking-under-lock(single-writer frame serialization)
    # xlint: allow-lock-order(reason)
    # xlint: allow-bare-acquire(reason)
    # xlint: allow-lock-annotation(reason)
    # xlint: allow-span-point(reason)
    # xlint: allow-hot-json(reason)
    # xlint: allow-rcu-frozen(reason)
    # xlint: allow-rcu-publish(reason)
    # xlint: allow-rcu-read(reason)
    # xlint: allow-async-blocking(reason)
    # xlint: allow-state-decl(reason)
    # xlint: allow-state-write(reason)
    # xlint: allow-state-read(reason)
    # xlint: allow-pair-release(reason)
    # xlint: allow-pair-once(reason)
    # xlint: allow-pair-evict(reason)

The state rules also accept the runtime hatch — writes lexically inside
``with ownership.escape("reason"):`` are exempt (and an empty reason is
itself a violation, mirroring ``rcu.thaw`` and ``lifecycle.escape``).

Run: ``python -m xllm_service_tpu.devtools.xlint xllm_service_tpu``
(exit 0 = clean, 1 = violations, 2 = usage error). ``--format json``
emits one machine-readable object (``{"profile", "roots", "files",
"count", "violations": [{"rule", "path", "line", "message"}, ...],
"hatches": [{"path", "line", "kind", "reason"}, ...]}`` — the hatches
list surfaces every escape-hatch reason in the tree, comment hatches and
``ownership.escape``/``rcu.thaw``/``lifecycle.escape`` runtime hatches
alike, so reviews can audit them) with the same exit codes —
``scripts/check.sh`` consumes it. The whole tree is parsed ONCE per run:
every rule shares the same per-file AST and cached node walks
(``SourceFile.walk`` / ``Project.fn_walk``).

``--changed <git-ref>`` lints the full tree but REPORTS only violations
in files changed vs the ref (``git diff --name-only <ref>``), plus any
violation in a registry file — full-tree semantics are preserved (the
registries are cross-checked against every call site, so killing the
last call site of a fault point from an unchanged registry still
reports), while the output stays scoped to your diff.

Support code (tests/, benchmarks/) is linted with the RELAXED profile —
``python -m xllm_service_tpu.devtools.xlint --support tests benchmarks``
— which drops the declaration-discipline rule (support code does not
register locks/points) but keeps the behavioral rules: blocking under a
lock in a bench driver corrupts the measurement it wraps just as surely
as it stalls a scheduler. Files under a ``xlint_fixtures`` directory are
skipped unless they are the scan root (they are deliberate
anti-patterns).
"""

from __future__ import annotations

import ast
import re
import sys
from dataclasses import dataclass, field
from pathlib import Path

_SUPPRESS_RE = re.compile(r"#\s*xlint:\s*allow-([a-z-]+)\(([^)]*)\)")

#: Rule tokens accepted in suppression comments.
SUPPRESSIBLE = {
    "broad-except", "blocking-under-lock", "lock-order", "bare-acquire",
    "lock-annotation", "local-lock", "span-point", "hot-json",
    "rcu-frozen", "rcu-publish", "rcu-read", "async-blocking",
    "state-decl", "state-write", "state-read",
    "pair-release", "pair-once", "pair-evict",
}


@dataclass(frozen=True)
class Violation:
    rule: str
    path: str
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclass
class SourceFile:
    path: Path
    rel: str                      # path relative to the scan root's parent
    tree: ast.Module
    lines: list[str]
    # line number -> set of rule tokens allowed on that line.
    suppressions: dict[int, set[str]] = field(default_factory=dict)
    # Registered comment hatches with their reasons, in line order:
    # (line, token, reason). The JSON output surfaces these (plus the
    # runtime escape/thaw hatches) so hatch reasons stay auditable.
    hatches: "list[tuple[int, str, str]]" = field(default_factory=list)
    # Cached flat node list: the tree is parsed once per run and every
    # rule shares the same walk instead of re-walking per rule (the
    # single-parse/single-walk contract the CLI advertises).
    _nodes: "list[ast.AST] | None" = field(default=None, repr=False)

    def walk(self) -> "list[ast.AST]":
        if self._nodes is None:
            self._nodes = list(ast.walk(self.tree))
        return self._nodes

    def allowed(self, token: str, *linenos: int) -> bool:
        # A hatch comment may trail the offending line or sit on its own
        # line directly above it.
        return any(token in self.suppressions.get(ln, ())
                   or token in self.suppressions.get(ln - 1, ())
                   for ln in linenos)

    def line_comment_order(self, lineno: int) -> int | None:
        """Parse a trailing ``# lock-order: N`` annotation."""
        if 1 <= lineno <= len(self.lines):
            m = re.search(r"#\s*lock-order:\s*(-?\d+)", self.lines[lineno - 1])
            if m:
                return int(m.group(1))
        return None


def _parse_suppressions(lines: list[str]) -> tuple[
        dict[int, set[str]], "list[tuple[int, str, str]]"]:
    """Comment hatches → (line→tokens map, [(line, token, reason)]).
    The reason is mandatory: an empty one does not register the
    suppression (so the violation it meant to silence still fires)."""
    out: dict[int, set[str]] = {}
    hatches: list[tuple[int, str, str]] = []
    for i, line in enumerate(lines, 1):
        for m in _SUPPRESS_RE.finditer(line):
            token, reason = m.group(1), m.group(2).strip()
            if token in SUPPRESSIBLE and reason:
                out.setdefault(i, set()).add(token)
                hatches.append((i, token, reason))
    return out, hatches


# Runtime escape hatches whose reason argument position we know:
# ownership.escape(reason) / lifecycle.escape(reason) take it first,
# rcu.thaw(obj, reason) second.
_RUNTIME_HATCHES = {"escape": 0, "thaw": 1}


def _runtime_hatches(f: "SourceFile") -> "list[tuple[int, str, str]]":
    """``escape(...)``/``thaw(...)`` calls with their literal reasons —
    the runtime half of the hatch audit. Non-literal reasons surface as
    ``"<dynamic>"`` (still auditable, just not statically)."""
    out: list[tuple[int, str, str]] = []
    for node in f.walk():
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = fn.id if isinstance(fn, ast.Name) else \
            fn.attr if isinstance(fn, ast.Attribute) else None
        if name not in _RUNTIME_HATCHES:
            continue
        idx = _RUNTIME_HATCHES[name]
        if len(node.args) > idx:
            a = node.args[idx]
            reason = a.value if isinstance(a, ast.Constant) \
                and isinstance(a.value, str) else "<dynamic>"
        else:
            reason = ""   # missing reason: the state/rcu rules flag it
        out.append((node.lineno, name, reason))
    return out


def load_files(roots: list[str]) -> tuple[list[SourceFile], list[Violation]]:
    """Parse every .py under the given roots. Unparseable files are
    reported as violations (a linter that skips broken files lies)."""
    files: list[SourceFile] = []
    errors: list[Violation] = []
    seen: set[Path] = set()
    for root in roots:
        rp = Path(root)
        paths = sorted(rp.rglob("*.py")) if rp.is_dir() else [rp]
        base = rp.parent
        root_in_fixtures = "xlint_fixtures" in rp.resolve().parts
        for p in paths:
            p = p.resolve()
            if p in seen:
                continue
            if "xlint_fixtures" in p.parts and not root_in_fixtures:
                # Deliberate anti-pattern fixtures: linted only when the
                # fixture tree itself is the scan root (the rule tests).
                continue
            seen.add(p)
            try:
                rel = str(p.relative_to(base.resolve()))
            except ValueError:
                rel = str(p)
            try:
                src = p.read_text()
                tree = ast.parse(src, filename=str(p))
            except (OSError, SyntaxError) as e:
                errors.append(Violation("parse", rel, getattr(e, "lineno", 0)
                                        or 0, f"cannot parse: {e}"))
                continue
            lines = src.splitlines()
            suppressions, hatches = _parse_suppressions(lines)
            files.append(SourceFile(path=p, rel=rel, tree=tree, lines=lines,
                                    suppressions=suppressions,
                                    hatches=hatches))
    return files, errors


def run(roots: list[str], profile: str = "strict",
        stats: "dict | None" = None) -> list[Violation]:
    """Lint ``roots``. ``profile="support"`` (tests/, benchmarks/) drops
    the declaration-discipline rule — support code does not register
    locks or points — but keeps every behavioral rule; the registry
    rules are inert on partial trees anyway (no registry file in the
    roots). The tree is parsed once; every rule shares the parse and
    the cached walks. ``stats`` (optional dict) receives run metadata
    (currently ``files``)."""
    from . import rules

    files, violations = load_files(roots)
    if stats is not None:
        stats["files"] = len(files)
        hatches = []
        for f in files:
            for line, token, reason in f.hatches:
                hatches.append({"path": f.rel, "line": line,
                                "kind": f"comment:{token}",
                                "reason": reason})
            for line, name, reason in _runtime_hatches(f):
                hatches.append({"path": f.rel, "line": line,
                                "kind": f"runtime:{name}",
                                "reason": reason})
        stats["hatches"] = sorted(hatches,
                                  key=lambda h: (h["path"], h["line"]))
    project = rules.Project(files)
    active = rules.ALL_RULES if profile == "strict" else rules.SUPPORT_RULES
    for rule_fn in active:
        violations.extend(rule_fn(project))
    return sorted(set(violations), key=lambda v: (v.path, v.line, v.rule))


#: Flags the CLI understands; anything else dash-prefixed is a usage
#: error (stable exit code 2, so callers can tell "violations" from
#: "you invoked me wrong").
_KNOWN_FLAGS = {"-q", "--support", "--format", "--changed"}

#: Registry files: violations here are NEVER filtered by --changed —
#: the registries are bidirectionally cross-checked against every call
#: site, so an unchanged registry can go stale because of your diff.
_REGISTRY_BASENAMES = {"faults.py", "tracing.py", "wire.py", "rcu.py",
                       "ownership.py", "lifecycle.py", "metrics.py"}


def _changed_files(ref: str) -> "set[str] | None":
    """Basenamed-relative paths changed vs `ref` (tracked diff +
    untracked), or None when git can't answer (bad ref / not a repo)."""
    import subprocess
    out: set[str] = set()
    for cmd in (["git", "diff", "--name-only", ref],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            res = subprocess.run(cmd, capture_output=True, text=True,
                                 check=True)
        except (OSError, subprocess.CalledProcessError):
            return None
        out.update(line.strip() for line in res.stdout.splitlines()
                   if line.strip().endswith(".py"))
    return out


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    quiet = "-q" in argv
    profile = "support" if "--support" in argv else "strict"
    fmt = "text"
    changed_ref: "str | None" = None
    roots: list[str] = []
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--format":
            if i + 1 >= len(argv) or argv[i + 1] not in ("text", "json"):
                print("xlint: --format takes 'text' or 'json'",
                      file=sys.stderr)
                return 2
            fmt = argv[i + 1]
            i += 2
            continue
        if a == "--changed":
            if i + 1 >= len(argv) or argv[i + 1].startswith("-"):
                print("xlint: --changed takes a git ref", file=sys.stderr)
                return 2
            changed_ref = argv[i + 1]
            i += 2
            continue
        if a.startswith("-") and a not in _KNOWN_FLAGS:
            print(f"xlint: unknown flag {a!r} (known: "
                  f"{' '.join(sorted(_KNOWN_FLAGS))})", file=sys.stderr)
            return 2
        if not a.startswith("-"):
            roots.append(a)
        i += 1
    if not roots:
        pkg = Path(__file__).resolve().parents[2]
        roots = [str(pkg)]
    stats: dict = {}
    violations = run(roots, profile=profile, stats=stats)
    if changed_ref is not None:
        # Full-tree analysis (registry cross-checks and call-graph
        # summaries need the whole tree), output scoped to the diff.
        changed = _changed_files(changed_ref)
        if changed is None:
            print(f"xlint: --changed {changed_ref!r}: git diff failed "
                  f"(bad ref or not a git checkout)", file=sys.stderr)
            return 2
        changed_norm = {c.replace("\\", "/") for c in changed}

        def keep(v: Violation) -> bool:
            p = v.path.replace("\\", "/")
            if Path(p).name in _REGISTRY_BASENAMES:
                return True
            return any(c == p or c.endswith("/" + p) for c in changed_norm)

        violations = [v for v in violations if keep(v)]
    if fmt == "json":
        import json as _json

        print(_json.dumps({
            "profile": profile,
            "roots": roots,
            "files": stats.get("files", 0),
            "changed": changed_ref,
            "count": len(violations),
            "violations": [{"rule": v.rule, "path": v.path,
                            "line": v.line, "message": v.message}
                           for v in violations],
            "hatches": stats.get("hatches", []),
        }, indent=None))
        return 1 if violations else 0
    for v in violations:
        print(v)
    if not violations and not quiet:
        scope = f", changed vs {changed_ref}" if changed_ref else ""
        print(f"xlint: clean ({len(roots)} root(s), {profile} "
              f"profile{scope})")
    return 1 if violations else 0
