"""The xlint rule implementations (each a small pass over the whole tree)."""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Optional

from . import SourceFile, Violation

# Scopes where a lock attribute may legitimately be declared. `setup` is
# the socketserver handler analog of __init__.
DECL_METHODS = {"__init__", "setup", "__post_init__"}

# threading factories that produce a lock-like object.
LOCK_FACTORIES = {"Lock", "RLock", "Condition"}

# asyncio factories: `async with self._alock` participates in the same
# declaration/annotation/ordering machinery as the threaded locks (the
# event loop serializes coroutines, but await points inside an async
# critical section interleave other coroutines — ordering still matters).
ASYNC_LOCK_FACTORIES = {"Lock", "Semaphore", "BoundedSemaphore", "Condition"}

# EngineChannel control-plane methods: calling any of these is an RPC.
CHANNEL_METHODS = {"forward", "forward_status", "health", "link", "unlink",
                   "flip_role", "models"}

# Method names too generic to resolve to one project class for the
# call-graph half of the lock-order rule (dict/queue/socket/file methods
# would alias them and fabricate edges).
GENERIC_NAMES = {
    "get", "put", "pop", "set", "add", "clear", "close", "release", "start",
    "stop", "join", "wait", "send", "read", "write", "open", "run", "update",
    "append", "remove", "submit", "flush", "encode", "decode", "render",
    "value", "count", "mean", "stats", "meta", "register", "push", "cancel",
    "step", "fire", "check", "items", "keys", "values", "acquire", "copy",
    "extend", "discard", "setdefault", "sleep", "log",
}

LOG_METHODS = {"exception", "warning", "error", "info", "debug", "critical",
               "log"}

#: Dirs (path segments) where the broad-except rule is enforced.
EXCEPT_SCOPED_DIRS = {"scheduler", "rpc", "coordination", "engine"}


# --------------------------------------------------------------- AST helpers
def _expr_text(node: ast.AST) -> str:
    """Dotted-path text for Name/Attribute/Call chains ('' if unprintable)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _expr_text(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    if isinstance(node, ast.Call):
        base = _expr_text(node.func)
        return f"{base}()" if base else ""
    return ""


def _lock_factory_kind(node: ast.AST) -> Optional[str]:
    """'threading' | 'make_lock' when `node` constructs a lock; else None.
    Handles `A if cond else B` declarations (either arm a lock call)."""
    if isinstance(node, ast.IfExp):
        return _lock_factory_kind(node.body) or _lock_factory_kind(node.orelse)
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in LOCK_FACTORIES \
            and _expr_text(f.value) == "threading":
        return "threading"
    if isinstance(f, ast.Attribute) and f.attr in ASYNC_LOCK_FACTORIES \
            and _expr_text(f.value) == "asyncio":
        return "asyncio"
    if isinstance(f, ast.Name) and f.id in LOCK_FACTORIES:
        return "threading"
    if (isinstance(f, ast.Name) and f.id == "make_lock") or \
            (isinstance(f, ast.Attribute) and f.attr == "make_lock"):
        return "make_lock"
    return None


def _make_lock_order_kwarg(node: ast.AST) -> Optional[int]:
    if isinstance(node, ast.IfExp):
        return _make_lock_order_kwarg(node.body) \
            if _lock_factory_kind(node.body) == "make_lock" \
            else _make_lock_order_kwarg(node.orelse)
    if isinstance(node, ast.Call):
        for kw in node.keywords:
            if kw.arg == "order" and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, int):
                return kw.value.value
    return None


def _first_str_arg(call: ast.Call) -> Optional[str]:
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        return call.args[0].value
    return None


def _registry_dict(f: SourceFile, name: str) -> dict[str, int]:
    """Top-level ``NAME = {...}`` / ``NAME: dict[...] = {...}`` string-key
    registry → {key: lineno}. Handling AnnAssign matters: the real
    registries are type-annotated, and an Assign-only parse silently turns
    the whole rule into a no-op (which is exactly what happened to the
    fault/span rules between their landing and this helper)."""
    out: dict[str, int] = {}
    for node in f.tree.body:
        if isinstance(node, ast.Assign):
            targets = node.targets
            value = node.value
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
            value = node.value
        else:
            continue
        if not isinstance(value, ast.Dict) or not any(
                isinstance(t, ast.Name) and t.id == name for t in targets):
            continue
        for k in value.keys:
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                out[k.value] = k.lineno
    return out


def _registry_items(f: SourceFile, name: str) -> "dict[str, tuple[Optional[str], int]]":
    """Like :func:`_registry_dict` but also captures constant-string
    VALUES → {key: (value-or-None, lineno)} — for registries whose value
    carries machine-readable structure (the RCU publication specs)."""
    out: dict[str, tuple[Optional[str], int]] = {}
    for node in f.tree.body:
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign):
            targets, value = [node.target], node.value
        else:
            continue
        if not isinstance(value, ast.Dict) or not any(
                isinstance(t, ast.Name) and t.id == name for t in targets):
            continue
        for k, v in zip(value.keys, value.values):
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                val = v.value if isinstance(v, ast.Constant) \
                    and isinstance(v.value, str) else None
                out[k.value] = (val, k.lineno)
    return out


# ------------------------------------------------------------- project model
@dataclass
class LockDecl:
    key: tuple[str, str]          # (class name or "<module>", attr name)
    file: SourceFile
    line: int
    kind: str                     # "threading" | "make_lock"
    order: Optional[int]          # from the # lock-order comment
    kwarg_order: Optional[int]    # from make_lock(order=N)
    in_decl_scope: bool


class Project:
    """Cross-file indices shared by the rules (one parse, one set of
    walks — every rule reads these instead of re-walking the tree)."""

    def __init__(self, files: list[SourceFile]):
        self.files = files
        # (class, method) -> (FunctionDef, SourceFile, ClassDef)
        self.methods: dict[tuple[str, str], tuple[ast.AST, SourceFile]] = {}
        # method name -> set of class names defining it
        self.method_classes: dict[str, set[str]] = {}
        self.lock_decls: dict[tuple[str, str], LockDecl] = {}
        self.stray_lock_decls: list[LockDecl] = []   # declared outside scope
        # Same (class, attr) declared twice with a different order — the
        # graph would silently use the wrong order, so it is a violation.
        self.conflicting_lock_decls: list[tuple[LockDecl, LockDecl]] = []
        # Locks bound to function locals: invisible to the annotation /
        # ordering machinery, so their creation is itself a violation.
        self.local_lock_decls: list[LockDecl] = []
        # Cached per-function node walks (keyed by node identity; the
        # nodes stay alive for the project's lifetime via `files`).
        self._fn_nodes: dict[int, list[ast.AST]] = {}
        # Lazy shared call-site index, see call_contexts().
        self._call_contexts: "dict[tuple, list] | None" = None
        for f in files:
            self._index_file(f)
            self._index_local_locks(f)

    def fn_walk(self, fn: ast.AST) -> list[ast.AST]:
        """Cached ``ast.walk`` over one function — several rules walk the
        same methods; they share one traversal."""
        nodes = self._fn_nodes.get(id(fn))
        if nodes is None:
            nodes = self._fn_nodes[id(fn)] = list(ast.walk(fn))
        return nodes

    def call_contexts(self) -> "dict[tuple, list]":
        """Shared call-site index: resolvable call target ``(cls, meth)``
        -> list of ``(caller_key, frozenset(held lock attrs),
        caller_in_decl_scope)`` — the lock-context summaries the RCU and
        state-write rules both consume (computed once)."""
        if self._call_contexts is not None:
            return self._call_contexts
        ctx: dict[tuple, list] = {}

        def visit(node, f: SourceFile, cls_name, caller, in_decl,
                  outer_fn, stack: list[str]) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not outer_fn:
                # Nested defs run later: fresh lexical lock context, but
                # call sites still belong to the enclosing method.
                for child in ast.iter_child_nodes(node):
                    visit(child, f, cls_name, caller, in_decl, outer_fn, [])
                return
            entered = 0
            if isinstance(node, (ast.With, ast.AsyncWith)) \
                    and cls_name is not None:
                for key in _with_decl_locks(node, cls_name, self):
                    stack.append(key[1])
                    entered += 1
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute):
                recv = _expr_text(node.func.value)
                target = self.resolve_call(cls_name, recv == "self",
                                           node.func.attr)
                if target is not None:
                    ctx.setdefault(target, []).append(
                        (caller, frozenset(stack), in_decl))
            for child in ast.iter_child_nodes(node):
                visit(child, f, cls_name, caller, in_decl, outer_fn, stack)
            for _ in range(entered):
                stack.pop()

        for f in self.files:
            for cls_name, fn in _iter_functions(f):
                visit(fn, f, cls_name, (cls_name, fn.name),
                      fn.name in DECL_METHODS, fn, [])
        self._call_contexts = ctx
        return ctx

    def _index_file(self, f: SourceFile) -> None:
        for node in f.tree.body:
            if isinstance(node, ast.ClassDef):
                self._index_class(f, node)
            elif isinstance(node, ast.Assign):
                self._maybe_lock_decl(f, "<module>", node, targets_self=False,
                                      in_scope=True)

    def _index_class(self, f: SourceFile, cls: ast.ClassDef) -> None:
        for node in cls.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[(cls.name, node.name)] = (node, f)
                self.method_classes.setdefault(node.name, set()).add(cls.name)
                in_scope = node.name in DECL_METHODS
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Assign):
                        self._maybe_lock_decl(f, cls.name, sub,
                                              targets_self=True,
                                              in_scope=in_scope)
            elif isinstance(node, ast.Assign):
                self._maybe_lock_decl(f, cls.name, node, targets_self=False,
                                      in_scope=True)

    def _maybe_lock_decl(self, f: SourceFile, owner: str, node: ast.Assign,
                         targets_self: bool, in_scope: bool) -> None:
        kind = _lock_factory_kind(node.value)
        if kind is None:
            return
        for tgt in node.targets:
            if targets_self:
                if not (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id in ("self", "cls")):
                    continue
                attr = tgt.attr
            else:
                if not isinstance(tgt, ast.Name):
                    continue
                attr = tgt.id
            decl = LockDecl(
                key=(owner, attr), file=f, line=node.lineno, kind=kind,
                order=f.line_comment_order(node.lineno),
                kwarg_order=_make_lock_order_kwarg(node.value),
                in_decl_scope=in_scope)
            if in_scope:
                prior = self.lock_decls.get(decl.key)
                if prior is None:
                    self.lock_decls[decl.key] = decl
                elif prior.line != decl.line and prior.order != decl.order:
                    self.conflicting_lock_decls.append((prior, decl))
            else:
                self.stray_lock_decls.append(decl)

    def _index_local_locks(self, f: SourceFile) -> None:
        """Lock factories assigned to plain names inside any function:
        a short-lived local lock protects nothing across threads unless it
        escapes, and escapes untracked — both are bugs."""
        for node in f.walk():
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign) \
                        and _lock_factory_kind(sub.value) is not None:
                    for tgt in sub.targets:
                        if isinstance(tgt, ast.Name):
                            self.local_lock_decls.append(LockDecl(
                                key=(node.name, tgt.id), file=f,
                                line=sub.lineno,
                                kind=_lock_factory_kind(sub.value),
                                order=f.line_comment_order(sub.lineno),
                                kwarg_order=None, in_decl_scope=False))

    # Resolution for the call-graph half of the lock-order rule.
    def resolve_call(self, cls_name: Optional[str],
                     is_self_call: bool, name: str
                     ) -> Optional[tuple[str, str]]:
        if is_self_call and cls_name and (cls_name, name) in self.methods:
            return (cls_name, name)
        if name in GENERIC_NAMES:
            return None
        owners = self.method_classes.get(name, set())
        if len(owners) == 1:
            return (next(iter(owners)), name)
        return None

    def class_lock_attrs(self, cls_name: str) -> set[str]:
        return {attr for (owner, attr) in self.lock_decls if owner == cls_name}


# ---------------------------------------------------------- lock discipline
def rule_lock_discipline(project: Project) -> list[Violation]:
    out: list[Violation] = []
    for decl in list(project.lock_decls.values()):
        f = decl.file
        if decl.order is None:
            if not f.allowed("lock-annotation", decl.line):
                out.append(Violation(
                    "lock-discipline", f.rel, decl.line,
                    f"lock {decl.key[0]}.{decl.key[1]} has no "
                    f"'# lock-order: N' annotation"))
        elif decl.kwarg_order is not None and decl.kwarg_order != decl.order:
            out.append(Violation(
                "lock-discipline", f.rel, decl.line,
                f"lock {decl.key[0]}.{decl.key[1]}: make_lock(order="
                f"{decl.kwarg_order}) disagrees with '# lock-order: "
                f"{decl.order}' annotation"))
    for decl in project.stray_lock_decls:
        out.append(Violation(
            "lock-discipline", decl.file.rel, decl.line,
            f"lock {decl.key[0]}.{decl.key[1]} created outside "
            f"__init__/setup/class scope (locks must be declared once, "
            f"at construction)"))
    for decl in project.local_lock_decls:
        if not decl.file.allowed("local-lock", decl.line):
            out.append(Violation(
                "lock-discipline", decl.file.rel, decl.line,
                f"lock bound to local {decl.key[1]!r} in {decl.key[0]}() — "
                f"locks must be long-lived attributes declared at "
                f"construction (annotation + ordering cannot track a "
                f"function local)"))
    for prior, dup in project.conflicting_lock_decls:
        out.append(Violation(
            "lock-discipline", dup.file.rel, dup.line,
            f"lock {dup.key[0]}.{dup.key[1]} re-declared with order "
            f"{dup.order} but {prior.file.rel}:{prior.line} declares "
            f"order {prior.order} (the graph check uses the first)"))

    # Bare .acquire()/.release() — locks are `with`-only.
    for f in project.files:
        for cls_name, fn in _iter_functions(f):
            lock_attrs = project.class_lock_attrs(cls_name) if cls_name else set()
            for node in project.fn_walk(fn):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)):
                    continue
                recv = _expr_text(node.func.value)
                recv_is_lock = (
                    "lock" in recv.lower()
                    or (recv.startswith("self.")
                        and recv[len("self."):] in lock_attrs))
                if not recv_is_lock:
                    continue
                if node.func.attr == "acquire" or (
                        node.func.attr == "release" and not node.args):
                    if not f.allowed("bare-acquire", node.lineno):
                        out.append(Violation(
                            "lock-discipline", f.rel, node.lineno,
                            f"bare {recv}.{node.func.attr}() — acquire "
                            f"locks via 'with' only"))
    return out


def _iter_functions(f: SourceFile):
    """Yields (enclosing class name or None, function node) for every
    top-level function and every method."""
    for node in f.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield None, node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield node.name, sub


# ------------------------------------------------------ blocking under lock
def _with_lock_items(with_node: ast.With, cls_name: Optional[str],
                     lock_attrs: set[str]) -> list[str]:
    """Names of lock-like context managers entered by this With."""
    hits = []
    for item in with_node.items:
        text = _expr_text(item.context_expr)
        last = text.rsplit(".", 1)[-1]
        if "lock" in last.lower():
            hits.append(text)
        elif text.startswith(("self.", "cls.")) \
                and text.split(".", 1)[1] in lock_attrs:
            hits.append(text)
    return hits


def _is_blocking_call(node: ast.Call) -> Optional[str]:
    if not isinstance(node.func, ast.Attribute):
        return None
    attr = node.func.attr
    recv = _expr_text(node.func.value)
    rlow = recv.lower()
    if attr == "sleep":
        return f"{recv}.sleep() sleeps"
    if recv in ("requests", "_requests") and attr in (
            "get", "post", "put", "delete", "head", "request"):
        return f"requests.{attr}() performs HTTP I/O"
    if "session" in rlow and attr in ("get", "post", "put", "delete",
                                      "request", "head"):
        return f"{recv}.{attr}() performs HTTP I/O"
    if attr in ("sendall", "recv", "recv_into"):
        return f"{recv}.{attr}() performs socket I/O"
    if attr == "create_connection" and rlow.endswith("socket"):
        return "socket.create_connection() performs socket I/O"
    if recv == "FAULTS" and attr in ("check", "fire"):
        return f"FAULTS.{attr}() marks a blocking-I/O fault point"
    if rlow.endswith("coord") or "._coord" in rlow or rlow == "coord":
        return f"coordination call {recv}.{attr}()"
    if attr in CHANNEL_METHODS:
        return f"engine-channel RPC {recv}.{attr}()"
    if attr == "cancel" and (rlow in ("ch", "chan", "channel")
                             or rlow.endswith(".channel")):
        return f"engine-channel RPC {recv}.cancel()"
    return None


def rule_no_blocking_under_lock(project: Project) -> list[Violation]:
    out: list[Violation] = []

    def visit(node: ast.AST, f: SourceFile, cls_name: Optional[str],
              lock_attrs: set[str], stack: list[tuple[str, int]]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and stack:
            # A def nested inside a with-block runs later, not under the
            # lock — its body starts a fresh lexical context.
            for child in ast.iter_child_nodes(node):
                visit(child, f, cls_name, lock_attrs, [])
            return
        entered = 0
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for name in _with_lock_items(node, cls_name, lock_attrs):
                stack.append((name, node.lineno))
                entered += 1
        elif isinstance(node, ast.Call) and stack:
            why = _is_blocking_call(node)
            if why is not None:
                with_lines = [ln for _, ln in stack]
                if not f.allowed("blocking-under-lock", node.lineno,
                                 *with_lines):
                    out.append(Violation(
                        "no-blocking-under-lock", f.rel, node.lineno,
                        f"{why} while holding {stack[-1][0]} "
                        f"(acquired line {stack[-1][1]})"))
        for child in ast.iter_child_nodes(node):
            visit(child, f, cls_name, lock_attrs, stack)
        for _ in range(entered):
            stack.pop()

    for f in project.files:
        for cls_name, fn in _iter_functions(f):
            attrs = project.class_lock_attrs(cls_name) if cls_name else set()
            visit(fn, f, cls_name, attrs, [])
    return out


# ------------------------------------------------------------- lock ordering
def _with_decl_locks(with_node, cls_name: Optional[str],
                     project: Project) -> list[tuple[str, str]]:
    """Declared-lock keys entered by this With (only declared locks
    participate in the acquisition graph)."""
    keys = []
    if cls_name is None:
        return keys
    for item in with_node.items:
        text = _expr_text(item.context_expr)
        if text.startswith(("self.", "cls.")):
            attr = text.split(".", 1)[1]
            if (cls_name, attr) in project.lock_decls:
                keys.append((cls_name, attr))
    return keys


@dataclass
class _Edge:
    src: tuple[str, str]
    dst: tuple[str, str]
    file: SourceFile
    line: int
    via: str


def rule_lock_order(project: Project) -> list[Violation]:
    out: list[Violation] = []

    # Pass 1: per-method direct acquisitions + call refs (anywhere in the
    # method, nested defs included — conservative for summaries).
    direct: dict[tuple[str, str], set[tuple[str, str]]] = {}
    calls: dict[tuple[str, str], set[tuple[bool, str]]] = {}
    for (cls_name, meth), (fn, _f) in project.methods.items():
        acq: set[tuple[str, str]] = set()
        refs: set[tuple[bool, str]] = set()
        for node in project.fn_walk(fn):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                acq.update(_with_decl_locks(node, cls_name, project))
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute):
                recv = _expr_text(node.func.value)
                refs.add((recv == "self", node.func.attr))
        direct[(cls_name, meth)] = acq
        calls[(cls_name, meth)] = refs

    # Pass 2: transitive summaries (which locks can a method acquire).
    summary = {k: set(v) for k, v in direct.items()}
    changed = True
    while changed:
        changed = False
        for key, refs in calls.items():
            cur = summary[key]
            for is_self, name in refs:
                target = project.resolve_call(key[0], is_self, name)
                if target is not None and target in summary:
                    before = len(cur)
                    cur |= summary[target]
                    if len(cur) != before:
                        changed = True

    # Pass 3: edges from with-block bodies (direct nesting + one level of
    # resolvable calls through the summaries).
    edges: list[_Edge] = []

    def visit(node, f: SourceFile, cls_name: str,
              stack: list[tuple[tuple[str, str], int]]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and stack:
            for child in ast.iter_child_nodes(node):
                visit(child, f, cls_name, [])
            return
        entered = 0
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for key in _with_decl_locks(node, cls_name, project):
                if stack:
                    src = stack[-1][0]
                    if src != key:
                        edges.append(_Edge(src, key, f, node.lineno,
                                           via="nested with"))
                stack.append((key, node.lineno))
                entered += 1
        elif isinstance(node, ast.Call) and stack \
                and isinstance(node.func, ast.Attribute):
            target = project.resolve_call(
                cls_name, _expr_text(node.func.value) == "self",
                node.func.attr)
            if target is not None:
                src = stack[-1][0]
                for dst in summary.get(target, ()):
                    if dst != src:
                        edges.append(_Edge(
                            src, dst, f, node.lineno,
                            via=f"call to {target[0]}.{target[1]}()"))
        for child in ast.iter_child_nodes(node):
            visit(child, f, cls_name, stack)
        for _ in range(entered):
            stack.pop()

    for f in project.files:
        for cls_name, fn in _iter_functions(f):
            if cls_name is not None:
                visit(fn, f, cls_name, [])

    # Check declared order along every edge.
    seen: set[tuple] = set()
    adj: dict[tuple[str, str], set[tuple[str, str]]] = {}
    for e in edges:
        adj.setdefault(e.src, set()).add(e.dst)
        so = project.lock_decls[e.src].order
        do = project.lock_decls[e.dst].order
        if so is None or do is None:
            continue   # annotation violation already reported
        if so >= do:
            sig = (e.src, e.dst, e.file.rel, e.line)
            if sig in seen:
                continue
            seen.add(sig)
            if not e.file.allowed("lock-order", e.line):
                out.append(Violation(
                    "lock-order", e.file.rel, e.line,
                    f"{'.'.join(e.src)} (order {so}) -> "
                    f"{'.'.join(e.dst)} (order {do}) via {e.via} "
                    f"violates the declared lock order"))

    # Cycle detection over the acquisition graph.
    WHITE, GREY, BLACK = 0, 1, 2
    color = {k: WHITE for k in adj}

    def dfs(k, path):
        color[k] = GREY
        for nxt in adj.get(k, ()):
            if color.get(nxt, WHITE) == GREY:
                cyc = path[path.index(nxt):] + [nxt] if nxt in path else [k, nxt]
                names = " -> ".join(".".join(c) for c in cyc)
                decl = project.lock_decls[nxt]
                out.append(Violation(
                    "lock-order", decl.file.rel, decl.line,
                    f"lock-acquisition cycle: {names}"))
            elif color.get(nxt, WHITE) == WHITE and nxt in adj:
                dfs(nxt, path + [nxt])
        color[k] = BLACK

    for k in list(adj):
        if color[k] == WHITE:
            dfs(k, [k])
    return out


# -------------------------------------------------------------- fault points
def rule_fault_point(project: Project) -> list[Violation]:
    registry: dict[str, int] = {}
    reg_file: Optional[SourceFile] = None
    for f in project.files:
        if f.path.name != "faults.py":
            continue
        found = _registry_dict(f, "FAULT_POINTS")
        if found:
            registry, reg_file = found, f
    if reg_file is None:
        return []   # partial tree (e.g. fixture subset without a registry)

    out: list[Violation] = []
    used: set[str] = set()
    for f in project.files:
        for node in f.walk():
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("check", "fire")
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "FAULTS"):
                continue
            point = _first_str_arg(node)
            if point is None:
                out.append(Violation(
                    "fault-point", f.rel, node.lineno,
                    "fault point must be a string literal"))
            elif point not in registry:
                out.append(Violation(
                    "fault-point", f.rel, node.lineno,
                    f"fault point {point!r} is not registered in "
                    f"common/faults.py FAULT_POINTS"))
            else:
                used.add(point)
    for point, line in sorted(registry.items()):
        if point not in used:
            out.append(Violation(
                "fault-point", reg_file.rel, line,
                f"registered fault point {point!r} has no call site "
                f"(dead fault point)"))
    return out


# ------------------------------------------------------------------- metrics
#: Write methods that must go through .labels() on a labeled instrument.
_METRIC_WRITERS = {"inc", "set", "observe"}


def _decl_labelnames(call: ast.Call) -> Optional[tuple[str, ...]]:
    """The labelnames=(...) tuple of an instrument declaration (None when
    absent, () when explicitly empty)."""
    for kw in call.keywords:
        if kw.arg != "labelnames":
            continue
        if isinstance(kw.value, (ast.Tuple, ast.List)):
            names = []
            for e in kw.value.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    names.append(e.value)
            return tuple(names)
        return ()
    return None


def rule_metrics_registry(project: Project) -> list[Violation]:
    decl_file: Optional[SourceFile] = None
    # identifier -> (metric name, line, declared labelnames or None)
    instruments: dict[str, tuple[str, int, Optional[tuple[str, ...]]]] = {}
    top_names: set[str] = set()
    for f in project.files:
        if f.path.name != "metrics.py":
            continue
        names, found = set(), {}
        for node in f.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                names.add(node.name)
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
                if isinstance(node.value, ast.Call) \
                        and isinstance(node.value.func, ast.Attribute) \
                        and node.value.func.attr in ("counter", "gauge",
                                                     "histogram"):
                    mname = _first_str_arg(node.value)
                    tgt = node.targets[0]
                    if mname and isinstance(tgt, ast.Name):
                        found[tgt.id] = (mname, node.lineno,
                                         _decl_labelnames(node.value))
        if found:
            decl_file, instruments, top_names = f, found, names
    if decl_file is None:
        return []

    out: list[Violation] = []
    dupes: dict[str, str] = {}
    for ident, (mname, line, _labels) in instruments.items():
        if mname in dupes:
            out.append(Violation(
                "metrics-registry", decl_file.rel, line,
                f"metric name {mname!r} declared twice "
                f"({dupes[mname]} and {ident})"))
        dupes[mname] = ident

    def check_instrument_call(f: SourceFile, node: ast.Call) -> None:
        """Labeled-instrument hygiene at a call site whose receiver is a
        declared instrument identifier: .labels() must pass exactly the
        declared labelnames (keyword-only), and writes on a labeled family
        must go through .labels()."""
        recv = node.func.value
        # Bare (TTFT_MS.observe) and module-qualified (metrics.TTFT_MS
        # .observe) receivers — same access styles the liveness scan
        # accepts as a use.
        if isinstance(recv, ast.Name):
            ident = recv.id
        elif isinstance(recv, ast.Attribute):
            ident = recv.attr
        else:
            return
        if ident not in instruments:
            return
        _mname, _line, labelnames = instruments[ident]
        if node.func.attr == "labels":
            if not labelnames:
                out.append(Violation(
                    "metrics-registry", f.rel, node.lineno,
                    f"labels() on {ident}, which declares no labelnames"))
                return
            if node.args:
                out.append(Violation(
                    "metrics-registry", f.rel, node.lineno,
                    f"{ident}.labels() takes keyword arguments only"))
                return
            got = {kw.arg for kw in node.keywords if kw.arg}
            if any(kw.arg is None for kw in node.keywords):
                return   # **kwargs: not statically checkable
            if got != set(labelnames):
                out.append(Violation(
                    "metrics-registry", f.rel, node.lineno,
                    f"{ident}.labels() passes {tuple(sorted(got))} but "
                    f"the instrument declares labelnames {labelnames}"))
        elif node.func.attr in _METRIC_WRITERS and labelnames:
            out.append(Violation(
                "metrics-registry", f.rel, node.lineno,
                f"{ident}.{node.func.attr}() on a labeled instrument — "
                f"write through .labels(...).{node.func.attr}()"))

    used: set[str] = set()
    for f in project.files:
        if f is decl_file:
            continue
        for node in f.walk():
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("counter", "gauge", "histogram") \
                    and _expr_text(node.func.value).endswith("REGISTRY"):
                out.append(Violation(
                    "metrics-registry", f.rel, node.lineno,
                    f"ad-hoc metric creation (REGISTRY.{node.func.attr}) — "
                    f"declare instruments in common/metrics.py"))
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute):
                check_instrument_call(f, node)
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.module.rsplit(".", 1)[-1] == "metrics":
                for alias in node.names:
                    if alias.name not in top_names and alias.name != "*":
                        out.append(Violation(
                            "metrics-registry", f.rel, node.lineno,
                            f"import of {alias.name!r} not declared in "
                            f"common/metrics.py"))
                    # An import alone is NOT a use: only Name/Attribute
                    # references keep an instrument off the dead list.
            elif isinstance(node, ast.Name) and node.id in instruments:
                used.add(node.id)
            elif isinstance(node, ast.Attribute) and node.attr in instruments:
                used.add(node.attr)
    for ident, (mname, line, _labels) in sorted(instruments.items()):
        if ident not in used:
            out.append(Violation(
                "metrics-registry", decl_file.rel, line,
                f"instrument {ident} ({mname!r}) is never used "
                f"(dead metric)"))
    return out


# --------------------------------------------------------------- span points
def rule_span_point(project: Project) -> list[Violation]:
    """Bidirectional span-point registry check (mirrors the fault-point
    rule): every ``TRACER.span("p")``/``TRACER.start_span("p")`` call site
    must name a point registered in ``common/tracing.py``'s ``SPAN_POINTS``,
    and every registered point must have at least one live call site."""
    registry: dict[str, int] = {}
    reg_file: Optional[SourceFile] = None
    for f in project.files:
        if f.path.name != "tracing.py":
            continue
        found = _registry_dict(f, "SPAN_POINTS")
        if found:
            registry, reg_file = found, f
    if reg_file is None:
        return []   # partial tree (e.g. fixture subset without a registry)

    out: list[Violation] = []
    used: set[str] = set()
    for f in project.files:
        for node in f.walk():
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("span", "start_span")):
                continue
            recv = _expr_text(node.func.value)
            if not (recv == "tracing" or recv.split(".")[-1] == "TRACER"):
                continue
            if f.allowed("span-point", node.lineno):
                # Hatched sites (e.g. a helper forwarding literal points)
                # are exempt from the literal/registered checks.
                continue
            point = _first_str_arg(node)
            if point is None:
                out.append(Violation(
                    "span-point", f.rel, node.lineno,
                    "span point must be a string literal"))
            elif point not in registry:
                out.append(Violation(
                    "span-point", f.rel, node.lineno,
                    f"span point {point!r} is not registered in "
                    f"common/tracing.py SPAN_POINTS"))
            else:
                used.add(point)
    for point, line in sorted(registry.items()):
        if point not in used:
            out.append(Violation(
                "span-point", reg_file.rel, line,
                f"registered span point {point!r} has no call site "
                f"(dead span point)"))
    return out


# ----------------------------------------------------------------- hot json
def rule_hot_json(project: Project) -> list[Violation]:
    """Hot-path dispatch sites must not hand-roll JSON. ``rpc/wire.py``
    registers the hot functions (``HOT_PATH_FUNCTIONS``: "Class.method" or
    a bare module-level function name); inside each, ``json.dumps(...)``
    calls and ``json=`` kwargs (requests/aiohttp implicit JSON encoding)
    are violations — encode through ``rpc.wire`` so the wire format stays
    negotiated in one place (hatch: ``# xlint: allow-hot-json(reason)``).
    Bidirectional: a registered name with no matching function is a
    violation too (stale registry)."""
    registry: dict[str, int] = {}
    reg_file: Optional[SourceFile] = None
    for f in project.files:
        if f.path.name != "wire.py":
            continue
        found = _registry_dict(f, "HOT_PATH_FUNCTIONS")
        if found:
            registry, reg_file = found, f
    if reg_file is None:
        return []   # partial tree (e.g. fixture subset without a registry)

    out: list[Violation] = []
    found: set[str] = set()
    for f in project.files:
        for cls_name, fn in _iter_functions(f):
            qual = f"{cls_name}.{fn.name}" if cls_name else fn.name
            if qual not in registry:
                continue
            found.add(qual)
            for node in project.fn_walk(fn):
                why = None
                # Any json.dumps REFERENCE (call or alias like
                # `dumps = json.dumps`) — an alias would otherwise launder
                # the encode past a call-only check.
                if isinstance(node, ast.Attribute) \
                        and node.attr == "dumps" \
                        and _expr_text(node.value) \
                        .rsplit(".", 1)[-1] == "json":
                    why = "json.dumps"
                elif isinstance(node, ast.Call) \
                        and any(kw.arg == "json" for kw in node.keywords):
                    why = "json= kwarg (implicit JSON encode)"
                if why is not None and not f.allowed("hot-json", node.lineno):
                    out.append(Violation(
                        "hot-json", f.rel, node.lineno,
                        f"{qual}: {why} on a registered hot dispatch path "
                        f"— encode via rpc.wire (or hatch with "
                        f"'# xlint: allow-hot-json(reason)')"))
    for qual, line in sorted(registry.items()):
        if qual not in found:
            out.append(Violation(
                "hot-json", reg_file.rel, line,
                f"registered hot-path function {qual!r} has no matching "
                f"function in the tree (stale registry entry)"))
    return out


# -------------------------------------------------------------- broad except
def _handler_is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    names = []
    if isinstance(t, ast.Name):
        names = [t.id]
    elif isinstance(t, ast.Tuple):
        names = [e.id for e in t.elts if isinstance(e, ast.Name)]
    return any(n in ("Exception", "BaseException") for n in names)


def _handler_logs_or_raises(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in LOG_METHODS \
                and "log" in _expr_text(node.func.value).lower():
            return True
    return False


def rule_broad_except(project: Project) -> list[Violation]:
    out: list[Violation] = []
    for f in project.files:
        # Scope on the absolute path, not the display-relative one: a
        # single-file invocation strips the parent dirs from f.rel and
        # would silently drop the scheduler/rpc/coordination/engine scope.
        scoped = bool(EXCEPT_SCOPED_DIRS & set(f.path.parts))
        for node in f.walk():
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                if not f.allowed("broad-except", node.lineno):
                    out.append(Violation(
                        "broad-except", f.rel, node.lineno,
                        "bare 'except:' — name the exception type"))
                continue
            if scoped and _handler_is_broad(node) \
                    and not _handler_logs_or_raises(node) \
                    and not f.allowed("broad-except", node.lineno):
                out.append(Violation(
                    "broad-except", f.rel, node.lineno,
                    "broad 'except Exception' neither logs nor re-raises "
                    "(add logging, re-raise, or "
                    "'# xlint: allow-broad-except(reason)')"))
    return out


def _rel_parts(rel: str) -> list[str]:
    return rel.replace("\\", "/").split("/")


# ------------------------------------------------------------ async blocking
def _is_async_blocking_call(node: ast.Call) -> Optional[str]:
    """Blocking primitives that stall the event loop when called from a
    coroutine. Reuses the under-lock detector and adds the raw channel
    helpers (`_get`/`_post` are requests-backed)."""
    why = _is_blocking_call(node)
    if why is not None:
        return why
    if isinstance(node.func, ast.Attribute) \
            and node.func.attr in ("_get", "_post"):
        recv = _expr_text(node.func.value)
        return f"channel {recv}.{node.func.attr}() performs blocking HTTP I/O"
    return None


def rule_async_blocking(project: Project) -> list[Violation]:
    """No blocking calls lexically inside ``async def``: one
    ``time.sleep``/``requests.post`` in a handler freezes EVERY in-flight
    request on that loop, not just its own. Awaited calls and async-with/
    async-for operands are exempt (they are the async API); nested sync
    defs start a fresh execution context (they run wherever they are
    called — usually an executor)."""
    out: list[Violation] = []
    for f in project.files:
        for fn in [n for n in f.walk()
                   if isinstance(n, ast.AsyncFunctionDef)]:
            exempt: set[int] = set()
            for n in ast.walk(fn):
                if isinstance(n, ast.Await):
                    exempt.add(id(n.value))
                elif isinstance(n, ast.AsyncWith):
                    for item in n.items:
                        exempt.add(id(item.context_expr))
                elif isinstance(n, ast.AsyncFor):
                    exempt.add(id(n.iter))

            def visit(node, top=False):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.Lambda)) and not top:
                    return   # fresh execution context
                if isinstance(node, ast.Call) and id(node) not in exempt:
                    why = _is_async_blocking_call(node)
                    if why is not None \
                            and not f.allowed("async-blocking", node.lineno):
                        out.append(Violation(
                            "async-blocking", f.rel, node.lineno,
                            f"{why} inside 'async def {fn.name}' — "
                            f"blocking a coroutine stalls the whole event "
                            f"loop (await the async API or move to an "
                            f"executor)"))
                for child in ast.iter_child_nodes(node):
                    visit(child)

            visit(fn, top=True)
    return out


# ------------------------------------------------------- RCU publication
#: In-place container mutators: calling any of these on a published
#: value is a torn-state bug (readers hold the same object).
RCU_MUTATORS = {
    "append", "extend", "insert", "add", "update", "pop", "popitem",
    "clear", "remove", "discard", "setdefault", "sort", "reverse",
    "difference_update", "intersection_update",
    "symmetric_difference_update", "__setitem__",
}

#: Builtins whose call yields a FRESH container (safe to publish, safe
#: to mutate before publication even when fed a frozen source).
_FRESH_BUILTINS = {"dict", "list", "set", "tuple", "frozenset", "sorted"}

_FRESH_DISPLAYS = (ast.Dict, ast.List, ast.Set, ast.Tuple, ast.DictComp,
                   ast.ListComp, ast.SetComp)


@dataclass
class _RcuPub:
    cls: str
    attr: str
    type_name: str        # registered frozen type or builtin container
    lock_attr: str        # declared writer lock attribute
    line: int


class _RcuModel:
    """Parsed RCU registries + derived cross-file facts."""

    def __init__(self, project: Project, reg_file: SourceFile,
                 frozen: dict[str, int], pub_specs):
        self.project = project
        self.reg_file = reg_file
        self.frozen = frozen                       # type name -> line
        self.pubs: dict[tuple[str, str], _RcuPub] = {}
        self.spec_errors: list[Violation] = []
        for key, (val, line) in pub_specs.items():
            cls, _, attr = key.partition(".")
            tname, sep, lock = (val or "").partition("@")
            if not attr or not sep or not tname.strip() or not lock.strip():
                self.spec_errors.append(Violation(
                    "rcu-publish", reg_file.rel, line,
                    f"RCU publication {key!r} must be registered as "
                    f"'Class.attr': 'Type @ writer_lock'"))
                continue
            self.pubs[(cls, attr)] = _RcuPub(
                cls=cls, attr=attr, type_name=tname.strip(),
                lock_attr=lock.strip(), line=line)
        self.pub_attr_names = {attr for (_, attr) in self.pubs}
        # Accessors: (cls, meth) -> (cls, attr) it returns, for methods
        # whose body contains `return self.<registered pub attr>`; plus
        # frozen-returning methods (any `return FrozenType(...)`).
        self.accessors: dict[tuple[str, str], tuple[str, str]] = {}
        self.frozen_returning: set[tuple[str, str]] = set()
        for (cls, meth), (fn, _f) in project.methods.items():
            for node in project.fn_walk(fn):
                if not isinstance(node, ast.Return) or node.value is None:
                    continue
                v = node.value
                if isinstance(v, ast.Attribute) \
                        and isinstance(v.value, ast.Name) \
                        and v.value.id == "self" \
                        and (cls, v.attr) in self.pubs:
                    self.accessors[(cls, meth)] = (cls, v.attr)
                elif isinstance(v, ast.Call) \
                        and self.call_makes_frozen_type(v):
                    self.frozen_returning.add((cls, meth))

    def call_makes_frozen_type(self, call: ast.Call) -> bool:
        fn = call.func
        name = fn.id if isinstance(fn, ast.Name) else (
            fn.attr if isinstance(fn, ast.Attribute) else "")
        return name in self.frozen


def _is_thaw_call(call: ast.Call) -> bool:
    fn = call.func
    return (isinstance(fn, ast.Name) and fn.id == "thaw") or \
        (isinstance(fn, ast.Attribute) and fn.attr == "thaw")


def _is_publish_call(call: ast.Call) -> bool:
    fn = call.func
    return (isinstance(fn, ast.Name) and fn.id == "publish") or \
        (isinstance(fn, ast.Attribute) and fn.attr == "publish")


class _FnRcu:
    """Per-function frozen-value tracking (the one-level call summaries
    of the RCU pass: ctor calls, publication reads, accessor calls)."""

    def __init__(self, model: _RcuModel, f: SourceFile,
                 cls_name: Optional[str], fn) -> None:
        self.model = model
        self.f = f
        self.cls = cls_name
        self.fn = fn
        self.in_frozen_class = (cls_name in model.frozen
                                and fn.name not in DECL_METHODS)
        self.class_pubs = {attr for (c, attr) in model.pubs
                          if c == cls_name}
        self.frozen_names: set[str] = set()
        self.poisoned: set[str] = set()   # ever bound to a non-frozen RHS
        self._track_locals()

    def _track_locals(self) -> None:
        # Fixpoint over simple name bindings: a local is frozen iff every
        # binding it receives is a frozen source. Loop/with/aug targets
        # poison (conservative: no flow analysis).
        for node in ast.walk(self.fn):
            tgt = None
            if isinstance(node, ast.For):
                tgt = node.target
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if item.optional_vars is not None:
                        self._poison(item.optional_vars)
            elif isinstance(node, ast.AugAssign):
                tgt = node.target
            if tgt is not None:
                self._poison(tgt)
        for _ in range(3):
            changed = False
            for node in ast.walk(self.fn):
                # AnnAssign counts as a binding too — an annotated alias
                # (`snap: RoutingSnapshot = self._snapshot`) must not
                # escape tracking (the PR-4 AnnAssign lesson, again).
                if isinstance(node, ast.AnnAssign) and node.value is not None \
                        and isinstance(node.target, ast.Name):
                    name, value = node.target.id, node.value
                elif isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    name, value = node.targets[0].id, node.value
                else:
                    continue
                if name in self.poisoned:
                    continue
                if self.is_frozen_expr(value):
                    if name not in self.frozen_names:
                        self.frozen_names.add(name)
                        changed = True
                else:
                    self.poisoned.add(name)
                    if name in self.frozen_names:
                        self.frozen_names.discard(name)
                    changed = True
            if not changed:
                break

    def _poison(self, tgt: ast.AST) -> None:
        for n in ast.walk(tgt):
            if isinstance(n, ast.Name):
                self.poisoned.add(n.id)

    def is_frozen_expr(self, node: ast.AST) -> bool:
        """Is this expression a published / frozen value? (Fields of
        frozen values are frozen — the freeze is deep.)"""
        if isinstance(node, ast.Name):
            if node.id == "self" and self.in_frozen_class:
                return True
            return node.id in self.frozen_names
        if isinstance(node, ast.Attribute):
            if isinstance(node.value, ast.Name) \
                    and node.value.id in ("self", "cls"):
                if self.in_frozen_class:
                    return True
                return node.attr in self.class_pubs
            return self.is_frozen_expr(node.value)
        if isinstance(node, ast.Call):
            if _is_thaw_call(node):
                return False   # the declared-writer escape hatch
            if _is_publish_call(node):
                return True
            if self.model.call_makes_frozen_type(node):
                return True
            # Accessor / frozen-returning project methods (one level).
            fn = node.func
            if isinstance(fn, ast.Attribute):
                recv = _expr_text(fn.value)
                target = self.model.project.resolve_call(
                    self.cls, recv == "self", fn.attr)
                if target is not None and (
                        target in self.model.accessors
                        or target in self.model.frozen_returning):
                    return True
            return False
        return False


def rule_rcu(project: Project) -> list[Violation]:
    """The RCU publication-discipline pass (three rules over the
    ``devtools/rcu.py`` registries — see the module docstring table)."""
    frozen: dict[str, int] = {}
    pub_specs = {}
    reg_file: Optional[SourceFile] = None
    for f in project.files:
        if f.path.name != "rcu.py":
            continue
        fr = _registry_dict(f, "RCU_FROZEN_TYPES")
        pb = _registry_items(f, "RCU_PUBLICATIONS")
        if fr or pb:
            frozen, pub_specs, reg_file = fr, pb, f
    if reg_file is None:
        return []   # partial tree (e.g. fixture subset without a registry)

    model = _RcuModel(project, reg_file, frozen, pub_specs)
    out: list[Violation] = list(model.spec_errors)

    # ---- bidirectional registry checks
    class_index: dict[str, tuple[SourceFile, int]] = {}
    for f in project.files:
        for node in f.tree.body:
            if isinstance(node, ast.ClassDef):
                class_index.setdefault(node.name, (f, node.lineno))
    for tname, line in sorted(frozen.items()):
        if tname not in class_index:
            out.append(Violation(
                "rcu-frozen", reg_file.rel, line,
                f"registered frozen type {tname!r} has no class "
                f"definition in the tree (stale registry entry)"))
    attr_assigned: set[tuple[str, str]] = set()
    for (cls, meth), (fn, _f) in project.methods.items():
        for node in project.fn_walk(fn):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id in ("self", "cls"):
                        attr_assigned.add((cls, t.attr))
    for (cls, attr), pub in sorted(model.pubs.items()):
        if cls not in class_index:
            out.append(Violation(
                "rcu-publish", reg_file.rel, pub.line,
                f"registered publication {cls}.{attr} has no class "
                f"{cls!r} in the tree (stale registry entry)"))
            continue
        if (cls, attr) not in attr_assigned:
            out.append(Violation(
                "rcu-publish", reg_file.rel, pub.line,
                f"registered publication {cls}.{attr} is never assigned "
                f"in class {cls} (stale registry entry)"))
        if (cls, pub.lock_attr) not in project.lock_decls:
            out.append(Violation(
                "rcu-publish", reg_file.rel, pub.line,
                f"publication {cls}.{attr} declares writer lock "
                f"{pub.lock_attr!r}, which is not a declared lock of "
                f"{cls} (the lock registry has no {cls}.{pub.lock_attr})"))
        if pub.type_name not in frozen \
                and pub.type_name not in _FRESH_BUILTINS:
            out.append(Violation(
                "rcu-publish", reg_file.rel, pub.line,
                f"publication {cls}.{attr} declares type "
                f"{pub.type_name!r}, which is neither a registered "
                f"frozen type nor a builtin container"))

    # ---- per-function analysis
    # Publication-swap sites lacking a lexical lock, keyed by enclosing
    # method, checked against call sites afterwards (one-level summary
    # over the shared call-context index).
    pending_lock: dict[tuple[str, str], list[tuple[_RcuPub, SourceFile, int]]] = {}

    def fresh_rhs(node: ast.AST, fr: _FnRcu, fresh_names: set[str]) -> bool:
        if isinstance(node, _FRESH_DISPLAYS):
            return True
        if isinstance(node, ast.Name):
            return node.id in fresh_names
        if isinstance(node, ast.Call):
            if _is_publish_call(node):
                return bool(node.args) and fresh_rhs(node.args[0], fr,
                                                     fresh_names)
            fn = node.func
            name = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else "")
            if name in _FRESH_BUILTINS or name in model.frozen:
                return True
        return False

    def scan_function(f: SourceFile, cls_name: Optional[str], fn) -> None:
        fr = _FnRcu(model, f, cls_name, fn)
        # Locals bound (only) from fresh builders, for the swap check.
        fresh_names: set[str] = set()
        for node in project.fn_walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and fresh_rhs(node.value, fr, fresh_names):
                fresh_names.add(node.targets[0].id)
            elif isinstance(node, ast.AnnAssign) \
                    and node.value is not None \
                    and isinstance(node.target, ast.Name) \
                    and fresh_rhs(node.value, fr, fresh_names):
                fresh_names.add(node.target.id)
        in_decl = fn.name in DECL_METHODS

        def pub_of_target(t: ast.AST) -> Optional[_RcuPub]:
            if isinstance(t, ast.Attribute) \
                    and isinstance(t.value, ast.Name) \
                    and t.value.id in ("self", "cls"):
                return model.pubs.get((cls_name, t.attr))
            return None

        def flag_frozen(node: ast.AST, what: str) -> None:
            if not f.allowed("rcu-frozen", node.lineno):
                out.append(Violation(
                    "rcu-frozen", f.rel, node.lineno,
                    f"{what} — RCU-published values are immutable after "
                    f"publish; build a replacement and swap the "
                    f"reference (declared entry-level writers go through "
                    f"rcu.thaw(..., reason))"))

        def visit(node: ast.AST, lock_stack: list[str]) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fn:
                # Nested defs: fresh lexical context for the lock stack
                # (the RCU mutation checks still apply — same values).
                for child in ast.iter_child_nodes(node):
                    visit(child, [])
                return
            entered = 0
            if isinstance(node, (ast.With, ast.AsyncWith)) \
                    and cls_name is not None:
                for key in _with_decl_locks(node, cls_name, project):
                    lock_stack.append(key[1])
                    entered += 1
            elif isinstance(node, ast.Assign) or (
                    isinstance(node, ast.AnnAssign)
                    and node.value is not None):
                # AnnAssign is a swap site too — an annotated publication
                # write must not escape the rule (the PR-4 lesson).
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    pub = pub_of_target(t)
                    if pub is not None and not in_decl:
                        check_swap(node, t, pub, lock_stack)
            elif isinstance(node, ast.AugAssign):
                pub = pub_of_target(node.target)
                if pub is not None and not in_decl \
                        and not f.allowed("rcu-publish", node.lineno):
                    out.append(Violation(
                        "rcu-publish", f.rel, node.lineno,
                        f"augmented assignment to publication "
                        f"{pub.cls}.{pub.attr} — publish with one "
                        f"reference swap of a freshly built object"))
            elif isinstance(node, ast.Call) and _is_thaw_call(node):
                reason = None
                if len(node.args) >= 2:
                    reason = node.args[1]
                else:
                    for kw in node.keywords:
                        if kw.arg == "reason":
                            reason = kw.value
                if reason is None or (isinstance(reason, ast.Constant)
                                      and not reason.value):
                    flag_frozen(node, "rcu.thaw() without a reason "
                                      "(the hatch requires one, like "
                                      "# xlint: allow-*(reason))")
            # ---- in-place mutation checks
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    mut = mutated_frozen(t)
                    if mut:
                        flag_frozen(t, mut)
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    mut = mutated_frozen(t)
                    if mut:
                        flag_frozen(t, mut)
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in RCU_MUTATORS \
                    and fr.is_frozen_expr(node.func.value):
                flag_frozen(node, f"in-place .{node.func.attr}() on "
                                  f"published value "
                                  f"{_expr_text(node.func.value)!r}")
            for child in ast.iter_child_nodes(node):
                visit(child, lock_stack)
            for _ in range(entered):
                lock_stack.pop()

        def mutated_frozen(t: ast.AST) -> Optional[str]:
            """An assignment/delete target that mutates a frozen value:
            attribute store on a frozen expr, or subscript store whose
            container is frozen."""
            if isinstance(t, ast.Attribute) and fr.is_frozen_expr(t.value):
                # `self.<pub> = ...` swap sites were handled above — a
                # pub attr on `self` is only "frozen" through
                # in_frozen_class, which publication classes are not.
                return (f"attribute write to published value "
                        f"{_expr_text(t)!r}")
            if isinstance(t, ast.Subscript) and fr.is_frozen_expr(t.value):
                return (f"item write on published value "
                        f"{_expr_text(t.value)!r}")
            return None

        def check_swap(node: ast.Assign, t: ast.AST, pub: _RcuPub,
                       lock_stack: list[str]) -> None:
            if not fresh_rhs(node.value, fr, fresh_names) \
                    and not f.allowed("rcu-publish", node.lineno):
                out.append(Violation(
                    "rcu-publish", f.rel, node.lineno,
                    f"publication {pub.cls}.{pub.attr} must swap in a "
                    f"freshly built {pub.type_name} (ctor call, builtin "
                    f"copy, display, or a local bound from one) — not "
                    f"{_expr_text(node.value) or 'this expression'!r}"))
            if pub.lock_attr not in lock_stack:
                pending_lock.setdefault((cls_name, fn.name), []).append(
                    (pub, f, node.lineno))

        visit(fn, [])

    for f in project.files:
        for cls_name, fn in _iter_functions(f):
            scan_function(f, cls_name, fn)

    # ---- one-level call-site summaries for non-lexical lock holds
    # (read off the shared call-context index — same traversal the
    # state-write rule uses).
    contexts = project.call_contexts()
    for (cls, meth), sites in pending_lock.items():
        callers = [held for (_ck, held, _d) in contexts.get((cls, meth), [])]
        for pub, f, line in sites:
            ok = bool(callers) and all(pub.lock_attr in held
                                       for held in callers)
            if not ok and not f.allowed("rcu-publish", line):
                held_desc = "no resolvable call sites" if not callers \
                    else "a call site without it"
                out.append(Violation(
                    "rcu-publish", f.rel, line,
                    f"publication {pub.cls}.{pub.attr} swapped outside "
                    f"'with self.{pub.lock_attr}' and {held_desc} "
                    f"(writers must serialize on the declared lock)"))

    # ---- rcu-read: registered hot readers load each publication once
    hot_registry: dict[str, int] = {}
    for f in project.files:
        if f.path.name != "wire.py":
            continue
        found = _registry_dict(f, "HOT_PATH_FUNCTIONS")
        if found:
            hot_registry = found
    if hot_registry:
        for f in project.files:
            for cls_name, fn in _iter_functions(f):
                qual = f"{cls_name}.{fn.name}" if cls_name else fn.name
                if qual not in hot_registry:
                    continue
                loads: dict[str, list[int]] = {}
                for node in project.fn_walk(fn):
                    if isinstance(node, ast.Attribute) \
                            and isinstance(node.ctx, ast.Load) \
                            and node.attr in model.pub_attr_names:
                        loads.setdefault(node.attr, []).append(node.lineno)
                    elif isinstance(node, ast.Call) \
                            and isinstance(node.func, ast.Attribute):
                        recv = _expr_text(node.func.value)
                        target = project.resolve_call(
                            cls_name, recv == "self", node.func.attr)
                        acc = model.accessors.get(target) \
                            if target is not None else None
                        if acc is not None:
                            loads.setdefault(acc[1], []).append(node.lineno)
                for attr, lines in sorted(loads.items()):
                    if len(lines) > 1 \
                            and not f.allowed("rcu-read", *lines):
                        out.append(Violation(
                            "rcu-read", f.rel, lines[1],
                            f"hot-path reader {qual} loads publication "
                            f"{attr!r} {len(lines)} times (lines "
                            f"{', '.join(map(str, lines))}) — a double "
                            f"load is a torn read; load once into a "
                            f"local"))
    return out


# ------------------------------------------------- state-ownership rules
#: Teardown methods counted as declaration scope by the state rules
#: (mirrors devtools/ownership.py LIFECYCLE_METHODS): they run after the
#: worker threads are joined, so their rebinds are bookkeeping.
STATE_LIFECYCLE_METHODS = {"stop", "close", "shutdown"}

_STATE_KINDS = {"lock", "rcu", "confined", "init-only", "immutable",
                "owner"}

#: In-place mutators checked on lock:/immutable attrs (superset of the
#: RCU set: deque-style ends included).
STATE_MUTATORS = RCU_MUTATORS | {"appendleft", "popleft", "__ior__",
                                 "__iand__", "__isub__", "__ixor__"}


def _parse_discipline(spec: str) -> tuple[str, str]:
    kind, _, arg = spec.partition(":")
    return kind.strip(), arg.strip()


def _guard_calls_in_test(test: ast.AST) -> set[str]:
    """Method names called as ``self.<name>(...)``/``cls.<name>(...)``
    inside an if-test, EXCLUDING calls under a ``not`` — the positive
    guards whose if-body a write may rely on (``owner:`` discipline).
    A negated guard dominates the wrong branch and earns no credit."""
    found: set[str] = set()

    def walk(node: ast.AST, negated: bool) -> None:
        if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.Not):
            walk(node.operand, not negated)
            return
        if isinstance(node, ast.Call) and not negated \
                and isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id in ("self", "cls"):
            found.add(node.func.attr)
        for child in ast.iter_child_nodes(node):
            walk(child, negated)

    walk(test, False)
    return found


def _parse_thread_roles(f: SourceFile) -> "dict[str, tuple[tuple[str, ...], int]]":
    """``THREAD_ROLES = {"role": {"threads": (...), "entries": (...)}}``
    → role -> (entry "Class.method" names, lineno)."""
    out: dict[str, tuple[tuple[str, ...], int]] = {}
    for node in f.tree.body:
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign):
            targets, value = [node.target], node.value
        else:
            continue
        if not isinstance(value, ast.Dict) or not any(
                isinstance(t, ast.Name) and t.id == "THREAD_ROLES"
                for t in targets):
            continue
        for k, v in zip(value.keys, value.values):
            if not (isinstance(k, ast.Constant)
                    and isinstance(k.value, str)):
                continue
            entries: tuple[str, ...] = ()
            if isinstance(v, ast.Dict):
                for kk, vv in zip(v.keys, v.values):
                    if isinstance(kk, ast.Constant) \
                            and kk.value == "entries" \
                            and isinstance(vv, (ast.Tuple, ast.List)):
                        entries = tuple(
                            e.value for e in vv.elts
                            if isinstance(e, ast.Constant)
                            and isinstance(e.value, str))
            out[k.value] = (entries, k.lineno)
    return out


def _parse_state_classes(f: SourceFile) -> "dict[str, int]":
    """``STATE_CLASSES = ("Scheduler", ...)`` → {class name: lineno}."""
    out: dict[str, int] = {}
    for node in f.tree.body:
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign):
            targets, value = [node.target], node.value
        else:
            continue
        if not isinstance(value, (ast.Tuple, ast.List)) or not any(
                isinstance(t, ast.Name) and t.id == "STATE_CLASSES"
                for t in targets):
            continue
        for e in value.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out[e.value] = e.lineno
    return out


def _is_escape_call(node: ast.AST) -> bool:
    """``ownership.escape(...)`` / ``escape(...)`` — the unified hatch."""
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    return (isinstance(fn, ast.Name) and fn.id == "escape") or \
        (isinstance(fn, ast.Attribute) and fn.attr == "escape")


def _escape_reason_missing(call: ast.Call) -> bool:
    if not call.args:
        return True
    a = call.args[0]
    return isinstance(a, ast.Constant) and not a.value


@dataclass
class _StateDecl:
    cls: str
    attr: str
    kind: str            # lock | rcu | confined | init-only | immutable
    arg: str             # lock attr / role name
    line: int


@dataclass
class _StateSite:
    decl: "Optional[_StateDecl]"   # None = undeclared post-init assign
    cls: str
    attr: str
    shape: str                     # rebind | item | mut
    file: SourceFile
    meth: str
    line: int
    locks: frozenset
    escaped: bool
    guards: frozenset = frozenset()   # positive self.<guard>() if-tests
    #                                   dominating this write (owner:)


def rule_state(project: Project) -> list[Violation]:
    """The shared-state ownership pass: ``state-decl`` (bidirectional
    registry discipline), ``state-write`` (writes obey the declared
    discipline), ``state-read`` (registered hot-path functions do not
    read lock-guarded attrs without the lock). Driven by
    ``devtools/ownership.py``'s ``STATE_DISCIPLINES`` / ``STATE_CLASSES``
    / ``THREAD_ROLES``; inert on partial trees without the registry."""
    reg_file: Optional[SourceFile] = None
    specs: dict = {}
    roles: dict = {}
    strict_classes: dict = {}
    for f in project.files:
        if f.path.name != "ownership.py":
            continue
        found = _registry_items(f, "STATE_DISCIPLINES")
        if found:
            specs, reg_file = found, f
            roles = _parse_thread_roles(f)
            strict_classes = _parse_state_classes(f)
    if reg_file is None:
        return []   # partial tree (e.g. fixture subset without a registry)

    out: list[Violation] = []

    # ---- parse declarations
    decls: dict[tuple[str, str], _StateDecl] = {}
    for key, (val, line) in specs.items():
        cls, _, attr = key.partition(".")
        kind, arg = _parse_discipline(val or "")
        bad = (not attr or kind not in _STATE_KINDS
               or (kind in ("lock", "confined", "owner") and not arg)
               or (kind in ("rcu", "init-only", "immutable") and arg))
        if bad:
            out.append(Violation(
                "state-decl", reg_file.rel, line,
                f"state discipline {key!r}: {val!r} is not one of "
                f"lock:<attr> | rcu | confined:<role> | owner:<guard> | "
                f"init-only | immutable"))
            continue
        decls[(cls, attr)] = _StateDecl(cls, attr, kind, arg, line)
    registered_classes = {c for (c, _a) in decls}

    # ---- class index + per-class assigned/mutated attr sets
    class_index: dict[str, tuple[SourceFile, int]] = {}
    class_methods: dict[str, set[str]] = {}
    for f in project.files:
        for node in f.tree.body:
            if isinstance(node, ast.ClassDef):
                class_index.setdefault(node.name, (f, node.lineno))
                ms = class_methods.setdefault(node.name, set())
                for b in node.body:
                    if isinstance(b, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                        ms.add(b.name)

    touched: dict[str, set[str]] = {}      # cls -> attrs assigned/mutated
    sites: list[_StateSite] = []

    def scan_method(f: SourceFile, cls_name: str, fn) -> None:
        meth = fn.name
        cls_touched = touched.setdefault(cls_name, set())

        def visit(node, lock_stack: list[str], esc: int,
                  guards: frozenset = frozenset()) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fn:
                for child in ast.iter_child_nodes(node):
                    visit(child, [], esc)
                return
            if isinstance(node, ast.If):
                # owner: discipline — writes inside the if-body of a
                # positive ``self.<guard>(...)`` test are guard-credited;
                # the test itself and the else branch are not.
                found = _guard_calls_in_test(node.test)
                visit(node.test, lock_stack, esc, guards)
                for child in node.body:
                    visit(child, lock_stack, esc, guards | found)
                for child in node.orelse:
                    visit(child, lock_stack, esc, guards)
                return
            entered = 0
            esc_entered = 0
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for key in _with_decl_locks(node, cls_name, project):
                    lock_stack.append(key[1])
                    entered += 1
                for item in node.items:
                    if _is_escape_call(item.context_expr):
                        esc_entered += 1
                        if _escape_reason_missing(item.context_expr) \
                                and not f.allowed("state-write",
                                                  node.lineno):
                            out.append(Violation(
                                "state-write", f.rel, node.lineno,
                                "ownership.escape() without a reason "
                                "(the hatch requires one, like "
                                "# xlint: allow-*(reason))"))
            esc += esc_entered

            def emit(attr: str, shape: str, line: int) -> None:
                cls_touched.add(attr)
                sites.append(_StateSite(
                    decl=decls.get((cls_name, attr)), cls=cls_name,
                    attr=attr, shape=shape, file=f, meth=meth, line=line,
                    locks=frozenset(lock_stack), escaped=esc > 0,
                    guards=guards))

            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                if isinstance(node, ast.AnnAssign) and node.value is None:
                    targets = []
                for t in targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id in ("self", "cls"):
                        emit(t.attr, "rebind", node.lineno)
                    elif isinstance(t, ast.Subscript) \
                            and isinstance(t.value, ast.Attribute) \
                            and isinstance(t.value.value, ast.Name) \
                            and t.value.value.id in ("self", "cls"):
                        emit(t.value.attr, "item", node.lineno)
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id in ("self", "cls"):
                        emit(t.attr, "rebind", node.lineno)
                    elif isinstance(t, ast.Subscript) \
                            and isinstance(t.value, ast.Attribute) \
                            and isinstance(t.value.value, ast.Name) \
                            and t.value.value.id in ("self", "cls"):
                        emit(t.value.attr, "item", node.lineno)
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in STATE_MUTATORS \
                    and isinstance(node.func.value, ast.Attribute) \
                    and isinstance(node.func.value.value, ast.Name) \
                    and node.func.value.value.id in ("self", "cls"):
                emit(node.func.value.attr, "mut", node.lineno)
            for child in ast.iter_child_nodes(node):
                visit(child, lock_stack, esc, guards)
            for _ in range(entered):
                lock_stack.pop()

        visit(fn, [], 0)

    for f in project.files:
        for cls_name, fn in _iter_functions(f):
            if cls_name in registered_classes \
                    or cls_name in strict_classes:
                scan_method(f, cls_name, fn)

    # ---- state-decl: registry liveness + cross-checks
    rcu_pubs: dict = {}
    for f in project.files:
        if f.path.name == "rcu.py":
            found = _registry_items(f, "RCU_PUBLICATIONS")
            if found:
                rcu_pubs = found
    role_used: set[str] = set()
    for (cls, attr), d in sorted(decls.items()):
        if cls not in class_index:
            out.append(Violation(
                "state-decl", reg_file.rel, d.line,
                f"registered state attribute {cls}.{attr} has no class "
                f"{cls!r} in the tree (stale registry entry)"))
            continue
        if attr not in touched.get(cls, set()):
            out.append(Violation(
                "state-decl", reg_file.rel, d.line,
                f"registered state attribute {cls}.{attr} is never "
                f"assigned or mutated in class {cls} (stale registry "
                f"entry)"))
        if d.kind == "lock" and (cls, d.arg) not in project.lock_decls:
            out.append(Violation(
                "state-decl", reg_file.rel, d.line,
                f"{cls}.{attr} declares discipline lock:{d.arg}, but "
                f"{cls}.{d.arg} is not a declared lock (no "
                f"# lock-order annotation in the lock registry)"))
        if d.kind == "confined":
            if d.arg not in roles:
                out.append(Violation(
                    "state-decl", reg_file.rel, d.line,
                    f"{cls}.{attr} declares confined:{d.arg}, but role "
                    f"{d.arg!r} is not defined in THREAD_ROLES"))
            else:
                role_used.add(d.arg)
        if d.kind == "rcu" and f"{cls}.{attr}" not in rcu_pubs:
            out.append(Violation(
                "state-decl", reg_file.rel, d.line,
                f"{cls}.{attr} declares discipline rcu, but is not "
                f"registered in RCU_PUBLICATIONS (devtools/rcu.py)"))
        if d.kind == "owner" and d.arg not in class_methods.get(cls, set()):
            out.append(Violation(
                "state-decl", reg_file.rel, d.line,
                f"{cls}.{attr} declares owner:{d.arg}, but {cls}.{d.arg} "
                f"is not a method of the class (the guard must be the "
                f"live ownership check its writes are dominated by)"))
    for role, (_entries, line) in sorted(roles.items()):
        if role not in role_used:
            out.append(Violation(
                "state-decl", reg_file.rel, line,
                f"thread role {role!r} has no confined:{role} "
                f"declaration (dead role — stale registry entry)"))
    # Reverse rcu cross-check: publications on state-registered classes
    # must be declared rcu here (one ownership model, no blind spots).
    for key, (_val, line) in sorted(rcu_pubs.items()):
        cls, _, attr = key.partition(".")
        if cls in registered_classes and (cls, attr) not in decls:
            out.append(Violation(
                "state-decl", reg_file.rel, 0,
                f"RCU publication {key} (rcu.py:{line}) belongs to "
                f"state-registered class {cls} but has no "
                f"STATE_DISCIPLINES entry (declare it 'rcu')"))
    # STATE_CLASSES liveness.
    for cls, line in sorted(strict_classes.items()):
        if cls not in class_index:
            out.append(Violation(
                "state-decl", reg_file.rel, line,
                f"STATE_CLASSES entry {cls!r} has no class definition "
                f"in the tree (stale registry entry)"))
        elif cls not in registered_classes:
            out.append(Violation(
                "state-decl", reg_file.rel, line,
                f"STATE_CLASSES entry {cls!r} has no STATE_DISCIPLINES "
                f"declarations at all (register its attributes or drop "
                f"it)"))

    # ---- state-decl: completeness over the strict classes — every
    # attribute assigned outside construction/lifecycle scope must carry
    # a discipline (the enforcement ratchet the registry exists for).
    decl_scope = DECL_METHODS | STATE_LIFECYCLE_METHODS
    for s in sites:
        if s.decl is not None or s.shape != "rebind":
            continue
        if s.cls not in strict_classes or s.meth in decl_scope:
            continue
        if s.escaped or s.file.allowed("state-decl", s.line):
            continue
        out.append(Violation(
            "state-decl", s.file.rel, s.line,
            f"{s.cls}.{s.attr} is assigned outside __init__ but has no "
            f"STATE_DISCIPLINES entry — declare its discipline "
            f"(lock:<attr> | rcu | confined:<role> | init-only | "
            f"immutable) in devtools/ownership.py"))

    # ---- state-write
    contexts = project.call_contexts()

    def held_at_all_callsites(meth_key, lock_attr, path) -> bool:
        # Greatest-fixpoint walk with one twist: a cycle edge (caller
        # already on the path) contributes NO independent entry — a
        # write reachable only through mutually recursive helpers with
        # no locked external call site must flag, not vacuously pass.
        callers = contexts.get(meth_key)
        if not callers:
            return False
        external = False
        for ck, held, is_decl in callers:
            if is_decl or lock_attr in held:
                external = True
                continue
            if ck in path:
                continue   # cycle edge: no independent entry
            if not held_at_all_callsites(ck, lock_attr, path | {ck}):
                return False
            external = True
        return external

    def confined_via_callers(meth_key, entries, path) -> bool:
        callers = contexts.get(meth_key)
        if not callers:
            return False
        external = False
        for ck, _held, is_decl in callers:
            if is_decl or f"{ck[0]}.{ck[1]}" in entries \
                    or ck[1] in STATE_LIFECYCLE_METHODS:
                external = True
                continue
            if ck in path:
                continue   # cycle edge: no independent entry
            if not confined_via_callers(ck, entries, path | {ck}):
                return False
            external = True
        return external

    for s in sites:
        d = s.decl
        if d is None:
            continue
        if s.escaped or s.file.allowed("state-write", s.line):
            continue
        if s.meth in DECL_METHODS:
            continue
        key = (d.cls, s.meth)
        if d.kind == "lock":
            if d.arg in s.locks:
                continue
            if held_at_all_callsites(key, d.arg, {key}):
                continue
            out.append(Violation(
                "state-write", s.file.rel, s.line,
                f"{d.cls}.{d.attr} (lock:{d.arg}) written outside "
                f"'with self.{d.arg}' and not all call sites of "
                f"{s.meth}() hold it (writers must serialize on the "
                f"declared lock)"))
        elif d.kind == "confined":
            if s.shape != "rebind" or s.meth in STATE_LIFECYCLE_METHODS:
                continue
            entries = set(roles.get(d.arg, ((), 0))[0])
            if f"{d.cls}.{s.meth}" in entries:
                continue
            if confined_via_callers(key, entries, {key}):
                continue
            out.append(Violation(
                "state-write", s.file.rel, s.line,
                f"{d.cls}.{d.attr} (confined:{d.arg}) rebound in "
                f"{s.meth}(), which is not an entry function of role "
                f"{d.arg!r} (and not every call site resolves into "
                f"one)"))
        elif d.kind == "owner":
            if d.arg in s.guards:
                continue
            out.append(Violation(
                "state-write", s.file.rel, s.line,
                f"{d.cls}.{d.attr} (owner:{d.arg}) written outside an "
                f"'if self.{d.arg}(...)' guard — only the rendezvous "
                f"owner may write sharded telemetry state (hatch: "
                f"ownership.escape(reason) for owner-neutral "
                f"bookkeeping)"))
        elif d.kind in ("init-only", "immutable"):
            if s.shape == "rebind":
                if s.meth in STATE_LIFECYCLE_METHODS:
                    continue
                out.append(Violation(
                    "state-write", s.file.rel, s.line,
                    f"{d.cls}.{d.attr} ({d.kind}) rebound in "
                    f"{s.meth}() — declared assign-once at "
                    f"construction"))
            elif d.kind == "immutable":
                out.append(Violation(
                    "state-write", s.file.rel, s.line,
                    f"{d.cls}.{d.attr} (immutable) mutated in place in "
                    f"{s.meth}() — immutable state is never written "
                    f"after construction"))
        # d.kind == "rcu": writes are governed by the rcu-publish rule.

    # ---- state-read: hot-path functions vs lock-guarded attrs
    hot_registry: dict[str, int] = {}
    for f in project.files:
        if f.path.name == "wire.py":
            found = _registry_dict(f, "HOT_PATH_FUNCTIONS")
            if found:
                hot_registry = found
    if hot_registry:
        for f in project.files:
            for cls_name, fn in _iter_functions(f):
                if cls_name not in registered_classes:
                    continue
                qual = f"{cls_name}.{fn.name}"
                if qual not in hot_registry:
                    continue

                def visit_read(node, lock_stack, esc,
                               _f=f, _cls=cls_name, _fn=fn, _qual=qual):
                    if isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef,
                                         ast.Lambda)) and node is not _fn:
                        for child in ast.iter_child_nodes(node):
                            visit_read(child, [], esc)
                        return
                    entered = 0
                    if isinstance(node, (ast.With, ast.AsyncWith)):
                        for key in _with_decl_locks(node, _cls, project):
                            lock_stack.append(key[1])
                            entered += 1
                        for item in node.items:
                            if _is_escape_call(item.context_expr):
                                esc += 1
                    elif isinstance(node, ast.Attribute) \
                            and isinstance(node.ctx, ast.Load) \
                            and isinstance(node.value, ast.Name) \
                            and node.value.id == "self":
                        d = decls.get((_cls, node.attr))
                        if d is not None and d.kind == "lock" \
                                and d.arg not in lock_stack \
                                and not esc \
                                and not _f.allowed("state-read",
                                                   node.lineno):
                            out.append(Violation(
                                "state-read", _f.rel, node.lineno,
                                f"hot-path function {_qual} reads "
                                f"{_cls}.{node.attr} (lock:{d.arg}) "
                                f"without the lock — read an RCU "
                                f"snapshot instead, or take "
                                f"self.{d.arg}"))
                    for child in ast.iter_child_nodes(node):
                        visit_read(child, lock_stack, esc)
                    for _ in range(entered):
                        lock_stack.pop()

                visit_read(fn, [], 0)
    return out


# ------------------------------------------------------------- effect pairs
def _pair_registry(project: Project):
    """The ``devtools/lifecycle.py`` EFFECT_PAIRS registry (detected by
    filename, like the other registries): returns ``(registry file,
    {name: (PairSpec, line)}, [(error, line)])`` — or ``(None, {}, [])``
    when the tree subset has no registry (fixture runs)."""
    from ..lifecycle import parse_spec
    for f in project.files:
        if f.path.name != "lifecycle.py":
            continue
        items = _registry_items(f, "EFFECT_PAIRS")
        if not items:
            continue
        specs: dict[str, tuple] = {}
        errors: list[tuple[str, int]] = []
        for name, (val, line) in items.items():
            spec, errs = parse_spec(name, val)
            if spec is not None:
                specs[name] = (spec, line)
            errors.extend((e, line) for e in errs)
        return f, specs, errors
    return None, {}, []


def _ctor_class(node: ast.AST) -> Optional[str]:
    """Class name when `node` is a ``ClassName(...)`` constructor call."""
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    name = fn.id if isinstance(fn, ast.Name) else \
        fn.attr if isinstance(fn, ast.Attribute) else None
    return name if name and name[:1].isupper() else None


def _pair_aliases(project: Project) -> "dict[tuple, str]":
    """Receiver-resolution index for pair call sites. GENERIC_NAMES
    blocks method-name resolution for release-side names (``release``,
    ``remove``, ``record`` …), so the pair rules resolve the RECEIVER:
    module-level ``ADMISSION = AdmissionController(...)`` singletons map
    ``("<module>", "ADMISSION")`` → class, and ``self._journal =
    DeltaJournal(...)`` inits map ``(OwnerCls, "_journal")`` → class."""
    aliases: dict[tuple, str] = {}
    for f in project.files:
        for node in f.tree.body:
            cls = _ctor_class(node.value) \
                if isinstance(node, ast.Assign) else None
            if cls:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        aliases[("<module>", t.id)] = cls
        for cls_name, fn in _iter_functions(f):
            if cls_name is None:
                continue
            for sub in ast.walk(fn):
                if not isinstance(sub, ast.Assign):
                    continue
                cls = _ctor_class(sub.value)
                if not cls:
                    continue
                for t in sub.targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        aliases[(cls_name, t.attr)] = cls
    return aliases


def _pair_call_class(project: Project, aliases: "dict[tuple, str]",
                     cls_name: Optional[str],
                     call: ast.Call) -> Optional[str]:
    """Best-effort class of the receiver of an ``x.meth(...)`` call."""
    text = _expr_text(call.func.value)
    if text == "self":
        return cls_name
    if text.startswith("self.") and text.count(".") == 1:
        hit = aliases.get((cls_name, text[5:])) if cls_name else None
        if hit:
            return hit
    last = text.rsplit(".", 1)[-1]
    if ("<module>", last) in aliases:
        return aliases[("<module>", last)]
    if last in project.method_classes.get(call.func.attr, set()) or \
            (last, call.func.attr) in project.methods:
        return last          # classmethod/staticmethod-style receiver
    meth = call.func.attr
    if meth not in GENERIC_NAMES:
        owners = project.method_classes.get(meth, set())
        if len(owners) == 1:
            return next(iter(owners))
    return None


def _pair_own_classes(spec) -> set:
    own = {spec.acquire[0], spec.release[0]}
    if spec.transfer:
        own.add(spec.transfer[0])
    if spec.sink:
        own.add(spec.sink[0])
    return own


def rule_pair_release(project: Project) -> list[Violation]:
    """Every acquire site of a ``finally``-scope pair must be discharged
    by a try/finally that reaches the declared release — in the
    acquiring function itself or (for an acquire wrapped in a helper) in
    EVERY resolvable caller — or by the declared ownership transfer.
    This is the exact shape of the PR-12 admission-slot leak. Also owns
    the registry cross-checks: malformed specs, stale endpoints, dead
    pairs."""
    reg_f, specs, errors = _pair_registry(project)
    if reg_f is None:
        return []
    out: list[Violation] = [
        Violation("pair-release", reg_f.rel, line, msg)
        for msg, line in errors
        if not reg_f.allowed("pair-release", line)]
    aliases = _pair_aliases(project)

    # Bidirectional half 1: every declared endpoint must resolve to a
    # method defined somewhere in the tree.
    for name, (spec, line) in specs.items():
        for role, ref in (("acquire", spec.acquire),
                          ("release", spec.release),
                          ("transfer", spec.transfer),
                          ("sink", spec.sink)):
            if ref is not None and ref not in project.methods \
                    and not reg_f.allowed("pair-release", line):
                out.append(Violation(
                    "pair-release", reg_f.rel, line,
                    f"stale pair '{name}': {role} target "
                    f"{ref[0]}.{ref[1]} is not defined in the tree"))

    fin_pairs = [(name, spec) for name, (spec, _l) in specs.items()
                 if spec.scope == "finally"
                 and spec.acquire in project.methods]
    if not fin_pairs:
        return out

    fn_index: dict[tuple, tuple] = {}
    for f in project.files:
        for cls_name, fn in _iter_functions(f):
            fn_index[(cls_name, fn.name)] = (fn, f)
    contexts = project.call_contexts()

    def releases_in_finally(fn, cls_name, spec) -> bool:
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Try) and node.finalbody):
                continue
            for stmt in node.finalbody:
                for sub in ast.walk(stmt):
                    if isinstance(sub, ast.Call) \
                            and isinstance(sub.func, ast.Attribute) \
                            and sub.func.attr == spec.release[1] \
                            and _pair_call_class(project, aliases,
                                                 cls_name, sub) \
                            == spec.release[0]:
                        return True
        return False

    def discharged(key: tuple, spec, seen: frozenset) -> bool:
        """True when `key`'s function holds the finally-release itself
        or every resolvable caller does (the acquire-in-a-helper shape:
        the service's _admission_check acquires, its handler callers own
        the slot's try/finally). Cycle edges resolve optimistically,
        like the lock-summary fixpoint."""
        if key in seen:
            return True
        entry = fn_index.get(key)
        if entry is None:
            return False
        fn, _f = entry
        if releases_in_finally(fn, key[0], spec):
            return True
        callers = {c for (c, _locks, _d) in contexts.get(key, ())}
        callers.discard(key)
        if not callers:
            return False
        seen = seen | {key}
        return all(discharged(c, spec, seen) for c in callers)

    live: set = set()
    for f in project.files:
        for cls_name, fn in _iter_functions(f):
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)):
                    continue
                for name, spec in fin_pairs:
                    if node.func.attr != spec.acquire[1] \
                            or _pair_call_class(project, aliases, cls_name,
                                                node) != spec.acquire[0]:
                        continue
                    live.add(name)
                    if cls_name in _pair_own_classes(spec):
                        continue     # the pair's own machinery
                    if f.allowed("pair-release", node.lineno):
                        continue
                    if discharged((cls_name, fn.name), spec, frozenset()):
                        continue
                    out.append(Violation(
                        "pair-release", f.rel, node.lineno,
                        f"acquire of pair '{name}' "
                        f"({spec.acquire[0]}.{spec.acquire[1]}) is not "
                        f"discharged by a try/finally "
                        f"{spec.release[0]}.{spec.release[1]} here or in "
                        f"its callers (the PR-12 slot-leak shape)"))

    # Bidirectional half 2: a finally-pair no code acquires is dead —
    # the registry entry outlived its last call site.
    for name, (spec, line) in specs.items():
        if spec.scope == "finally" and spec.acquire in project.methods \
                and name not in live \
                and not reg_f.allowed("pair-release", line):
            out.append(Violation(
                "pair-release", reg_f.rel, line,
                f"dead pair '{name}': no acquire call site of "
                f"{spec.acquire[0]}.{spec.acquire[1]} in the tree"))
    return out


def rule_pair_once(project: Project) -> list[Violation]:
    """No path may release a ``finally``-scope pair twice: two
    unconditional releases in one function, or an unconditional release
    lexically after the declared ownership transfer (the transferred
    slot is released by the sink — releasing it here too would
    double-release). A release under a flag guard (``if slot["held"]``)
    is the blessed shape."""
    reg_f, specs, _errors = _pair_registry(project)
    if reg_f is None:
        return []
    aliases = _pair_aliases(project)
    fin_pairs = [(name, spec) for name, (spec, _l) in specs.items()
                 if spec.scope == "finally"]
    if not fin_pairs:
        return []

    out: list[Violation] = []
    GUARDS = (ast.If, ast.IfExp, ast.While, ast.ExceptHandler,
              ast.Assert, ast.BoolOp)

    for f in project.files:
        for cls_name, fn in _iter_functions(f):
            for name, spec in fin_pairs:
                if cls_name in _pair_own_classes(spec):
                    continue
                rels: list = []     # unguarded release calls, in order
                xfers: list = []    # unguarded transfer calls, in order

                def collect(node, guarded):
                    if isinstance(node, ast.Call) \
                            and isinstance(node.func, ast.Attribute):
                        attr = node.func.attr
                        if attr == spec.release[1] and not guarded \
                                and _pair_call_class(
                                    project, aliases, cls_name, node) \
                                == spec.release[0]:
                            rels.append(node)
                        elif spec.transfer is not None \
                                and attr == spec.transfer[1] \
                                and not guarded \
                                and _pair_call_class(
                                    project, aliases, cls_name, node) \
                                == spec.transfer[0]:
                            xfers.append(node)
                    for child in ast.iter_child_nodes(node):
                        collect(child,
                                guarded or isinstance(node, GUARDS))

                collect(fn, False)
                rels.sort(key=lambda n: n.lineno)
                xfers.sort(key=lambda n: n.lineno)
                for dup in rels[1:]:
                    if not f.allowed("pair-once", dup.lineno):
                        out.append(Violation(
                            "pair-once", f.rel, dup.lineno,
                            f"pair '{name}' released twice on the same "
                            f"path (first release at line "
                            f"{rels[0].lineno}); guard one release with "
                            f"the slot-ownership flag"))
                for rel in rels[:1]:
                    first_xfer = next((x for x in xfers
                                       if x.lineno < rel.lineno), None)
                    if first_xfer is not None \
                            and not f.allowed("pair-once", rel.lineno):
                        out.append(Violation(
                            "pair-once", f.rel, rel.lineno,
                            f"pair '{name}' released after ownership "
                            f"transfer ({spec.transfer[0]}."
                            f"{spec.transfer[1]} at line "
                            f"{first_xfer.lineno}) — the sink releases "
                            f"the transferred slot; guard this release "
                            f"with the slot-ownership flag"))
    return out


def rule_pair_evict(project: Project) -> list[Violation]:
    """Labeled metric series are released ONLY through the blessed
    eviction helper declared by the ``evict``-scope pair: a direct
    ``INSTRUMENT.remove(...)`` outside metrics.py is a hand-rolled
    eviction path, and a ``.labels(...)`` write lexically after an
    eviction of the same instrument in the same function is the PR-12
    gauge-resurrection shape."""
    reg_f, specs, _errors = _pair_registry(project)
    if reg_f is None:
        return []
    ev_pairs = [(name, spec, line) for name, (spec, line) in specs.items()
                if spec.scope == "evict"]
    if not ev_pairs:
        return []

    out: list[Violation] = []
    decl_file = None
    instruments: dict[str, int] = {}
    top_defs: set[str] = set()
    for f in project.files:
        if f.path.name != "metrics.py":
            continue
        found: dict[str, int] = {}
        defs: set[str] = set()
        for node in f.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.add(node.name)
            elif isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and isinstance(node.value.func, ast.Attribute) \
                    and node.value.func.attr in ("counter", "gauge",
                                                 "histogram"):
                mname = _first_str_arg(node.value)
                tgt = node.targets[0]
                if mname and isinstance(tgt, ast.Name):
                    found[tgt.id] = node.lineno
        if found:
            decl_file, instruments, top_defs = f, found, defs

    helper_names: set[str] = set()
    for name, spec, line in ev_pairs:
        if spec.helper is None:
            if not reg_f.allowed("pair-evict", line):
                out.append(Violation(
                    "pair-evict", reg_f.rel, line,
                    f"evict pair '{name}' declares no helper= "
                    f"(the blessed release site in metrics.py)"))
            continue
        helper_names.add(spec.helper)
        if decl_file is not None and spec.helper not in top_defs \
                and not reg_f.allowed("pair-evict", line):
            out.append(Violation(
                "pair-evict", reg_f.rel, line,
                f"stale pair '{name}': helper {spec.helper}() is not "
                f"defined in {decl_file.rel}"))
    if decl_file is None or not instruments or not helper_names:
        return out
    blessed = sorted(helper_names)[0]

    def recv_ident(call: ast.Call) -> Optional[str]:
        recv = call.func.value
        if isinstance(recv, ast.Name):
            return recv.id
        if isinstance(recv, ast.Attribute):
            return recv.attr
        return None

    for f in project.files:
        in_metrics = f.path.name == "metrics.py"
        for _cls_name, fn in _iter_functions(f):
            events: list[tuple[str, str, ast.Call]] = []
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                if isinstance(node.func, ast.Attribute):
                    ident = recv_ident(node)
                    if ident not in instruments:
                        continue
                    if node.func.attr == "remove":
                        events.append(("evict", ident, node))
                        if not in_metrics \
                                and not f.allowed("pair-evict",
                                                  node.lineno):
                            out.append(Violation(
                                "pair-evict", f.rel, node.lineno,
                                f"direct {ident}.remove(): evict labeled "
                                f"series via the blessed {blessed}() "
                                f"helper in metrics.py"))
                    elif node.func.attr == "labels":
                        events.append(("write", ident, node))
                elif isinstance(node.func, ast.Name) \
                        and node.func.id in helper_names and node.args:
                    a0 = node.args[0]
                    ident = a0.id if isinstance(a0, ast.Name) else \
                        a0.attr if isinstance(a0, ast.Attribute) else None
                    if ident in instruments:
                        events.append(("evict", ident, node))
            events.sort(key=lambda e: e[2].lineno)
            first_evict: dict[str, int] = {}
            for kind, ident, node in events:
                if kind == "evict":
                    first_evict.setdefault(ident, node.lineno)
                elif ident in first_evict \
                        and node.lineno > first_evict[ident] \
                        and not f.allowed("pair-evict", node.lineno):
                    out.append(Violation(
                        "pair-evict", f.rel, node.lineno,
                        f"write to {ident} after its series were evicted "
                        f"at line {first_evict[ident]} (the PR-12 "
                        f"gauge-resurrection shape) — writes must only "
                        f"be reachable while the owning entity is "
                        f"registered"))
    return out


ALL_RULES = (
    rule_lock_discipline,
    rule_no_blocking_under_lock,
    rule_lock_order,
    rule_fault_point,
    rule_span_point,
    rule_metrics_registry,
    rule_hot_json,
    rule_broad_except,
    rule_async_blocking,
    rule_rcu,
    rule_state,
    rule_pair_release,
    rule_pair_once,
    rule_pair_evict,
)

#: Relaxed profile for support code (tests/, benchmarks/): every
#: behavioral rule, minus the declaration-discipline rule — support code
#: does not register locks/points, and a bench driver's ad-hoc local
#: lock is fine as long as nothing blocks under it.
SUPPORT_RULES = tuple(r for r in ALL_RULES if r is not rule_lock_discipline)
