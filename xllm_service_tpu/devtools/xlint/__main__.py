"""CLI: ``python -m xllm_service_tpu.devtools.xlint [paths...]``."""

import sys

from . import main

if __name__ == "__main__":
    sys.exit(main())
