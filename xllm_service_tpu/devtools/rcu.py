"""RCU publication discipline: registry + opt-in deep-freeze detector.

Every hot path in the orchestration plane reads RCU-published state:
writers build a fresh immutable object under their writer lock and
install it with ONE reference swap; readers grab the reference once and
never lock. The bug class this invites — in-place mutation of a
published snapshot, copy-on-write skipped on one writer path,
delete/install ordered so readers see a half-pruned intermediate — has
produced most of the review fixes since the lock-free refactors landed
(PR-5 compaction, PR-6 COW apply, PR-7 `offloaded`-delta cancellation).
This module is the machine check, one layer up from ``make_lock``/
``XLLM_LOCK_DEBUG``:

**Registries** (statically cross-checked by xlint's rcu rules — both are
bidirectional, like ``FAULT_POINTS``/``SPAN_POINTS``):

- :data:`RCU_FROZEN_TYPES` — types whose instances are IMMUTABLE once
  published. xlint's ``rcu-frozen`` rule flags any mutation reachable
  from a published value anywhere in the tree.
- :data:`RCU_PUBLICATIONS` — the publication attributes themselves
  (``"Class.attr": "Type @ writer_lock"``). xlint's ``rcu-publish`` rule
  requires every write to be a single reference swap of a freshly built
  object under the declared writer lock; ``rcu-read`` requires
  registered hot-path readers to load the attribute exactly once.

**Runtime** (``XLLM_RCU_DEBUG=1``): :func:`publish` deep-freezes the
object being published — dicts/lists/sets are swapped for
mutation-raising views, registered types get a ``__setattr__``-raising
shadow subclass, recursively — so every existing chaos drill,
multimaster kill drill and tier-transition test doubles as a
snapshot-race detector. With the env var unset :func:`publish` returns
its argument unchanged (one module-global check — same disabled-path
cost as ``make_lock``).

**Escape hatch**: entry-level RCU writers (global_kvcache_mgr swaps
immutable ``_BlockLoc`` records inside the shared ``blocks`` dict — the
slot swap is atomic under the GIL) mutate through :func:`thaw`, which
requires a reason string exactly like an ``# xlint: allow-*(reason)``
comment. ``thaw`` is also the static hatch: xlint does not track a local
bound from ``rcu.thaw(...)`` as frozen.

Violations are recorded AND raised (:class:`RcuMutationError`): raising
pinpoints the mutating stack in the failing test; recording survives
broad-except swallowing — ``tests/conftest.py`` fails any test that
recorded one while debug mode is on, mirroring the instrumented-lock
guard.
"""

from __future__ import annotations

import os
import threading
import traceback
from dataclasses import dataclass, field
from typing import Any, Iterable, Iterator, Optional

#: Types whose instances are immutable once published (RCU read views).
#: Key = class name (matched by name: the owning modules import this
#: module, not the other way around). xlint requires each to resolve to
#: a live class in the tree.
RCU_FROZEN_TYPES: dict[str, str] = {
    "RoutingSnapshot":
        "instance_mgr's fleet view: built under _cluster_lock, read "
        "lock-free by every schedule/bind/dispatch",
    "PrefixIndex":
        "global_kvcache_mgr's published index wrapper: match() walks it "
        "with no lock (entries swap via thaw, see module doc)",
    "_BlockLoc":
        "per-block location record: writers build a replacement and swap "
        "the index slot, never edit in place",
    "InstanceLoadInfo":
        "per-instance load view handed to CAR/SLO scoring off the "
        "published _load_infos dict",
}

#: Publication attributes: ``"Class.attr" -> "Type @ writer_lock"``.
#: ``Type`` names the published value's type (a registered frozen type,
#: or a builtin container like ``dict``/``tuple``); ``writer_lock`` is
#: the declared lock attribute (cross-checked against the lock registry)
#: under which the single reference swap must occur. Writes anywhere in
#: the tree are checked by xlint's ``rcu-publish`` rule; registered
#: hot-path readers by ``rcu-read``.
RCU_PUBLICATIONS: dict[str, str] = {
    "InstanceMgr._snapshot": "RoutingSnapshot @ _cluster_lock",
    "InstanceMgr._load_infos": "dict @ _metrics_lock",
    "InstanceMgr._request_load_view": "dict @ _metrics_lock",
    "GlobalKVCacheMgr._snapshot": "PrefixIndex @ _lock",
    "OwnershipRouter._members": "tuple @ _lock",
}

_DEBUG = os.environ.get("XLLM_RCU_DEBUG", "") not in ("", "0")


def debug_enabled() -> bool:
    return _DEBUG


def set_debug(on: bool) -> None:
    """Test hook: toggles freezing for publications made AFTER the call
    (already-published objects keep whatever mode they were built with —
    same contract as ``locks.set_debug``)."""
    global _DEBUG
    _DEBUG = on


class RcuMutationError(RuntimeError):
    """A published (deep-frozen) RCU snapshot was mutated in place."""


@dataclass
class RcuViolation:
    kind: str            # "frozen-mutation"
    message: str
    thread: str
    stack: list[str] = field(default_factory=list)

    def __str__(self) -> str:
        return f"[{self.kind}] {self.message} (thread {self.thread})"


# Detector bookkeeping; never held across project locks.
_viol_lock = threading.Lock()   # lock-order: 902
_violations: list[RcuViolation] = []


def violations() -> list[RcuViolation]:
    with _viol_lock:
        return list(_violations)


def reset_violations() -> None:
    with _viol_lock:
        _violations.clear()


def _mutated(what: str, op: str) -> RcuMutationError:
    """Record a violation and build the error to raise. Recording happens
    even if a broad except swallows the raise — the conftest guard still
    fails the test."""
    msg = (f"in-place {op} on published {what} — RCU snapshots are "
           f"immutable after publish; build a fresh object and swap the "
           f"reference (or mutate via rcu.thaw(..., reason) if this is a "
           f"declared entry-level-RCU writer)")
    v = RcuViolation(kind="frozen-mutation", message=msg,
                     thread=threading.current_thread().name,
                     stack=traceback.format_stack(limit=16)[:-2])
    with _viol_lock:
        _violations.append(v)
    return RcuMutationError(msg)


# ------------------------------------------------------------ frozen views
class FrozenDict(dict):
    """Published dict view: reads are native dict reads, mutators raise.
    ``rcu.thaw`` is the declared-writer escape hatch (it mutates through
    the ``dict`` base methods, which this subclass cannot intercept — by
    design)."""

    __slots__ = ()

    def _no(self, *a, **k):
        raise _mutated("dict", "mutation")

    __setitem__ = __delitem__ = _no
    pop = popitem = clear = update = setdefault = _no
    __ior__ = _no


class FrozenList(list):
    __slots__ = ()

    def _no(self, *a, **k):
        raise _mutated("list", "mutation")

    __setitem__ = __delitem__ = _no
    append = extend = insert = remove = sort = reverse = clear = pop = _no
    __iadd__ = __imul__ = _no


class FrozenSet(set):
    __slots__ = ()

    def _no(self, *a, **k):
        raise _mutated("set", "mutation")

    add = discard = remove = pop = clear = update = _no
    difference_update = intersection_update = _no
    symmetric_difference_update = _no
    __ior__ = __iand__ = __isub__ = __ixor__ = _no


_FROZEN_VIEWS = (FrozenDict, FrozenList, FrozenSet)

# Generated __setattr__-raising shadow subclasses for registered types.
_frozen_classes: dict[type, type] = {}


def _frozen_subclass(cls: type) -> type:
    sub = _frozen_classes.get(cls)
    if sub is None:
        def _setattr(self, name, value):
            raise _mutated(cls.__name__, f"attribute write ({name})")

        def _delattr(self, name):
            raise _mutated(cls.__name__, f"attribute delete ({name})")

        sub = type(f"Frozen{cls.__name__}", (cls,), {
            "__slots__": (), "__setattr__": _setattr,
            "__delattr__": _delattr})
        _frozen_classes[cls] = sub
    return sub


def _slot_names(cls: type) -> Iterator[str]:
    for c in cls.__mro__:
        slots = getattr(c, "__slots__", ())
        if isinstance(slots, str):
            slots = (slots,)
        yield from slots


def _freeze_object(obj: Any) -> Any:
    """Shadow a registered-type instance with its frozen subclass:
    allocated without __init__, fields copied (deep-frozen). isinstance
    checks against the original class still pass."""
    cls = type(obj)
    if cls in _frozen_classes.values():
        return obj   # already frozen
    sub = _frozen_subclass(cls)
    out = object.__new__(sub)
    seen = set()
    for name in _slot_names(cls):
        if name in seen or name.startswith("__"):
            continue
        seen.add(name)
        try:
            object.__setattr__(out, name, freeze(getattr(obj, name)))
        except AttributeError:
            continue   # slot declared but never assigned
    d = getattr(obj, "__dict__", None)
    if d is not None:
        for name, value in d.items():
            object.__setattr__(out, name, freeze(value))
    return out


def freeze(value: Any) -> Any:
    """Deep-freeze a value: builtin containers become mutation-raising
    views, registered RCU types become ``__setattr__``-raising shadows;
    everything else (including deliberately-shared mutable leaves like
    ``_Entry``) passes through untouched. Idempotent."""
    t = type(value)
    if t in _FROZEN_VIEWS:
        return value
    # The ownership verifier's guarded views (devtools/ownership.py)
    # freeze as their base container — a drained/published guarded list
    # must still deep-freeze (the PR-7 bug class) when both detectors
    # are armed.
    guarded = getattr(t, "_xllm_guarded_kind", None)
    if t is dict or guarded == "dict":
        return FrozenDict({k: freeze(v) for k, v in value.items()})
    if t is list or guarded == "list":
        return FrozenList(freeze(v) for v in value)
    if t is set or guarded == "set":
        return FrozenSet(value)   # elements are hashable ⇒ immutable
    if t is tuple:
        frozen = tuple(freeze(v) for v in value)
        if all(a is b for a, b in zip(frozen, value)):
            return value   # no mutable children — keep the original
        return frozen
    if t.__name__ in RCU_FROZEN_TYPES or t in _frozen_classes.values():
        return _freeze_object(value)
    return value


def publish(obj: Any, label: str = "") -> Any:
    """Publication wrapper for RCU reference swaps:
    ``self._snapshot = rcu.publish(RoutingSnapshot(...))``.

    Passthrough (identity) when ``XLLM_RCU_DEBUG`` is unset; deep-frozen
    via :func:`freeze` when set. ``label`` is documentation only."""
    if not _DEBUG:
        return obj
    return freeze(obj)


# ------------------------------------------------------------ escape hatch
class _ThawedDict:
    """Mutable view over a FrozenDict for DECLARED entry-level-RCU
    writers (mutations route through the ``dict`` base methods). Reads
    delegate so writer code is oblivious to the wrapper."""

    __slots__ = ("_d",)

    def __init__(self, d: dict):
        self._d = d

    # reads
    def __getitem__(self, k):
        return self._d[k]

    def __contains__(self, k):
        return k in self._d

    def __iter__(self):
        return iter(self._d)

    def __len__(self):
        return len(self._d)

    def get(self, k, default=None):
        return self._d.get(k, default)

    def items(self):
        return self._d.items()

    def keys(self):
        return self._d.keys()

    def values(self):
        return self._d.values()

    # writes (through the base class, bypassing the frozen overrides)
    def __setitem__(self, k, v):
        dict.__setitem__(self._d, k, v)

    def __delitem__(self, k):
        dict.__delitem__(self._d, k)

    def pop(self, k, *default):
        return dict.pop(self._d, k, *default)

    def setdefault(self, k, default=None):
        return dict.setdefault(self._d, k, default)

    def update(self, *a, **k):
        dict.update(self._d, *a, **k)

    def clear(self):
        dict.clear(self._d)


def thaw(container: Any, reason: str) -> Any:
    """Escape hatch for declared entry-level-RCU writers: a mutable view
    of a frozen container. ``reason`` is mandatory (the runtime mirror of
    ``# xlint: allow-*(reason)``); xlint's ``rcu-frozen`` rule does not
    track a local bound from ``rcu.thaw(...)`` as frozen, and flags a
    call with a missing/empty reason. Passthrough when the container is
    not frozen (i.e. always, in production mode)."""
    if not reason or not isinstance(reason, str):
        raise ValueError("rcu.thaw requires a non-empty reason string")
    if isinstance(container, FrozenDict):
        return _ThawedDict(container)
    return container
