"""Tiktoken-style byte-level BPE.

Parity: reference `tiktoken_tokenizer.cpp` (470 LoC) — BPE over a vocab
file of `base64(token_bytes) rank` lines, with:

- regex pre-splitting (re2 in the reference; the `regex` module here —
  tiktoken-family patterns use `\\p{L}`-class properties stdlib `re`
  can't express),
- special tokens (escaped alternation split, longest-first, so special
  strings embedded in user text encode to their single ids),
- prefix tokens prepended to every encode (reference
  `tiktoken_tokenizer.cpp:63-70`).
"""

from __future__ import annotations

import base64
from pathlib import Path
from typing import Optional, Sequence

from .base import Tokenizer

try:                      # `regex` supports \p{...}; stdlib re does not.
    import regex as _re
except ImportError:       # pragma: no cover - regex ships in this image
    import re as _re


def _bpe_merge(piece: bytes, ranks: dict[bytes, int]) -> list[bytes]:
    """Standard greedy lowest-rank pair merging."""
    parts = [piece[i:i + 1] for i in range(len(piece))]
    while len(parts) > 1:
        best_rank = None
        best_i = -1
        for i in range(len(parts) - 1):
            r = ranks.get(parts[i] + parts[i + 1])
            if r is not None and (best_rank is None or r < best_rank):
                best_rank, best_i = r, i
        if best_rank is None:
            break
        parts[best_i:best_i + 2] = [parts[best_i] + parts[best_i + 1]]
    return parts


class TiktokenTokenizer(Tokenizer):
    def __init__(self, vocab_path: str | Path,
                 pattern: Optional[str] = None,
                 special_tokens: dict[str, int] | None = None,
                 prefix_tokens: Sequence[str] = ()):
        """vocab_path: the vocab file, or a model dir (first *.tiktoken
        inside; pass a TokenizerArgs-driven path from the factory)."""
        p = Path(vocab_path)
        if p.is_dir():
            cands = sorted(p.glob("*.tiktoken"))
            if not cands:
                raise FileNotFoundError(f"no *.tiktoken under {p}")
            p = cands[0]
        self._ranks: dict[bytes, int] = {}
        for line in p.read_text().splitlines():
            if not line.strip():
                continue
            tok_b64, _, rank = line.partition(" ")
            self._ranks[base64.b64decode(tok_b64)] = int(rank)
        self._id_to_bytes = {v: k for k, v in self._ranks.items()}

        # Special tokens not given explicit ids get sequential ids after
        # the vocab (reference `load_special_tokens`,
        # `tiktoken_tokenizer.cpp:79-96`).
        self._special: dict[str, int] = {}
        next_id = (max(self._ranks.values()) + 1) if self._ranks else 0
        for tok, tid in (special_tokens or {}).items():
            if tid is None or tid < 0:
                tid = next_id
                next_id += 1
            self._special[tok] = int(tid)
            next_id = max(next_id, int(tid) + 1)
        self._special_by_id = {v: k for k, v in self._special.items()}

        self._pattern = _re.compile(pattern) if pattern else None
        if self._special:
            # Longest-first alternation: overlapping specials resolve to
            # the longest match (reference escapes + joins with "|").
            self._special_split = _re.compile(
                "(" + "|".join(_re.escape(t) for t in sorted(
                    self._special, key=len, reverse=True)) + ")")
        else:
            self._special_split = None
        # Prefix token ids prepended to every encode (reference
        # `tiktoken_tokenizer.cpp:63-70`).
        self._prefix_ids: list[int] = []
        for tok in prefix_tokens:
            tid = self.token_to_id(tok)
            if tid is not None:
                self._prefix_ids.append(tid)

    def _encode_ordinary(self, text: str) -> list[int]:
        out: list[int] = []
        chunks = (self._pattern.findall(text) if self._pattern else [text])
        for chunk in chunks:
            if not isinstance(chunk, str):   # groups in user patterns
                chunk = next((c for c in chunk if c), "")
            data = chunk.encode("utf-8")
            if not data:
                continue
            rank = self._ranks.get(data)
            if rank is not None:
                out.append(rank)
                continue
            out.extend(self._ranks[p] for p in _bpe_merge(data, self._ranks)
                       if p in self._ranks)
        return out

    def encode(self, text: str) -> list[int]:
        out: list[int] = list(self._prefix_ids)
        if not self._special_split:
            out.extend(self._encode_ordinary(text))
            return out
        for part in self._special_split.split(text):
            if not part:
                continue
            if part in self._special:
                out.append(self._special[part])
            else:
                out.extend(self._encode_ordinary(part))
        return out

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        data = bytearray()
        for i in ids:
            if i in self._special_by_id:
                if not skip_special_tokens:
                    data.extend(self._special_by_id[i].encode("utf-8"))
                continue
            b = self._id_to_bytes.get(i)
            if b is not None:
                data.extend(b)
        return data.decode("utf-8", errors="replace")

    def vocab_size(self) -> int:
        return len(self._ranks) + len(self._special)

    def id_to_token(self, token_id: int) -> Optional[str]:
        if token_id in self._special_by_id:
            return self._special_by_id[token_id]
        b = self._id_to_bytes.get(token_id)
        return b.decode("utf-8", errors="replace") if b is not None else None

    def token_to_id(self, token: str) -> Optional[int]:
        if token in self._special:
            return self._special[token]
        return self._ranks.get(token.encode("utf-8"))
