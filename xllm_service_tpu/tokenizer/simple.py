"""Hermetic tokenizer for tests and model-file-less service runs.

Deterministic reversible byte-level scheme: each UTF-8 byte maps to id
`byte + 256`; ids < 256 are reserved for special tokens. Fills the role of
the reference's missing test tokenizer (SURVEY.md §4 notes the reference has
no hermetic fixtures).
"""

from __future__ import annotations

from typing import Optional, Sequence

from ..common import native as _native
from .base import Tokenizer

_BYTE_OFFSET = 256


class SimpleTokenizer(Tokenizer):
    def __init__(self, special_tokens: dict[str, int] | None = None):
        self._special = dict(special_tokens or {"<pad>": 0, "<bos>": 1, "<eos>": 2})
        self._special_by_id = {v: k for k, v in self._special.items()}

    def encode(self, text: str) -> list[int]:
        # Hottest route frame under fleet load (per-request prompt encode
        # inside Scheduler._schedule_inner) — libhotcore builds the id
        # list in C when available; identical output by construction.
        ids = _native.tok_encode(text)
        if ids is not _native.MISS:
            return ids
        return [b + _BYTE_OFFSET for b in text.encode("utf-8")]

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        data = bytearray()
        for i in ids:
            if i >= _BYTE_OFFSET:
                # Ids beyond the byte range (e.g. random bench vocabularies)
                # fold back into bytes — decode must never throw.
                data.append((i - _BYTE_OFFSET) % 256)
            elif not skip_special_tokens and i in self._special_by_id:
                data.extend(self._special_by_id[i].encode("utf-8"))
        return data.decode("utf-8", errors="replace")

    def vocab_size(self) -> int:
        return 512

    def id_to_token(self, token_id: int) -> Optional[str]:
        if token_id in self._special_by_id:
            return self._special_by_id[token_id]
        if _BYTE_OFFSET <= token_id < 512:
            return chr(token_id - _BYTE_OFFSET)
        return None

    def token_to_id(self, token: str) -> Optional[int]:
        if token in self._special:
            return self._special[token]
        if len(token) == 1 and ord(token) < 256:
            return ord(token) + _BYTE_OFFSET
        return None

    @property
    def eos_id(self) -> int:
        return self._special.get("<eos>", 2)
