"""Tokenizer backend selection.

Parity: reference `tokenizer_factory.cpp:9-32` — `tokenizer.json` exists →
Fast; tiktoken vocab → Tiktoken; else SentencePiece. We add: no path or
nothing recognized → hermetic SimpleTokenizer (the service must still boot
for fleets whose engines do their own tokenization).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Optional, Sequence

from .base import Tokenizer
from .simple import SimpleTokenizer
from .tiktoken import TiktokenTokenizer
from ..utils import get_logger

logger = get_logger(__name__)


class HFTokenizer(Tokenizer):
    """HuggingFace fast tokenizer (Rust core via the `tokenizers` binding).

    Replaces the reference's hand-rolled Rust cdylib FFI
    (`tokenizer/tokenizers/src/lib.rs:56-204`, `fast_tokenizer.cpp:20-30`).
    """

    def __init__(self, tokenizer_json: str | Path):
        from tokenizers import Tokenizer as _HFTok

        self._tok = _HFTok.from_file(str(tokenizer_json))

    def encode(self, text: str) -> list[int]:
        return self._tok.encode(text, add_special_tokens=False).ids

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=skip_special_tokens)

    def vocab_size(self) -> int:
        return self._tok.get_vocab_size()

    def id_to_token(self, token_id: int):
        return self._tok.id_to_token(token_id)

    def token_to_id(self, token: str):
        return self._tok.token_to_id(token)


class TokenizerFactory:
    @staticmethod
    def create_tokenizer(tokenizer_path: str = "") -> Tokenizer:
        if not tokenizer_path:
            return SimpleTokenizer()
        p = Path(tokenizer_path)
        tokenizer_json = p / "tokenizer.json" if p.is_dir() else (
            p if p.name == "tokenizer.json" else None)
        if tokenizer_json is not None and tokenizer_json.exists():
            return HFTokenizer(tokenizer_json)
        # tiktoken vocab (`*.tiktoken`).
        if p.is_dir():
            for cand in p.glob("*.tiktoken"):
                return TiktokenTokenizer(cand)
        elif p.suffix == ".tiktoken" and p.exists():
            return TiktokenTokenizer(p)
        # sentencepiece model.
        sp_model = p / "tokenizer.model" if p.is_dir() else (
            p if p.suffix == ".model" else None)
        if sp_model is not None and sp_model.exists():
            try:
                import sentencepiece  # noqa: F401

                from .sentencepiece_tok import SentencePieceTokenizer

                return SentencePieceTokenizer(sp_model)
            except ImportError:
                logger.warning("sentencepiece lib unavailable; "
                               "falling back to SimpleTokenizer")
        logger.warning("no recognizable tokenizer at %s; using SimpleTokenizer",
                       tokenizer_path)
        return SimpleTokenizer()

    @staticmethod
    def load_chat_template(tokenizer_path: str) -> Optional[str]:
        """chat_template from tokenizer_config.json (reference
        `tokenizer_args.h:30`, parsed by the args loader)."""
        if not tokenizer_path:
            return None
        cfg = Path(tokenizer_path) / "tokenizer_config.json"
        if not cfg.exists():
            return None
        try:
            data = json.loads(cfg.read_text())
        except json.JSONDecodeError:
            return None
        tmpl = data.get("chat_template")
        if isinstance(tmpl, list):  # some models ship multiple named templates
            for item in tmpl:
                if item.get("name") == "default":
                    return item.get("template")
            return tmpl[0].get("template") if tmpl else None
        return tmpl
