"""Tokenizer backend selection.

Parity: reference `tokenizer_factory.cpp:9-32` — `tokenizer.json` exists →
Fast; tiktoken vocab → Tiktoken; else SentencePiece. We add: no path or
nothing recognized → hermetic SimpleTokenizer (the service must still boot
for fleets whose engines do their own tokenization).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Optional, Sequence

from .base import Tokenizer
from .simple import SimpleTokenizer
from .tiktoken import TiktokenTokenizer
from ..utils import get_logger

logger = get_logger(__name__)


class HFTokenizer(Tokenizer):
    """HuggingFace fast tokenizer (Rust core via the `tokenizers` binding).

    Replaces the reference's hand-rolled Rust cdylib FFI
    (`tokenizer/tokenizers/src/lib.rs:56-204`, `fast_tokenizer.cpp:20-30`).
    """

    def __init__(self, tokenizer_json: str | Path):
        from tokenizers import Tokenizer as _HFTok

        self._tok = _HFTok.from_file(str(tokenizer_json))

    def encode(self, text: str) -> list[int]:
        return self._tok.encode(text, add_special_tokens=False).ids

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=skip_special_tokens)

    def vocab_size(self) -> int:
        return self._tok.get_vocab_size()

    def id_to_token(self, token_id: int):
        return self._tok.id_to_token(token_id)

    def token_to_id(self, token: str):
        return self._tok.token_to_id(token)


class TokenizerFactory:
    @staticmethod
    def load_args(tokenizer_path: str = ""):
        from .args import TokenizerArgs, load_tokenizer_args

        if not tokenizer_path:
            return TokenizerArgs()
        return load_tokenizer_args(tokenizer_path)

    @staticmethod
    def create_tokenizer(tokenizer_path: str = "",
                         args=None) -> Tokenizer:
        """Reference selection order (`tokenizer_factory.cpp:9-32`, args
        loaded first): tokenizer.json → Fast; args say tiktoken (or a
        *.tiktoken vocab exists) → Tiktoken with pattern/special/prefix
        tokens from args; else SentencePiece. We add: no path or nothing
        recognized → hermetic SimpleTokenizer."""
        if not tokenizer_path:
            return SimpleTokenizer()
        if args is None:
            args = TokenizerFactory.load_args(tokenizer_path)
        p = Path(tokenizer_path)
        tokenizer_json = p / "tokenizer.json" if p.is_dir() else (
            p if p.name == "tokenizer.json" else None)
        if tokenizer_json is not None and tokenizer_json.exists():
            return HFTokenizer(tokenizer_json)

        is_tiktoken = (args.tokenizer_type == "tiktoken"
                       or args.tokenizer_class == "TikTokenTokenizer"
                       or (p.is_dir() and any(p.glob("*.tiktoken")))
                       or p.suffix == ".tiktoken")
        if is_tiktoken:
            vocab = p
            if p.is_dir():
                named = p / args.vocab_file
                if named.exists() and named.suffix == ".tiktoken":
                    vocab = named
            try:
                return TiktokenTokenizer(
                    vocab, pattern=args.pattern or None,
                    special_tokens=dict(args.special_tokens),
                    prefix_tokens=args.prefix_tokens)
            except FileNotFoundError:
                logger.warning("tiktoken requested but no vocab at %s", p)

        # sentencepiece model.
        sp_model = p / args.vocab_file if p.is_dir() else (
            p if p.suffix == ".model" else None)
        if sp_model is not None and not sp_model.exists() and p.is_dir():
            sp_model = p / "tokenizer.model"
        if sp_model is not None and sp_model.exists():
            try:
                import sentencepiece  # noqa: F401

                from .sentencepiece_tok import SentencePieceTokenizer

                return SentencePieceTokenizer(sp_model, args=args)
            except ImportError:
                logger.warning("sentencepiece lib unavailable; "
                               "falling back to SimpleTokenizer")
        logger.warning("no recognizable tokenizer at %s; using SimpleTokenizer",
                       tokenizer_path)
        return SimpleTokenizer()

    @staticmethod
    def load_chat_template(tokenizer_path: str) -> Optional[str]:
        """chat_template via the args loader (reference
        `tokenizer_args.cpp:8-28,36-42`: chat_template.json /
        chat_template.jinja take priority over tokenizer_config.json)."""
        if not tokenizer_path:
            return None
        return TokenizerFactory.load_args(tokenizer_path).chat_template \
            or None
