"""Tokenizer interface (reference `tokenizer/tokenizer.h:28-46`)."""

from __future__ import annotations

import abc
from typing import Optional, Sequence


class Tokenizer(abc.ABC):
    @abc.abstractmethod
    def encode(self, text: str) -> list[int]: ...

    @abc.abstractmethod
    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str: ...

    @abc.abstractmethod
    def vocab_size(self) -> int: ...

    @abc.abstractmethod
    def id_to_token(self, token_id: int) -> Optional[str]: ...

    @abc.abstractmethod
    def token_to_id(self, token: str) -> Optional[int]: ...

    def clone(self) -> "Tokenizer":
        """Reference clones per thread for lock-free encode
        (`scheduler.cpp:274-277`); our backends are thread-safe, so the
        default clone is self."""
        return self
