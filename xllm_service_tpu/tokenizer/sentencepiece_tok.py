"""SentencePiece backend (reference `sentencepiece_tokenizer.cpp`, 337
LoC): sp model + TokenizerArgs-driven special tokens (escaped-alternation
split, same machinery as tiktoken, `sentencepiece_tokenizer.cpp:79-112`)
and prefix tokens prepended to every encode (:63-70).

Gated on the `sentencepiece` package (not present in every deployment
image); the factory falls back when missing.
"""

from __future__ import annotations

import re
from pathlib import Path
from typing import Optional, Sequence

from .base import Tokenizer


class SentencePieceTokenizer(Tokenizer):
    def __init__(self, model_path: str | Path, args=None):
        import sentencepiece as spm

        self._sp = spm.SentencePieceProcessor(model_file=str(model_path))
        self._special: dict[str, int] = {}
        self._special_by_id: dict[int, str] = {}
        self._special_split = None
        self._prefix_ids: list[int] = []
        if args is not None:
            for tok, tid in args.special_tokens:
                if tok in self._special or tid in self._special_by_id:
                    continue
                self._special[tok] = int(tid)
                self._special_by_id[int(tid)] = tok
            if self._special:
                self._special_split = re.compile(
                    "(" + "|".join(re.escape(t) for t in sorted(
                        self._special, key=len, reverse=True)) + ")")
            prefix = list(args.prefix_tokens)
            if args.add_bos_token and args.bos_token:
                prefix.insert(0, args.bos_token)
            for tok in prefix:
                tid = self.token_to_id(tok)
                if tid is not None:
                    self._prefix_ids.append(tid)
                elif args.add_bos_token and tok == args.bos_token \
                        and self._sp.bos_id() >= 0:
                    self._prefix_ids.append(self._sp.bos_id())

    def encode(self, text: str) -> list[int]:
        out: list[int] = list(self._prefix_ids)
        if self._special_split is None:
            out.extend(self._sp.encode(text))
            return out
        for part in self._special_split.split(text):
            if not part:
                continue
            if part in self._special:
                out.append(self._special[part])
            else:
                out.extend(self._sp.encode(part))
        return out

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        if not self._special_by_id:
            return self._sp.decode(list(ids))
        pieces: list[str] = []
        run: list[int] = []
        for i in ids:
            if i in self._special_by_id:
                if run:
                    pieces.append(self._sp.decode(run))
                    run = []
                if not skip_special_tokens:
                    pieces.append(self._special_by_id[i])
            else:
                run.append(int(i))
        if run:
            pieces.append(self._sp.decode(run))
        return "".join(pieces)

    def vocab_size(self) -> int:
        # Only specials OUTSIDE the sp id space extend the vocab; standard
        # checkpoints re-declare <s>/</s>/<unk> (ids inside the model) in
        # added_tokens_decoder and must not inflate the count.
        base = self._sp.vocab_size()
        return base + sum(1 for i in self._special_by_id if i >= base)

    def id_to_token(self, token_id: int) -> Optional[str]:
        if token_id in self._special_by_id:
            return self._special_by_id[token_id]
        try:
            return self._sp.id_to_piece(token_id)
        except IndexError:
            return None

    def token_to_id(self, token: str) -> Optional[int]:
        if token in self._special:
            return self._special[token]
        tid = self._sp.piece_to_id(token)
        return tid if tid != self._sp.unk_id() or token == self._sp.id_to_piece(self._sp.unk_id()) else None
