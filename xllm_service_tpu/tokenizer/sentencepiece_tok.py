"""SentencePiece backend (reference `sentencepiece_tokenizer.cpp`, 337 LoC).

Gated on the `sentencepiece` package (not present in every deployment
image); the factory falls back when missing.
"""

from __future__ import annotations

from pathlib import Path
from typing import Optional, Sequence

from .base import Tokenizer


class SentencePieceTokenizer(Tokenizer):
    def __init__(self, model_path: str | Path):
        import sentencepiece as spm

        self._sp = spm.SentencePieceProcessor(model_file=str(model_path))

    def encode(self, text: str) -> list[int]:
        return list(self._sp.encode(text))

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        return self._sp.decode(list(ids))

    def vocab_size(self) -> int:
        return self._sp.vocab_size()

    def id_to_token(self, token_id: int) -> Optional[str]:
        try:
            return self._sp.id_to_piece(token_id)
        except IndexError:
            return None

    def token_to_id(self, token: str) -> Optional[int]:
        tid = self._sp.piece_to_id(token)
        return tid if tid != self._sp.unk_id() or token == self._sp.id_to_piece(self._sp.unk_id()) else None
