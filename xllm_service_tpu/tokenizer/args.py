"""Tokenizer argument surface.

Parity: reference `tokenizer/tokenizer_args.{h,cpp}` (171 LoC) —
`TokenizerArgs` {tokenizer_type, vocab_file, special_tokens, pattern,
prefix_tokens, chat_template, add_bos_token, add_eos_token, bos_token,
eos_token, pad_token, tokenizer_class} loaded from the model directory:
chat_template.json / chat_template.jinja override tokenizer_config.json's
`chat_template`; bos/eos/pad accept either the HF dict form
(`{"content": ...}`) or a plain string. We additionally surface HF's
`added_tokens_decoder` as special tokens (the reference receives its
special-token list from engine model code the service repo doesn't ship).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Optional


@dataclass
class TokenizerArgs:
    tokenizer_type: str = "sentencepiece"     # "sentencepiece" | "tiktoken"
    vocab_file: str = "tokenizer.model"
    special_tokens: list[tuple[str, int]] = field(default_factory=list)
    pattern: str = ""                         # tiktoken regex pre-split
    prefix_tokens: list[str] = field(default_factory=list)
    chat_template: str = ""
    add_bos_token: bool = False
    add_eos_token: bool = False
    bos_token: str = ""
    eos_token: str = ""
    pad_token: str = ""
    tokenizer_class: str = ""


def _token_content(v) -> Optional[str]:
    """HF configs carry tokens as either "tok" or {"content": "tok", ...}
    (reference reads `bos_token.content` first, then the string form)."""
    if isinstance(v, str):
        return v
    if isinstance(v, dict):
        c = v.get("content")
        return c if isinstance(c, str) else None
    return None


def _load_chat_template_file(model_dir: Path) -> Optional[str]:
    """chat_template.json / chat_template.jinja take priority over the
    tokenizer_config.json field (reference `tokenizer_args.cpp:8-28`)."""
    ct_json = model_dir / "chat_template.json"
    if ct_json.exists():
        try:
            v = json.loads(ct_json.read_text()).get("chat_template")
            if isinstance(v, str):
                return v
        except (json.JSONDecodeError, OSError):
            pass
    ct_jinja = model_dir / "chat_template.jinja"
    if ct_jinja.exists():
        try:
            return ct_jinja.read_text()
        except OSError:
            pass
    return None


def load_tokenizer_args(model_dir: str | Path) -> TokenizerArgs:
    """Reference `load_tokenizer_args` (`tokenizer_args.cpp:30-72`)."""
    args = TokenizerArgs()
    model_dir = Path(model_dir)
    if not model_dir.is_dir():
        return args

    tmpl = _load_chat_template_file(model_dir)
    if tmpl is not None:
        args.chat_template = tmpl

    cfg_path = model_dir / "tokenizer_config.json"
    data: dict = {}
    if cfg_path.exists():
        try:
            data = json.loads(cfg_path.read_text())
        except (json.JSONDecodeError, OSError):
            data = {}
    if not args.chat_template:
        v = data.get("chat_template")
        if isinstance(v, list):   # multiple named templates
            default = next((i for i in v if i.get("name") == "default"),
                           v[0] if v else None)
            if default:
                args.chat_template = default.get("template") or ""
        elif isinstance(v, str):
            args.chat_template = v
    if isinstance(data.get("add_bos_token"), bool):
        args.add_bos_token = data["add_bos_token"]
    if isinstance(data.get("add_eos_token"), bool):
        args.add_eos_token = data["add_eos_token"]
    if isinstance(data.get("tokenizer_class"), str):
        args.tokenizer_class = data["tokenizer_class"]
    if isinstance(data.get("tokenizer_type"), str):
        args.tokenizer_type = data["tokenizer_type"]
    if isinstance(data.get("pattern"), str):
        args.pattern = data["pattern"]
    if isinstance(data.get("vocab_file"), str):
        args.vocab_file = data["vocab_file"]
    for name in ("bos_token", "eos_token", "pad_token"):
        c = _token_content(data.get(name))
        if c is not None:
            setattr(args, name, c)
    prefix = data.get("prefix_tokens")
    if isinstance(prefix, list):
        args.prefix_tokens = [str(t) for t in prefix]

    # HF added_tokens_decoder: {"id": {"content": "<tok>", ...}, ...}.
    added = data.get("added_tokens_decoder")
    if isinstance(added, dict):
        for tid, info in added.items():
            c = _token_content(info)
            try:
                tid_i = int(tid)
            except (TypeError, ValueError):
                continue
            if c is not None:
                args.special_tokens.append((c, tid_i))
    return args
