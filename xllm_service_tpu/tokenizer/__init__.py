"""L4c tokenization.

Parity: reference `tokenizer/` (SURVEY.md §2.8) — a `Tokenizer` interface
(`tokenizer.h:28-46`) with three backends selected by
`TokenizerFactory` (`tokenizer_factory.cpp:9-32`):

- tokenizer.json present → HuggingFace fast tokenizer. The reference binds
  the Rust `tokenizers` crate through a hand-rolled C ABI cdylib
  (`tokenizer/tokenizers/src/lib.rs`); here the same Rust core is reached
  through the maintained `tokenizers` Python binding — native speed, no FFI
  shim to maintain.
- tiktoken vocab file → our own byte-level BPE over ranked merges
  (reference `tiktoken_tokenizer.cpp`).
- sentencepiece model → wraps the sentencepiece lib when importable
  (absent in this environment; gated).

Plus a hermetic `SimpleTokenizer` used by tests and by services run without
model files.
"""

from .base import Tokenizer
from .factory import TokenizerFactory
from .simple import SimpleTokenizer

__all__ = ["Tokenizer", "TokenizerFactory", "SimpleTokenizer"]
