"""Multi-host (DCN) distributed backend.

The reference scales across hosts with an engine-side NCCL/MPI
communication backend (SURVEY.md §2.13; the service plane only carries
the metadata — `xllm_rpc_service.proto` InstanceMetaInfo). The TPU-native
equivalent needs no hand-written transport at all: `jax.distributed`
wires the process group, after which `jax.devices()` is GLOBAL and every
jitted program over a global `Mesh` executes collectively — XLA emits the
cross-host collectives and routes them over ICI within a slice and DCN
across slices. On CPU test meshes the same code path runs over Gloo, so
multi-host drills are hermetic (tests/test_multihost.py).

This module owns process-group bring-up plus the tiny host-side control
plane (`broadcast_bytes`) the lockstep serving driver
(`engine/multihost_driver.py`) uses to mirror request events from the
primary host to followers.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np

_initialized = False


def initialize(coordinator_address: str, num_processes: int,
               process_id: int) -> None:
    """Join the cross-host process group (idempotent). After this,
    `jax.devices()` spans every host and `build_mesh` meshes are global."""
    global _initialized
    if _initialized:
        return
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    _initialized = True


def initialize_from_env() -> bool:
    """Bring up multi-host from XLLM_MH_COORDINATOR / XLLM_MH_NUM_HOSTS /
    XLLM_MH_HOST_ID (the agent calls this before touching devices).
    Returns True when a multi-host group was (or already is) joined."""
    coord = os.environ.get("XLLM_MH_COORDINATOR", "")
    if not coord:
        return _initialized or jax.process_count() > 1
    initialize(coord,
               int(os.environ.get("XLLM_MH_NUM_HOSTS", "1")),
               int(os.environ.get("XLLM_MH_HOST_ID", "0")))
    return True


def process_count() -> int:
    return jax.process_count()


def is_primary() -> bool:
    """The primary host owns the request stream (HTTP service, agent
    registration); followers mirror engine events (multihost_driver)."""
    return jax.process_index() == 0


def broadcast_bytes(payload: Optional[bytes]) -> bytes:
    """Broadcast the primary's byte payload to every host.

    COLLECTIVE: all hosts must call this the same number of times, in the
    same program order. Two `broadcast_one_to_all` rounds — length first,
    then the body padded to that length (the collective needs identical
    shapes on every host; followers learn the shape from round one).
    """
    from jax.experimental import multihost_utils

    if is_primary():
        data = payload or b""
        n_arr = np.asarray([len(data)], np.int32)
    else:
        data = b""
        n_arr = np.zeros((1,), np.int32)
    n = int(multihost_utils.broadcast_one_to_all(n_arr)[0])
    if n == 0:
        return b""
    buf = (np.frombuffer(data, np.uint8) if is_primary()
           else np.zeros((n,), np.uint8))
    buf = multihost_utils.broadcast_one_to_all(buf)
    return bytes(np.asarray(buf))
