"""Parameter/activation sharding rules (GSPMD).

Rules map parameter-path regexes → PartitionSpecs over the named mesh axes.
XLA inserts the collectives (psum after row-parallel matmuls, all-gather
where needed) — we only annotate layouts (scaling-book recipe: pick a mesh,
annotate shardings, let XLA insert collectives).
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Any, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import AXIS_DATA, AXIS_EXPERT, AXIS_MODEL


@dataclass
class ShardingRules:
    """Ordered (path_regex, PartitionSpec) table; first match wins."""

    rules: list[tuple[str, P]]
    default: P = P()

    def spec_for(self, path: str) -> P:
        for pattern, spec in self.rules:
            if re.search(pattern, path):
                return spec
        return self.default


# Llama/Qwen family: column-parallel qkv/gate/up (shard output dim on
# `model`), row-parallel o/down (shard input dim on `model` — XLA emits the
# psum), vocab-sharded embeddings.
LLAMA_RULES = ShardingRules(rules=[
    (r"embed/embedding", P(AXIS_MODEL, None)),          # [vocab, d]
    # int8-quant scales ([out]) first: output-sharded for column-parallel
    # kernels, replicated for row-parallel (models/quant.py).
    (r"(q_proj|k_proj|v_proj|gate_proj|up_proj|lm_head)/kernel/scale",
     P(AXIS_MODEL)),
    (r"(o_proj|down_proj)/kernel/scale", P()),
    (r"(q_proj|k_proj|v_proj)/kernel", P(None, AXIS_MODEL)),   # [d, heads*hd]
    (r"(q_proj|k_proj|v_proj)/bias", P(AXIS_MODEL)),
    (r"o_proj/kernel", P(AXIS_MODEL, None)),            # [heads*hd, d]
    (r"(gate_proj|up_proj)/kernel", P(None, AXIS_MODEL)),      # [d, ffn]
    (r"down_proj/kernel", P(AXIS_MODEL, None)),         # [ffn, d]
    (r"lm_head/kernel", P(None, AXIS_MODEL)),           # [d, vocab]
    (r"(input_norm|post_attn_norm|final_norm)/scale", P()),
])

# MoE family adds expert-stacked tensors: leading expert dim on `expert`,
# per-expert ffn on `model`.
MOE_RULES = ShardingRules(rules=[
    (r"experts/(gate_proj|up_proj)/kernel", P(AXIS_EXPERT, None, AXIS_MODEL)),
    (r"experts/down_proj/kernel", P(AXIS_EXPERT, AXIS_MODEL, None)),
    (r"(shared_expert|shared)/(gate_proj|up_proj)/kernel", P(None, AXIS_MODEL)),
    (r"(shared_expert|shared)/down_proj/kernel", P(AXIS_MODEL, None)),
    (r"router/kernel", P()),
    *LLAMA_RULES.rules,
])

# KV pages: [layers, 2, pages, kv_heads, page_size, head_dim] — kv heads on
# `model` (must divide), pages replicated within an instance.
KV_PAGES_SPEC = P(None, None, None, AXIS_MODEL, None, None)
# Decode activations: batch on `data`.
BATCH_SPEC = P(AXIS_DATA)


def named_sharding(mesh: Mesh, spec: P) -> NamedSharding:
    return NamedSharding(mesh, spec)


def _flatten_path(path) -> str:
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        else:
            parts.append(str(k))
    return "/".join(parts)


def tree_specs(params: Any, rules: ShardingRules) -> Any:
    """PartitionSpec pytree matching `params` by path."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: rules.spec_for(_flatten_path(path)), params)


def shard_params(params: Any, mesh: Mesh, rules: ShardingRules) -> Any:
    """Device-put a param pytree with rule-derived shardings."""
    specs = tree_specs(params, rules)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs)
