"""Mesh + sharding utilities for the TPU engine plane.

The reference's engine (empty submodule) ran TP/DP/EP via HCCL collectives;
here parallelism is expressed as a `jax.sharding.Mesh` with named axes and
GSPMD shardings — XLA inserts the collectives over ICI (SURVEY.md §2.12).
"""

from .mesh import MeshConfig, build_mesh, axis_size
from .sharding import (
    ShardingRules,
    LLAMA_RULES,
    MOE_RULES,
    named_sharding,
    shard_params,
)

__all__ = [
    "MeshConfig", "build_mesh", "axis_size",
    "ShardingRules", "LLAMA_RULES", "MOE_RULES",
    "named_sharding", "shard_params",
]
