"""Device-mesh construction.

Axis vocabulary (fixed across the framework):
- ``data``   — data parallel (replica) axis; maps to the reference's
  `dp_size` in InstanceMetaInfo (`xllm_rpc_service.proto:40-43`).
- ``expert`` — expert parallel axis for MoE decode (BASELINE config 4).
- ``seq``    — sequence/context parallel axis (ring attention, §5.7).
- ``model``  — tensor parallel axis (heads / ffn sharding).

A serving instance owns one mesh over its TPU sub-slice; the mesh shape and
axis names are advertised in TpuTopology so the scheduler can place roles
topology-aware (common/types.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_DATA = "data"
AXIS_EXPERT = "expert"
AXIS_PIPE = "pipe"
AXIS_SEQ = "seq"
AXIS_MODEL = "model"
ALL_AXES = (AXIS_DATA, AXIS_EXPERT, AXIS_PIPE, AXIS_SEQ, AXIS_MODEL)


@dataclass
class MeshConfig:
    data: int = 1
    expert: int = 1
    pipe: int = 1
    seq: int = 1
    model: int = 1

    @property
    def shape(self) -> tuple[int, ...]:
        return (self.data, self.expert, self.pipe, self.seq, self.model)

    def num_devices(self) -> int:
        return int(np.prod(self.shape))

    @classmethod
    def for_devices(cls, n: int, tp: Optional[int] = None) -> "MeshConfig":
        """Default layout: all devices on the model (TP) axis unless told
        otherwise — serving decode is latency-bound, and TP over ICI is the
        latency-optimal first choice (scaling-book recipe)."""
        tp = tp or n
        assert n % tp == 0, f"{n} devices not divisible by tp={tp}"
        return cls(data=n // tp, model=tp)


def build_mesh(config: Optional[MeshConfig] = None,
               devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    config = config or MeshConfig.for_devices(len(devices))
    if config.num_devices() != len(devices):
        raise ValueError(
            f"mesh {config.shape} needs {config.num_devices()} devices, "
            f"got {len(devices)}")
    arr = np.array(devices).reshape(config.shape)
    return Mesh(arr, ALL_AXES)


def axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape[axis]
