"""Pallas TPU multi-query paged attention (speculative-verify kernel).

The spec-verify forward attends a SHORT query block (last accepted token
+ drafts, S_q <= ~32) per sequence against that sequence's paged KV. The
XLA fallback gathers every sequence's full page span to dense tensors —
memory-bound at large batch*context. This kernel walks only the occupied
pages with the same double-buffered page-DMA structure as the decode
kernel (`pallas_paged_attention.py`), adding a per-query causal offset:
query s (at absolute position prefix + s) may attend key positions
<= prefix + s.

Assumes the block's own K/V have already been written into the pages
(true in `prefill_from_embeddings`: `write_prefill_kv` runs before
attention), so the pages hold the full context = prefix + block and the
kernel never needs the separate suffix K/V tensors.

Gated OFF by default (XLLM_MQ_PALLAS=1 to enable on TPU): correctness is
interpret-verified on CPU; Mosaic compilation must be validated on a real
chip before it becomes a default path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_page_dma import (
    NEG_INF as _NEG_INF,
    chunked_page_walk,
    flash_accumulate,
    masked_kv_f32,
    page_chunk_size,
    tpu_compiler_params,
)


def _kernel(page_table_ref, prefix_ref, block_ref,    # scalar prefetch
            q_ref,                                    # [1, Sq, n_q, hd]
            k_hbm, v_hbm,                             # pools in HBM/ANY
            o_ref,                                    # [1, Sq, n_q, hd]
            k_buf, v_buf, sems, m_scr, l_scr, acc_scr,
            *, page_size: int, n_kv: int, group: int, scale: float,
            max_pages: int, chunk: int, s_q: int,
            pipeline_rows: bool):
    b = pl.program_id(0)
    nb = pl.num_programs(0)
    prefix = prefix_ref[b]
    blk = block_ref[b]                 # valid queries in this row's block
    ctx = prefix + blk                 # total written context

    def n_pages_of(row):
        row_ctx = prefix_ref[row] + block_ref[row]
        return jnp.minimum(pl.cdiv(row_ctx, page_size), max_pages)

    m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
    l_scr[...] = jnp.zeros_like(l_scr)
    acc_scr[...] = jnp.zeros_like(acc_scr)

    def compute(c, slot):
        span = chunk * page_size
        start = c * span
        # Query s sits at absolute position prefix + s; it may attend
        # keys at positions <= prefix + s. Rows are (s, g) flattened.
        key_pos = start + jax.lax.broadcasted_iota(
            jnp.int32, (s_q * group, span), 1)
        q_row_pos = prefix + jax.lax.broadcasted_iota(
            jnp.int32, (s_q * group, span), 0) // group
        mask = key_pos <= q_row_pos
        for kv in range(n_kv):
            # [Sq, G, hd] -> [Sq*G, hd] query rows for this KV head.
            qh = q_ref[0, :, kv * group:(kv + 1) * group, :] \
                .astype(jnp.float32).reshape(s_q * group, -1) * scale
            k, v = masked_kv_f32(k_buf, v_buf, slot, kv, start, ctx)
            s = jax.lax.dot_general(
                qh, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)   # [Sq*G, span]
            s = jnp.where(mask, s, _NEG_INF)
            flash_accumulate(
                slice(kv * s_q * group, (kv + 1) * s_q * group),
                s, v, m_scr, l_scr, acc_scr)

    chunked_page_walk(page_table_ref, b, nb, n_pages_of(b), n_pages_of,
                      chunk, k_hbm, v_hbm, k_buf, v_buf, sems, compute,
                      pipeline_rows)

    l = jnp.maximum(l_scr[:, :1], 1e-9)
    out = acc_scr[...] / l                         # [n_kv*Sq*G, hd]
    n_q = o_ref.shape[2]
    hd = o_ref.shape[3]
    # rows are (kv, s, g): reshape back to [Sq, n_q, hd].
    out = out.reshape(n_kv, s_q, group, hd).transpose(1, 0, 2, 3) \
        .reshape(s_q, n_q, hd)
    o_ref[0] = out.astype(o_ref.dtype)


def mq_paged_attention_pallas(q: jax.Array, k_pages: jax.Array,
                              v_pages: jax.Array, page_table: jax.Array,
                              prefix_lens: jax.Array,
                              block_lens: jax.Array,
                              interpret: bool = False) -> jax.Array:
    """q: [B, Sq, n_q, hd] (short block per sequence); k/v_pages:
    [pages, n_kv, ps, hd] holding prefix AND block KV; page_table:
    [B, max_pages]; prefix_lens/block_lens: [B]. Returns [B, Sq, n_q, hd]
    — causal over absolute positions, identical to the XLA
    prefill_attention reference (tested).

    XLLM_PAGE_CHUNK / XLLM_PAGE_PIPELINE are resolved here, OUTSIDE
    jit, and passed static — a shape-keyed cache would silently pin the
    first-traced variant."""
    import os

    return _mq_impl(q, k_pages, v_pages, page_table, prefix_lens,
                    block_lens, chunk=page_chunk_size(page_table.shape[1]),
                    pipeline_rows=os.environ.get(
                        "XLLM_PAGE_PIPELINE", "") == "row",
                    interpret=interpret)


@functools.partial(jax.jit, static_argnames=("chunk", "pipeline_rows",
                                             "interpret"))
def _mq_impl(q, k_pages, v_pages, page_table, prefix_lens, block_lens, *,
             chunk: int, pipeline_rows: bool = False,
             interpret: bool = False) -> jax.Array:
    B, s_q, n_q, hd = q.shape
    _, n_kv, page_size, _ = k_pages.shape
    max_pages = page_table.shape[1]
    group = n_q // n_kv
    scale = 1.0 / (hd ** 0.5)
    kernel = functools.partial(_kernel, page_size=page_size, n_kv=n_kv,
                               group=group, scale=scale,
                               max_pages=max_pages, chunk=chunk, s_q=s_q,
                               pipeline_rows=pipeline_rows)
    rows = n_kv * s_q * group
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, s_q, n_q, hd), lambda b, pt, pf, bl: (b, 0, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((1, s_q, n_q, hd),
                               lambda b, pt, pf, bl: (b, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, chunk, n_kv, page_size, hd), k_pages.dtype),
            pltpu.VMEM((2, chunk, n_kv, page_size, hd), v_pages.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
            pltpu.VMEM((rows, 128), jnp.float32),   # m
            pltpu.VMEM((rows, 128), jnp.float32),   # l
            pltpu.VMEM((rows, hd), jnp.float32),    # acc
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, s_q, n_q, hd), q.dtype),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(page_table, prefix_lens, block_lens, q, k_pages, v_pages)
