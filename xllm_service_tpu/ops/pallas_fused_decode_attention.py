"""Fused decode step: KV write + paged attention in ONE Pallas kernel.

The unfused decode path does, per layer: an XLA scatter of the new token's
K/V into the paged pool (`ops/attention.write_decode_kv`), then the paged
attention kernel re-reads those pages from HBM. That costs an extra HBM
round-trip per layer per step (read-modify-write of the touched page plus
the kernel's re-read) on the most bandwidth-bound program in the engine
(SURVEY.md §7.3 hard part #2 — decode is weights+KV bound).

This kernel fuses the append:
- the new token's K/V arrive as VMEM operands ``[B, n_kv, hd]``;
- the append is a whole-page read-modify-write: at grid-step start the
  kernel DMAs the tail page ``page_table[b, pos // ps]`` (where ``pos =
  context_lens[b] - 1``; context_lens INCLUDE the new token) into VMEM —
  Mosaic tiles HBM memrefs (8,128) over (ps, hd) too, so a single-slot
  [n_kv, 1, hd] window can't be DMA'd directly, but page-granular slices
  cut only the major dim and are always aligned. After the page walk the
  new row is spliced in with a vector select and the page DMA'd back
  (~2x 64KB per step vs the multi-MB walk — noise, and it replaces the
  separate XLA scatter's own read-modify-write);
- attention walks only the *previous* ``ctx - 1`` tokens from HBM pages
  (the write-back can race the walk's read of the same page — the
  written slot is masked out of every read, so a torn read is never
  used; the rest of the written page is bit-identical to what was read);
- the new token's attention contribution is computed directly from the
  VMEM operands and merged into the online softmax at the end — exact,
  and it never waits on the HBM write;
- the write-back is waited at the end of the grid step; the pools are
  input/output-aliased so the append is in place.
- The tail page is PRIVATE to the sequence (the engine allocates a fresh
  page at each boundary and prefix-cache sharing only covers full hash
  blocks), so the RMW never clobbers another sequence's data; inactive
  rows RMW the garbage page 0, where torn whole-page writes are
  harmless (nothing reads it).

Per-sequence pages are disjoint (the engine owns the page allocator), so
concurrent grid steps never write the same live slot; padded/finished
rows redirect to the reserved garbage page 0, where torn writes are
harmless (same invariant as `write_decode_kv(mode="drop")`).

Gated behind XLLM_KV_WRITEBACK=fused (see `ops/attention.decode_attention_step`)
until Mosaic-validated + measured on a real chip; interpret-mode parity is
covered by tests/test_pallas_attention.py (test_fused_decode_step_*).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_page_dma import (
    NEG_INF as _NEG_INF,
    chunked_page_walk,
    flash_accumulate,
    masked_kv_f32,
    page_chunk_size,
    tpu_compiler_params,
)


def _kernel(page_table_ref, context_lens_ref,   # scalar prefetch (SMEM)
            q_ref,                              # VMEM block [1, n_q, hd]
            k_new_ref, v_new_ref,               # VMEM blocks [1, n_kv, hd]
            k_in, v_in,                         # full pools (HBM/ANY, aliased)
            o_ref,                              # VMEM block [1, n_q, hd]
            k_out, v_out,                       # same buffers as k_in/v_in
            k_buf, v_buf, sems, wsems,          # scratch
            k_pg, v_pg,                         # tail-page RMW staging
            m_scr, l_scr, acc_scr,
            *, page_size: int, n_kv: int, group: int, scale: float,
            max_pages: int, chunk: int, pipeline_rows: bool):
    b = pl.program_id(0)
    nb = pl.num_programs(0)
    ctx = context_lens_ref[b]
    pos = jnp.maximum(ctx - 1, 0)               # the new token's position
    # Kick the tail-page READ DMAs first so they overlap the page walk
    # (see module docstring: whole-page RMW is the only tiling-aligned
    # way to land one token's row in the (8,128)-tiled HBM pool).
    wpage = page_table_ref[b, jnp.minimum(pos // page_size, max_pages - 1)]
    slot = pos % page_size
    pltpu.make_async_copy(k_in.at[wpage], k_pg, wsems.at[0, 0]).start()
    pltpu.make_async_copy(v_in.at[wpage], v_pg, wsems.at[0, 1]).start()

    ctx_prev = pos                              # tokens already in the pool

    def n_pages_of(row):
        # The walk covers only the PREVIOUS tokens (ctx - 1); the new
        # token's contribution merges from VMEM below. Cross-row
        # prefetch uses the same rule for row b+1, so its guard set
        # matches the waits row b+1 will issue.
        prev = jnp.maximum(context_lens_ref[row] - 1, 0)
        return jnp.minimum(pl.cdiv(prev, page_size), max_pages)

    m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
    l_scr[...] = jnp.zeros_like(l_scr)
    acc_scr[...] = jnp.zeros_like(acc_scr)

    q = q_ref[0].astype(jnp.float32) * scale           # [n_q, hd]

    def compute(c, slot_):
        span = chunk * page_size
        start = c * span
        token_pos = start + jax.lax.broadcasted_iota(
            jnp.int32, (1, span), 1)
        # Bound the walk at ctx_prev: the new token's slot (possibly
        # racing the in-flight append DMA) is masked out of every
        # read, both in scores and in the V zeroing inside
        # masked_kv_f32.
        mask = token_pos < ctx_prev
        for kv in range(n_kv):
            qh = q[kv * group:(kv + 1) * group, :]     # [G, hd]
            k, v = masked_kv_f32(k_buf, v_buf, slot_, kv, start,
                                 ctx_prev)
            s = jax.lax.dot_general(
                qh, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)    # [G, span]
            s = jnp.where(mask, s, _NEG_INF)
            flash_accumulate(slice(kv * group, (kv + 1) * group),
                             s, v, m_scr, l_scr, acc_scr)

    chunked_page_walk(page_table_ref, b, nb, n_pages_of(b), n_pages_of,
                      chunk, k_in, v_in, k_buf, v_buf, sems, compute,
                      pipeline_rows)

    # Merge the new token's contribution straight from VMEM (it is always
    # attended: position ctx-1 < ctx).
    k_new = k_new_ref[0].astype(jnp.float32)           # [n_kv, hd]
    v_new = v_new_ref[0].astype(jnp.float32)
    for kv in range(n_kv):
        rows = slice(kv * group, (kv + 1) * group)
        qh = q[rows, :]                                # [G, hd]
        s = jax.lax.dot_general(
            qh, k_new[kv:kv + 1], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [G, 1]
        flash_accumulate(rows, s, v_new[kv:kv + 1], m_scr, l_scr, acc_scr)

    l = jnp.maximum(l_scr[:, :1], 1e-9)
    o_ref[0] = (acc_scr[...] / l).astype(o_ref.dtype)

    # Splice the new row into the staged tail page and write it back.
    pltpu.make_async_copy(k_in.at[wpage], k_pg, wsems.at[0, 0]).wait()
    pltpu.make_async_copy(v_in.at[wpage], v_pg, wsems.at[0, 1]).wait()
    sel = jax.lax.broadcasted_iota(
        jnp.int32, (1, page_size, 1), 1) == slot
    k_pg[...] = jnp.where(sel, k_new_ref[0][:, None, :], k_pg[...])
    v_pg[...] = jnp.where(sel, v_new_ref[0][:, None, :], v_pg[...])
    pltpu.make_async_copy(k_pg, k_out.at[wpage], wsems.at[1, 0]).start()
    pltpu.make_async_copy(v_pg, v_out.at[wpage], wsems.at[1, 1]).start()
    # The aliased pools must hold the append when this grid step retires.
    pltpu.make_async_copy(k_pg, k_out.at[wpage], wsems.at[1, 0]).wait()
    pltpu.make_async_copy(v_pg, v_out.at[wpage], wsems.at[1, 1]).wait()


def fused_decode_attention_pallas(
        q: jax.Array,                    # [B, n_q, hd]
        k_new: jax.Array,                # [B, n_kv, hd]
        v_new: jax.Array,                # [B, n_kv, hd]
        k_pages: jax.Array,              # [pages, n_kv, ps, hd]
        v_pages: jax.Array,
        page_table: jax.Array,           # [B, max_pages] i32
        context_lens: jax.Array,         # [B] i32, INCLUDING the new token
        interpret: bool = False,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Returns (attn_out [B, n_q, hd], k_pages, v_pages) with the new
    token's K/V appended in place (pools are donated via aliasing).

    XLLM_PAGE_CHUNK / XLLM_PAGE_PIPELINE are resolved here, OUTSIDE jit,
    and passed static — a shape-keyed cache would silently pin the
    first-traced variant."""
    import os

    return _fused_impl(q, k_new, v_new, k_pages, v_pages, page_table,
                       context_lens,
                       chunk=page_chunk_size(page_table.shape[1]),
                       pipeline_rows=os.environ.get(
                           "XLLM_PAGE_PIPELINE", "") == "row",
                       interpret=interpret)


@functools.partial(jax.jit, static_argnames=("chunk", "pipeline_rows",
                                             "interpret"))
def _fused_impl(q, k_new, v_new, k_pages, v_pages, page_table,
                context_lens, *, chunk: int, pipeline_rows: bool = False,
                interpret: bool = False):
    B, n_q, hd = q.shape
    _, n_kv, page_size, _ = k_pages.shape
    max_pages = page_table.shape[1]
    group = n_q // n_kv
    scale = 1.0 / (hd ** 0.5)
    kernel = functools.partial(_kernel, page_size=page_size, n_kv=n_kv,
                               group=group, scale=scale,
                               max_pages=max_pages, chunk=chunk,
                               pipeline_rows=pipeline_rows)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, n_q, hd), lambda b, pt, cl: (b, 0, 0)),
            pl.BlockSpec((1, n_kv, hd), lambda b, pt, cl: (b, 0, 0)),
            pl.BlockSpec((1, n_kv, hd), lambda b, pt, cl: (b, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),   # k pool stays in HBM
            pl.BlockSpec(memory_space=pl.ANY),   # v pool stays in HBM
        ],
        out_specs=[
            pl.BlockSpec((1, n_q, hd), lambda b, pt, cl: (b, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, chunk, n_kv, page_size, hd), k_pages.dtype),
            pltpu.VMEM((2, chunk, n_kv, page_size, hd), v_pages.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
            pltpu.SemaphoreType.DMA((2, 2)),     # tail-page read/write (k,v)
            pltpu.VMEM((n_kv, page_size, hd), k_pages.dtype),  # k_pg
            pltpu.VMEM((n_kv, page_size, hd), v_pages.dtype),  # v_pg
            pltpu.VMEM((n_q, 128), jnp.float32),   # m
            pltpu.VMEM((n_q, 128), jnp.float32),   # l
            pltpu.VMEM((n_q, hd), jnp.float32),    # acc
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, n_q, hd), q.dtype),
            jax.ShapeDtypeStruct(k_pages.shape, k_pages.dtype),
            jax.ShapeDtypeStruct(v_pages.shape, v_pages.dtype),
        ],
        # Flattened operand order: (page_table, context_lens, q, k_new,
        # v_new, k_pages, v_pages) -> pools at 5/6 alias outputs 1/2.
        input_output_aliases={5: 1, 6: 2},
        compiler_params=tpu_compiler_params(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(page_table, context_lens, q, k_new, v_new, k_pages, v_pages)
