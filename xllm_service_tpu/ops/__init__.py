"""TPU compute ops: attention (dense prefill + paged decode, XLA and Pallas
paths), rotary embeddings, normalization.

The decode paged-attention kernel is the perf-critical op (SURVEY.md §7.3
item 2: "Pallas ragged paged-attention kernel quality drives the tok/s/chip
north star").
"""

from .attention import (
    rms_norm,
    apply_rope,
    prefill_attention,
    paged_attention_xla,
    write_prefill_kv,
    write_decode_kv,
    decode_attention_step,
)

__all__ = [
    "rms_norm", "apply_rope", "prefill_attention", "paged_attention_xla",
    "write_prefill_kv", "write_decode_kv", "decode_attention_step",
]
