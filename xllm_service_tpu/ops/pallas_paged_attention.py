"""Pallas TPU paged-attention decode kernel.

The engine's hottest op (SURVEY.md §7.3: "Pallas ragged paged-attention
kernel quality drives the tok/s/chip north star"). One query token per
sequence attends over that sequence's KV pages, located via its page table.

Design (v2 — manual double-buffered DMA):
- grid = (batch,). K/V pools stay in HBM (`memory_space=ANY`); the kernel
  walks only the pages the sequence actually occupies (`cdiv(ctx, ps)` —
  a *dynamic* trip count, unlike a grid dimension) and DMAs each page into
  a 2-slot VMEM scratch ring, prefetching page i+1 while computing page i.
- page table + context lengths are scalar-prefetch operands (SMEM) so DMA
  source addresses are computable before compute starts.
- online-softmax accumulation (flash-style m/l/acc) in VMEM scratch; GQA
  via a static loop over KV heads with G query rows each.
- KV page layout ``[num_pages, n_kv, page_size, head_dim]``: one page is a
  contiguous (n_kv, ps, hd) block whose minor dims match the bf16
  (16, 128) tile.

vs the v1 grid-over-pages version: no DMA for garbage pages past the
context length (the old version fetched all `max_pages` table slots), and
~B× fewer grid steps.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_page_dma import (
    NEG_INF as _NEG_INF,
    chunked_page_walk,
    flash_accumulate,
    masked_kv_f32,
    page_chunk_size,
    tpu_compiler_params,
)


def _kernel(page_table_ref, context_lens_ref,   # scalar prefetch (SMEM)
            q_ref,                              # VMEM block [1, n_q, hd]
            k_hbm, v_hbm,                       # full pools in HBM/ANY
            o_ref,                              # VMEM block [1, n_q, hd]
            k_buf, v_buf, sems,                 # scratch: 2-slot chunk ring
            m_scr, l_scr, acc_scr,
            *, page_size: int, n_kv: int, group: int, scale: float,
            max_pages: int, chunk: int, pipeline_rows: bool,
            softcap: float, window: int):
    b = pl.program_id(0)
    nb = pl.num_programs(0)
    ctx = context_lens_ref[b]

    def n_pages_of(row):
        return jnp.minimum(pl.cdiv(context_lens_ref[row], page_size),
                           max_pages)

    m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
    l_scr[...] = jnp.zeros_like(l_scr)
    acc_scr[...] = jnp.zeros_like(acc_scr)

    def compute(c, slot):
        span = chunk * page_size
        start = c * span
        token_pos = start + jax.lax.broadcasted_iota(
            jnp.int32, (1, span), 1)
        mask = token_pos < ctx
        if window > 0:
            # gemma-2 sliding window: the query sits at position ctx-1,
            # so visible keys are >= ctx - window (matches the XLA path).
            mask &= token_pos >= ctx - window
        q = q_ref[0].astype(jnp.float32) * scale           # [n_q, hd]
        for kv in range(n_kv):
            qh = q[kv * group:(kv + 1) * group, :]         # [G, hd]
            k, v = masked_kv_f32(k_buf, v_buf, slot, kv, start, ctx)
            s = jax.lax.dot_general(
                qh, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)        # [G, span]
            if softcap > 0.0:
                s = softcap * jnp.tanh(s / softcap)
            s = jnp.where(mask, s, _NEG_INF)
            flash_accumulate(slice(kv * group, (kv + 1) * group),
                             s, v, m_scr, l_scr, acc_scr)

    if window > 0:
        # Sliding window: pages wholly below ctx - window are never
        # visible — start the walk at the first visible page's chunk.
        def c_lo_of(row):
            first = jnp.maximum(context_lens_ref[row] - window, 0)
            return (first // page_size) // chunk

        c_lo, c_lo_fn = c_lo_of(b), c_lo_of
    else:
        c_lo, c_lo_fn = None, None

    chunked_page_walk(page_table_ref, b, nb, n_pages_of(b), n_pages_of,
                      chunk, k_hbm, v_hbm, k_buf, v_buf, sems, compute,
                      pipeline_rows, c_lo=c_lo, c_lo_of=c_lo_fn)

    l = jnp.maximum(l_scr[:, :1], 1e-9)
    o_ref[0] = (acc_scr[...] / l).astype(o_ref.dtype)


def paged_attention_pallas(q: jax.Array, k_pages: jax.Array,
                           v_pages: jax.Array, page_table: jax.Array,
                           context_lens: jax.Array,
                           interpret: bool = False,
                           scale: float | None = None,
                           softcap: float = 0.0,
                           window: int = 0) -> jax.Array:
    """q: [B, n_q, hd]; k/v_pages: [pages, n_kv, ps, hd];
    page_table: [B, max_pages] i32; context_lens: [B] i32 (incl. the new
    token, whose K/V must already be written). Returns [B, n_q, hd].

    scale/softcap/window cover the gemma-2 extras (explicit query scale,
    score soft-capping, sliding window) so that family decodes through
    this kernel instead of the full-span XLA gather.

    Env knobs are resolved HERE (outside jit) and passed as static args —
    a jit cache keyed only on shapes would silently pin the first-traced
    variant for the whole process, defeating in-process A/Bs and tests.
    """
    import os

    chunk = page_chunk_size(page_table.shape[1])
    # Cross-row DMA pipelining (see _kernel): XLLM_PAGE_PIPELINE=row
    # enables; default off until the on-chip A/B proves it.
    pipeline_rows = os.environ.get("XLLM_PAGE_PIPELINE", "") == "row"
    return _paged_attention_impl(q, k_pages, v_pages, page_table,
                                 context_lens, chunk=chunk,
                                 pipeline_rows=pipeline_rows,
                                 scale=(float(scale)
                                        if scale is not None else None),
                                 softcap=float(softcap),
                                 window=int(window),
                                 interpret=interpret)


@functools.partial(jax.jit, static_argnames=("chunk", "pipeline_rows",
                                             "scale", "softcap", "window",
                                             "interpret"))
def _paged_attention_impl(q: jax.Array, k_pages: jax.Array,
                          v_pages: jax.Array, page_table: jax.Array,
                          context_lens: jax.Array, *, chunk: int,
                          pipeline_rows: bool,
                          scale: float | None = None,
                          softcap: float = 0.0, window: int = 0,
                          interpret: bool = False) -> jax.Array:
    B, n_q, hd = q.shape
    _, n_kv, page_size, _ = k_pages.shape
    max_pages = page_table.shape[1]
    group = n_q // n_kv
    if scale is None:
        scale = 1.0 / (hd ** 0.5)

    kernel = functools.partial(_kernel, page_size=page_size, n_kv=n_kv,
                               group=group, scale=scale,
                               max_pages=max_pages, chunk=chunk,
                               pipeline_rows=pipeline_rows,
                               softcap=softcap, window=window)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, n_q, hd), lambda b, pt, cl: (b, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),   # k pool stays in HBM
            pl.BlockSpec(memory_space=pl.ANY),   # v pool stays in HBM
        ],
        out_specs=pl.BlockSpec((1, n_q, hd), lambda b, pt, cl: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, chunk, n_kv, page_size, hd), k_pages.dtype),
            pltpu.VMEM((2, chunk, n_kv, page_size, hd), v_pages.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
            pltpu.VMEM((n_q, 128), jnp.float32),   # m
            pltpu.VMEM((n_q, 128), jnp.float32),   # l
            pltpu.VMEM((n_q, hd), jnp.float32),    # acc
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, n_q, hd), q.dtype),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(page_table, context_lens, q, k_pages, v_pages)
