"""Pallas TPU paged-attention decode kernel.

The engine's hottest op (SURVEY.md §7.3: "Pallas ragged paged-attention
kernel quality drives the tok/s/chip north star"). One query token per
sequence attends over that sequence's KV pages, located via its page table.

Design (vs the XLA gather fallback in ops/attention.py):
- grid = (batch, max_pages); the page table is a **scalar-prefetch** operand,
  so each grid step's K/V page block is DMA'd straight from its physical
  page (``index_map`` reads ``page_table[b, p]``) with Pallas' automatic
  double-buffering — no [B, T, heads, hd] gather materialization in HBM.
- online-softmax accumulation in VMEM scratch across the page dimension
  (flash-attention style m/l/acc carry), GQA handled by a static loop over
  KV heads with G query rows each.
- KV page layout: ``[num_pages, n_kv, page_size, head_dim]`` — the per-page
  block (1, n_kv, ps, hd) keeps (page_size, head_dim) as the minor dims,
  matching the bf16 (16, 128) tile.

Pages past a sequence's context length contribute nothing (masked; their
page-table entries point at the reserved garbage page 0).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _kernel(page_table_ref, context_lens_ref,   # scalar prefetch
            q_ref, k_ref, v_ref,                # blocks
            o_ref,                              # output block
            m_scr, l_scr, acc_scr,              # VMEM scratch
            *, page_size: int, n_kv: int, group: int, scale: float):
    b = pl.program_id(0)
    p = pl.program_id(1)

    @pl.when(p == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    ctx = context_lens_ref[b]
    start = p * page_size

    @pl.when(start < ctx)
    def _compute():
        # Valid tokens in this page.
        token_pos = start + jax.lax.broadcasted_iota(jnp.int32,
                                                     (1, page_size), 1)
        mask = (token_pos < ctx)
        q = q_ref[0].astype(jnp.float32) * scale          # [n_q, hd]
        for kv in range(n_kv):
            qh = q[kv * group:(kv + 1) * group, :]        # [G, hd]
            k = k_ref[0, kv].astype(jnp.float32)          # [ps, hd]
            v = v_ref[0, kv].astype(jnp.float32)          # [ps, hd]
            s = jax.lax.dot_general(
                qh, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)       # [G, ps]
            s = jnp.where(mask, s, _NEG_INF)
            rows = slice(kv * group, (kv + 1) * group)
            m_prev = m_scr[rows, :1]                      # [G, 1]
            l_prev = l_scr[rows, :1]
            m_cur = jnp.max(s, axis=1, keepdims=True)     # [G, 1]
            m_new = jnp.maximum(m_prev, m_cur)
            alpha = jnp.exp(m_prev - m_new)
            ps_ = jnp.exp(s - m_new)                      # [G, ps]
            l_new = l_prev * alpha + jnp.sum(ps_, axis=1, keepdims=True)
            acc_scr[rows, :] = acc_scr[rows, :] * alpha + \
                jax.lax.dot_general(ps_, v, (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            m_scr[rows, :1] = m_new
            l_scr[rows, :1] = l_new

    @pl.when(p == pl.num_programs(1) - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:, :1], 1e-9)
        o_ref[0] = (acc_scr[...] / l).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_attention_pallas(q: jax.Array, k_pages: jax.Array,
                           v_pages: jax.Array, page_table: jax.Array,
                           context_lens: jax.Array,
                           interpret: bool = False) -> jax.Array:
    """q: [B, n_q, hd]; k/v_pages: [pages, n_kv, ps, hd];
    page_table: [B, max_pages] i32; context_lens: [B] i32 (incl. the new
    token, whose K/V must already be written). Returns [B, n_q, hd]."""
    B, n_q, hd = q.shape
    _, n_kv, page_size, _ = k_pages.shape
    max_pages = page_table.shape[1]
    group = n_q // n_kv
    scale = 1.0 / (hd ** 0.5)

    kernel = functools.partial(_kernel, page_size=page_size, n_kv=n_kv,
                               group=group, scale=scale)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, max_pages),
        in_specs=[
            pl.BlockSpec((1, n_q, hd), lambda b, p, pt, cl: (b, 0, 0)),
            pl.BlockSpec((1, n_kv, page_size, hd),
                         lambda b, p, pt, cl: (pt[b, p], 0, 0, 0)),
            pl.BlockSpec((1, n_kv, page_size, hd),
                         lambda b, p, pt, cl: (pt[b, p], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, n_q, hd), lambda b, p, pt, cl: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((n_q, 128), jnp.float32),   # m
            pltpu.VMEM((n_q, 128), jnp.float32),   # l
            pltpu.VMEM((n_q, hd), jnp.float32),    # acc
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, n_q, hd), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )(page_table, context_lens, q, k_pages, v_pages)
