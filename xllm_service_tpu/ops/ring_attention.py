"""Ring attention: causal self-attention with the sequence sharded over a
mesh axis (context parallelism for long-context prefill; SURVEY.md §5.7).

Mechanism (blockwise ring, flash-style): each device holds one contiguous
sequence chunk of Q/K/V. K/V chunks rotate around the ring with
`lax.ppermute` over ICI; every hop each device accumulates its local Q's
attention over the visiting K/V chunk with an online-softmax merge. Causal
structure across chunks: a visiting chunk earlier in the sequence is fully
attended, the device's own chunk gets the intra-chunk causal mask, and
later chunks are skipped (their contribution is masked to zero weight).

FLOP note: all n ring hops run the same einsum shape (static shapes for
XLA); later-chunk hops are masked rather than skipped — the usual tradeoff
for compiler-friendly control flow.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
try:
    from jax import shard_map
except ImportError:   # pre-0.5 spelling of the same API
    from jax.experimental.shard_map import shard_map

_NEG_INF = -1e30


def _ring_attention_local(q: jax.Array, k: jax.Array, v: jax.Array,
                          axis_name: str,
                          scale: float | None = None) -> jax.Array:
    """Per-device body. q: [B, S_loc, H, hd]; k/v: [B, S_loc, H_kv, hd]
    (GQA: H_kv may divide H — K/V rotate around the ring at their small
    head count and are repeated only at use, so ICI traffic stays at the
    KV size, not the query size)."""
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    B, S, H, hd = q.shape
    n_rep = H // k.shape[2]
    if scale is None:
        scale = 1.0 / (hd ** 0.5)
    qf = q.astype(jnp.float32) * scale

    rows = jnp.arange(S)[:, None]
    cols = jnp.arange(S)[None, :]

    def hop(carry, step):
        k_cur, v_cur, m, l, acc = carry
        src = (my_idx - step) % n        # which chunk is visiting
        k_use = jnp.repeat(k_cur, n_rep, axis=2) if n_rep > 1 else k_cur
        v_use = jnp.repeat(v_cur, n_rep, axis=2) if n_rep > 1 else v_cur
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, k_use.astype(jnp.float32))
        # Causal structure across chunks.
        intra = jnp.where(cols <= rows, 0.0, _NEG_INF)       # same chunk
        full = jnp.zeros((S, S), jnp.float32)                # earlier chunk
        none = jnp.full((S, S), _NEG_INF)                    # later chunk
        mask = jnp.where(src == my_idx, intra,
                         jnp.where(src < my_idx, full, none))
        s = s + mask[None, None, :, :]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_cur)
        # Guard fully-masked hops (exp(-inf - -inf)).
        p = jnp.exp(s - m_new)
        p = jnp.where(s <= _NEG_INF / 2, 0.0, p)
        alpha = jnp.exp(m - m_new)
        alpha = jnp.where(m <= _NEG_INF / 2, 0.0, alpha)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        # acc: [B, S, H, hd]; alpha: [B, H, S, 1] -> align axes.
        alpha_b = jnp.swapaxes(alpha[..., 0], 1, 2)[..., None]  # [B, S, H, 1]
        acc_new = acc * alpha_b + jnp.swapaxes(
            jnp.einsum("bhqk,bkhd->bhqd", p, v_use.astype(jnp.float32)),
            1, 2)
        # Rotate K/V to the next device on the ring.
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, m_new, l_new, acc_new), None

    # Mark the constant initial carries as device-varying so the scan
    # carry types line up with the ring-permuted outputs. Older jax has
    # no pcast/varying-axes typing — there the carries already unify.
    if hasattr(jax.lax, "pcast"):
        def _vary(x):
            return jax.lax.pcast(x, axis_name, to="varying")
    else:
        def _vary(x):
            return x

    m0 = _vary(jnp.full((B, H, S, 1), _NEG_INF, jnp.float32))
    l0 = _vary(jnp.zeros((B, H, S, 1), jnp.float32))
    acc0 = _vary(jnp.zeros((B, S, H, hd), jnp.float32))
    (k_f, v_f, m, l, acc), _ = jax.lax.scan(
        hop, (k, v, m0, l0, acc0), jnp.arange(n))
    l_b = jnp.swapaxes(l[..., 0], 1, 2)[..., None]          # [B, S, H, 1]
    out = acc / jnp.maximum(l_b, 1e-9)
    return out.astype(q.dtype)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   mesh: Mesh, seq_axis: str = "seq",
                   scale: float | None = None) -> jax.Array:
    """q: [B, S, H, hd], k/v: [B, S, H_kv, hd] (H_kv | H for GQA) with S
    divisible by the seq-axis size; returns causal self-attention output,
    sequence-parallel over `seq_axis`."""
    spec = P(None, seq_axis, None, None)
    fn = shard_map(
        functools.partial(_ring_attention_local, axis_name=seq_axis,
                          scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)
