"""Shared chunked page-DMA scaffolding for the paged-attention Pallas
kernels (decode + multi-query verify): a 2-slot VMEM ring of
`chunk`-page blocks, one async copy per page (pages are non-contiguous
in HBM), waits batched per chunk. Extracted so a fix to the DMA pattern
lands in every kernel at once."""

from __future__ import annotations

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def make_chunk_dma(page_table_ref, b, n_pages, chunk,
                   k_hbm, v_hbm, k_buf, v_buf, sems):
    """Returns (start_chunk(slot, c), wait_chunk(slot, c))."""

    def start_chunk(slot, c):
        base = c * chunk
        for j in range(chunk):
            p = base + j

            @pl.when(p < n_pages)
            def _():
                page = page_table_ref[b, p]
                pltpu.make_async_copy(k_hbm.at[page], k_buf.at[slot, j],
                                      sems.at[slot, 0]).start()
                pltpu.make_async_copy(v_hbm.at[page], v_buf.at[slot, j],
                                      sems.at[slot, 1]).start()

    def wait_chunk(slot, c):
        base = c * chunk
        for j in range(chunk):
            p = base + j

            @pl.when(p < n_pages)
            def _():
                page = page_table_ref[b, p]
                pltpu.make_async_copy(k_hbm.at[page], k_buf.at[slot, j],
                                      sems.at[slot, 0]).wait()
                pltpu.make_async_copy(v_hbm.at[page], v_buf.at[slot, j],
                                      sems.at[slot, 1]).wait()

    return start_chunk, wait_chunk
