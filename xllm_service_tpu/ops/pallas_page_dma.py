"""Shared scaffolding for the paged-attention Pallas kernels (decode,
fused decode-append, multi-query verify):

- `make_chunk_dma`: a 2-slot VMEM ring of `chunk`-page blocks, one async
  copy per page (pages are non-contiguous in HBM), waits batched per
  chunk;
- `masked_kv_f32` / `flash_accumulate`: the per-head chunk read and the
  online-softmax (flash) m/l/acc update.

Extracted so a fix to the DMA pattern or the accumulate numerics lands
in every kernel at once."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def tpu_compiler_params(**kw):
    """TPU Pallas compiler params across the JAX API rename: newer
    releases expose ``pltpu.CompilerParams``, older ones (<= 0.4.x)
    ``pltpu.TPUCompilerParams`` — same fields either way. Every kernel in
    this package builds its ``compiler_params`` through here so the suite
    runs under both spellings."""
    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return cls(**kw)


def page_chunk_size(max_pages: int, default: int = 8) -> int:
    """Pages per double-buffered DMA chunk in the paged-attention
    kernels. Bigger chunks mean fewer, larger DMAs — the decode walk is
    DMA-latency-bound at serving shapes (B rows x ~pages/chunk waits per
    layer), so this is a first-order knob. XLLM_PAGE_CHUNK overrides for
    on-chip A/B; VMEM cost is 4 * chunk * n_kv * ps * hd bytes (two
    k/v double buffers)."""
    import os

    try:
        v = int(os.environ.get("XLLM_PAGE_CHUNK", "") or default)
    except ValueError:
        v = default
    return max(1, min(v, max_pages))


def make_chunk_dma(page_table_ref, b, n_pages, chunk,
                   k_hbm, v_hbm, k_buf, v_buf, sems):
    """Returns (start_chunk(slot, c), wait_chunk(slot, c))."""

    def start_chunk(slot, c):
        base = c * chunk
        for j in range(chunk):
            p = base + j

            @pl.when(p < n_pages)
            def _():
                page = page_table_ref[b, p]
                pltpu.make_async_copy(k_hbm.at[page], k_buf.at[slot, j],
                                      sems.at[slot, 0]).start()
                pltpu.make_async_copy(v_hbm.at[page], v_buf.at[slot, j],
                                      sems.at[slot, 1]).start()

    def wait_chunk(slot, c):
        base = c * chunk
        for j in range(chunk):
            p = base + j

            @pl.when(p < n_pages)
            def _():
                page = page_table_ref[b, p]
                pltpu.make_async_copy(k_hbm.at[page], k_buf.at[slot, j],
                                      sems.at[slot, 0]).wait()
                pltpu.make_async_copy(v_hbm.at[page], v_buf.at[slot, j],
                                      sems.at[slot, 1]).wait()

    return start_chunk, wait_chunk


def chunked_page_walk(page_table_ref, b, nb, n_pages, n_pages_of, chunk,
                      k_hbm, v_hbm, k_buf, v_buf, sems, compute,
                      pipeline_rows: bool, c_lo=None, c_lo_of=None):
    """Run the double-buffered page walk for grid row ``b``, calling
    ``compute(c, slot)`` per chunk.

    pipeline_rows=False: classic within-row prefetch (chunk c+1 loads
    while chunk c computes; each row pays one cold-start DMA stall).

    pipeline_rows=True: rows cooperate — the final chunk (or an empty
    row) prefetches row b+1's FIRST chunk into the free buffer slot,
    hiding the per-row cold-start stall behind the previous row's
    compute. Invariants: every non-empty row runs an EVEN chunk count
    (one masked pad chunk when odd — its DMAs/waits are no-ops via the
    p < n_pages guards and `compute` must mask it), so rows always start
    in slot 0 (relative) and end in slot 1; only row 0 cold-starts
    itself.

    ``n_pages_of(row)`` must return the page count for any row with the
    same semantics used for ``n_pages`` (= n_pages_of(b)).

    ``c_lo`` / ``c_lo_of(row)`` (optional) give the FIRST chunk to walk —
    a sliding-window decode (gemma-2 local layers) never needs pages
    wholly below ctx - window, so the walk can start there instead of
    chunk 0. Slot parity is relative to c_lo, so the cross-row
    invariants are unchanged.
    """
    if c_lo is None:
        c_lo = 0
        c_lo_of = lambda row: 0   # noqa: E731 — trace-time closure
    n_chunks = pl.cdiv(n_pages, chunk) - c_lo   # chunks actually walked
    n_chunks = jnp.maximum(n_chunks, 0)
    start_chunk, wait_chunk = make_chunk_dma(
        page_table_ref, b, n_pages, chunk, k_hbm, v_hbm, k_buf, v_buf,
        sems)

    if not pipeline_rows:
        @pl.when(n_chunks > 0)
        def _run():
            start_chunk(0, c_lo)

            def body(i, _):
                c = c_lo + i
                slot = jax.lax.rem(i, 2)

                @pl.when(i + 1 < n_chunks)
                def _prefetch():
                    start_chunk(1 - slot, c + 1)

                wait_chunk(slot, c)
                compute(c, slot)
                return ()

            # No unroll kwarg: older jax rejects it outright when the
            # trip count is dynamic (and False is the default anyway).
            jax.lax.fori_loop(0, n_chunks, body, ())
        return

    b_next = jnp.minimum(b + 1, nb - 1)
    start_next, _ = make_chunk_dma(
        page_table_ref, b_next, n_pages_of(b_next), chunk, k_hbm, v_hbm,
        k_buf, v_buf, sems)
    c_lo_next = c_lo_of(b_next)
    n_chunks_e = n_chunks + jax.lax.rem(n_chunks, 2)     # pad to even

    @pl.when(b == 0)
    def _cold():
        start_chunk(0, c_lo)

    @pl.when((n_chunks_e == 0) & (b + 1 < nb))
    def _forward_empty_row():
        start_next(0, c_lo_next)

    def body(i, _):
        c = c_lo + i
        slot = jax.lax.rem(i, 2)

        @pl.when(i + 1 < n_chunks_e)
        def _prefetch():
            start_chunk(1 - slot, c + 1)

        @pl.when((i + 1 == n_chunks_e) & (b + 1 < nb))
        def _prefetch_next_row():
            start_next(0, c_lo_next)

        wait_chunk(slot, c)
        compute(c, slot)
        return ()

    jax.lax.fori_loop(0, n_chunks_e, body, ())


# --------------------------------------------------------------- page movers
#
# Device-side movers for the tiered KV-cache data plane (engine/kv_tier.py):
# gather a hash block's pages out of the pool (offload: the gathered buffer
# is downloaded to the host tier off-thread) and scatter a host-restored
# block back into freshly allocated pages (onload, dispatched ahead of the
# prefill that reads them). On TPU the gather runs as a Pallas kernel — one
# async copy per (layer, k/v, page) row, pure DMA, no compute — so the
# block never stages through VMEM-size-limited compute tiles; elsewhere
# (CPU tests, interpret mode off) a plain XLA gather/scatter is identical.


def _pallas_page_mover_on() -> bool:
    """Pallas DMA mover on real TPU backends; XLA gather/scatter fallback
    elsewhere. XLLM_PALLAS_INTERPRET=1 forces the kernel in interpret
    mode (parity tests on CPU)."""
    import os

    if os.environ.get("XLLM_PALLAS_INTERPRET", "") == "1":
        return True
    return jax.default_backend() == "tpu"


def _gather_pages_kernel(ids_ref, pool, out, sem):
    """grid (L, 2, n): one page row per step, pure DMA (ANY→ANY), no
    compute tile — the block never stages through VMEM."""
    li = pl.program_id(0)
    si = pl.program_id(1)
    i = pl.program_id(2)
    cp = pltpu.make_async_copy(pool.at[li, si, ids_ref[i]],
                               out.at[li, si, i], sem)
    cp.start()
    cp.wait()


def _scatter_pages_kernel(ids_ref, blk, pool_in, pool_out, sem):
    """grid (L, 2, n): pool_in aliases pool_out (in-place page writes);
    only the selected page rows move."""
    del pool_in   # aliased with pool_out; pages not written keep their data
    li = pl.program_id(0)
    si = pl.program_id(1)
    i = pl.program_id(2)
    cp = pltpu.make_async_copy(blk.at[li, si, i],
                               pool_out.at[li, si, ids_ref[i]], sem)
    cp.start()
    cp.wait()


def gather_kv_pages(kv, page_ids):
    """kv: [L, 2, num_pages, n_kv, ps, hd]; page_ids: [n] int32 →
    [L, 2, n, n_kv, ps, hd] block buffer (a NEW array; the pool is
    untouched, so the caller can download it off-thread while later
    programs overwrite the pages)."""
    if not _pallas_page_mover_on():
        return kv[:, :, page_ids]
    import os

    L, _, _, n_kv, ps, hd = kv.shape
    n = page_ids.shape[0]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(L, 2, n),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA],
    )
    return pl.pallas_call(
        _gather_pages_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((L, 2, n, n_kv, ps, hd), kv.dtype),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary")),
        interpret=os.environ.get("XLLM_PALLAS_INTERPRET", "") == "1",
    )(page_ids, kv)


def scatter_kv_pages(kv, page_ids, block):
    """Inverse of :func:`gather_kv_pages`: write `block`
    [L, 2, n, n_kv, ps, hd] into the pool at `page_ids`; returns the
    updated pool (callers donate it through their jit wrapper)."""
    block = block.astype(kv.dtype)
    if not _pallas_page_mover_on():
        return kv.at[:, :, page_ids].set(block)
    import os

    L = kv.shape[0]
    n = page_ids.shape[0]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(L, 2, n),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY),
                  pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA],
    )
    return pl.pallas_call(
        _scatter_pages_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(kv.shape, kv.dtype),
        # Flattened operand order (ids, blk, pool): pool at 2 aliases the
        # output — in-place page writes, no pool copy.
        input_output_aliases={2: 0},
        compiler_params=tpu_compiler_params(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary")),
        interpret=os.environ.get("XLLM_PALLAS_INTERPRET", "") == "1",
    )(page_ids, block, kv)


def masked_kv_f32(k_buf, v_buf, slot, kv, start, bound):
    """Read one KV head's chunk from the ring as f32 ``[span, hd]``,
    zeroing V rows at positions >= ``bound``: their probabilities are 0,
    but 0 x garbage from never-DMA'd (or concurrently written) sub-buffers
    must not reach the accumulator (0 x NaN = NaN). Column-oriented iota
    (Mosaic cannot transpose 1-bit vectors)."""
    k = k_buf[slot, :, kv].astype(jnp.float32)
    span = k.shape[0] * k.shape[1]
    k = k.reshape(span, -1)
    v = v_buf[slot, :, kv].astype(jnp.float32).reshape(span, -1)
    vmask = (start + jax.lax.broadcasted_iota(
        jnp.int32, (span, 1), 0)) < bound
    return k, jnp.where(vmask, v, 0.0)


def masked_kv_f32_pos(k_buf, v_buf, slot, kv, pos_col, bound):
    """`masked_kv_f32` for NON-contiguous chunk pages (the CP partial
    kernel walks a compacted list of locally-owned pages, so row
    positions come as an explicit column vector ``pos_col: [span, 1]``
    instead of start+iota)."""
    k = k_buf[slot, :, kv].astype(jnp.float32)
    span = k.shape[0] * k.shape[1]
    k = k.reshape(span, -1)
    v = v_buf[slot, :, kv].astype(jnp.float32).reshape(span, -1)
    return k, jnp.where(pos_col < bound, v, 0.0)


def flash_accumulate(rows, s, v, m_scr, l_scr, acc_scr):
    """Online-softmax update of the (m, l, acc) scratch rows with masked
    scores ``s: [R, span]`` and values ``v: [span, hd]``. Fully-masked
    rows are exact: p is re-zeroed where s is the mask sentinel, so a row
    whose every key is masked in this chunk contributes nothing (without
    the guard, exp(NEG_INF - NEG_INF) = 1 would pollute l/acc)."""
    m_prev = m_scr[rows, :1]
    l_prev = l_scr[rows, :1]
    m_cur = jnp.max(s, axis=1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_new)
    p_ = jnp.exp(s - m_new)
    p_ = jnp.where(s <= NEG_INF / 2, 0.0, p_)
    l_new = l_prev * alpha + jnp.sum(p_, axis=1, keepdims=True)
    acc_scr[rows, :] = acc_scr[rows, :] * alpha + \
        jax.lax.dot_general(p_, v, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    m_scr[rows, :1] = m_new
    l_scr[rows, :1] = l_new
