"""Attention primitives for the paged-KV engine.

Layouts:
- KV pool (per layer): ``k_pages/v_pages: [num_pages, n_kv, page_size, hd]``
  (stacked over layers by the engine: leading ``L`` dim). The
  (page_size, head_dim) minor dims match the bf16 (16, 128) TPU tile so the
  Pallas decode kernel reads whole pages as aligned blocks.
- ``page_tables: [B, max_pages]`` int32 — page ids per sequence, in order.
- ``context_lens: [B]`` int32 — tokens currently in cache per sequence.

Numerics: matmuls in model dtype (bf16 on TPU), softmax in f32.

The XLA paged-attention path below is the portable implementation (runs on
CPU test meshes and compiles well on TPU); `ops/pallas_paged_attention.py`
provides the hand-written TPU kernel and the engine selects per backend.
"""

from __future__ import annotations

import contextlib
import threading

import jax
import jax.numpy as jnp

_NEG_INF = -1e30

# Sequence-parallel prefill context (SURVEY.md §5.7). The engine activates
# this while TRACING its long-prefill program; `prefill_attention` then
# routes the suffix self-attention through the blockwise ring op sharded
# over the mesh's seq axis. Trace-time only — the engine guarantees the
# prompt has no cached prefix on this path (prefix attention would need a
# traced branch, which XLA cannot take on a dynamic prefix_lens).
_sp_ctx = threading.local()


@contextlib.contextmanager
def sequence_parallel_prefill(mesh, seq_axis: str = "seq"):
    prev = getattr(_sp_ctx, "cfg", None)
    _sp_ctx.cfg = (mesh, seq_axis)
    try:
        yield
    finally:
        _sp_ctx.cfg = prev


# Speculative-verify context: the engine sets this while tracing its
# verify program; `prefill_attention` may then route the short query
# block through the multi-query paged Pallas kernel (pages-only read —
# valid because the block KV is written before attention) instead of the
# gather-based XLA path. Requires XLLM_MQ_PALLAS=1 + a TPU backend:
# interpret-verified on CPU, Mosaic compile still to be validated on a
# real chip.
_mq_ctx = threading.local()


@contextlib.contextmanager
def mq_paged_verify():
    prev = getattr(_mq_ctx, "on", None)
    _mq_ctx.on = True
    try:
        yield
    finally:
        _mq_ctx.on = prev


# Context-parallel DECODE context: the engine activates this while tracing
# its decode program when the KV pool is sharded over the seq axis;
# `paged_attention` then routes through the flash-stats-merge CP op.
_cp_ctx = threading.local()


@contextlib.contextmanager
def decode_context_parallel(mesh, seq_axis: str = "seq"):
    prev = getattr(_cp_ctx, "cfg", None)
    _cp_ctx.cfg = (mesh, seq_axis)
    try:
        yield
    finally:
        _cp_ctx.cfg = prev


def rms_norm(x: jax.Array, scale: jax.Array, eps: float) -> jax.Array:
    dtype = x.dtype
    x = x.astype(jnp.float32)
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    return ((x * jax.lax.rsqrt(var + eps)) * scale.astype(jnp.float32)).astype(dtype)


def rope_cos_sin(positions: jax.Array, head_dim: int,
                 theta: float) -> tuple[jax.Array, jax.Array]:
    """positions [...] -> cos/sin [..., head_dim//2] in f32."""
    inv_freq = 1.0 / (theta ** (
        jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    angles = positions.astype(jnp.float32)[..., None] * inv_freq
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jax.Array, positions: jax.Array, theta: float,
               mrope_section: tuple[int, ...] = ()) -> jax.Array:
    """x: [..., n_heads, head_dim]; positions broadcastable to x.shape[:-2].

    With `mrope_section` (Qwen2-VL M-RoPE; half-dim units summing to
    head_dim/2, reference `rope_scaling.mrope_section`), positions may
    instead carry a trailing multimodal axis [..., 3] = (temporal, h, w):
    each half-dim frequency then rotates by ITS section's position
    stream. 1D positions (all axes equal — any text-only sequence, and
    every decode step) take the standard path, which is numerically
    identical for them.
    """
    hd = x.shape[-1]
    if (mrope_section and positions.ndim == x.ndim - 1
            and positions.shape[-1] == len(mrope_section)):
        cos3, sin3 = rope_cos_sin(positions, hd, theta)  # [..., 3, hd/2]
        lo = 0
        cos_parts, sin_parts = [], []
        for k, n in enumerate(mrope_section):
            cos_parts.append(cos3[..., k, lo:lo + n])
            sin_parts.append(sin3[..., k, lo:lo + n])
            lo += n
        cos = jnp.concatenate(cos_parts, axis=-1)        # [..., hd/2]
        sin = jnp.concatenate(sin_parts, axis=-1)
    else:
        cos, sin = rope_cos_sin(positions, hd, theta)    # [..., hd/2]
    cos = cos[..., None, :]                              # broadcast over heads
    sin = sin[..., None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def _repeat_kv(kv: jax.Array, n_rep: int) -> jax.Array:
    """GQA: repeat kv heads to match query heads. kv [..., n_kv, hd]."""
    if n_rep == 1:
        return kv
    return jnp.repeat(kv, n_rep, axis=-2)


# --------------------------------------------------------------- KV writes
def write_prefill_kv(k_pages: jax.Array, v_pages: jax.Array,
                     k: jax.Array, v: jax.Array,
                     page_table: jax.Array, prefix_lens: jax.Array,
                     seq_lens: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Scatter a prefill suffix's K/V into the paged pool.

    k/v: [B, S, n_kv, hd] — token j of row b lands at absolute position
    prefix_lens[b] + j (prefix blocks already cached are skipped). Padding
    positions (j >= seq_lens[b]) are redirected to the reserved garbage
    page 0 so bucket padding never overwrites live cache lines.
    """
    B, S = k.shape[0], k.shape[1]
    page_size = k_pages.shape[2]
    pos = prefix_lens[:, None] + jnp.arange(S)[None, :]          # [B, S]
    valid = jnp.arange(S)[None, :] < seq_lens[:, None]
    max_pages = page_table.shape[1]
    page_idx = jnp.take_along_axis(
        page_table, jnp.clip(pos // page_size, 0, max_pages - 1), axis=1)
    page_idx = jnp.where(valid, page_idx, 0)
    slot = pos % page_size
    p_flat = page_idx.reshape(-1)
    s_flat = slot.reshape(-1)
    # [N, n_kv, hd] scattered at (page, :, slot, :).
    k_pages = k_pages.at[p_flat, :, s_flat, :].set(
        k.reshape(B * S, *k.shape[2:]), mode="drop")
    v_pages = v_pages.at[p_flat, :, s_flat, :].set(
        v.reshape(B * S, *v.shape[2:]), mode="drop")
    return k_pages, v_pages


def write_decode_kv(k_pages: jax.Array, v_pages: jax.Array,
                    k: jax.Array, v: jax.Array,
                    page_table: jax.Array, context_lens: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Append one token's K/V per sequence. k/v: [B, n_kv, hd]; the new token
    occupies position context_lens[b]."""
    page_size = k_pages.shape[2]
    B = k.shape[0]
    page_idx = jnp.take_along_axis(
        page_table, (context_lens // page_size)[:, None], axis=1)[:, 0]
    slot = context_lens % page_size
    k_pages = k_pages.at[page_idx, :, slot, :].set(k, mode="drop")
    v_pages = v_pages.at[page_idx, :, slot, :].set(v, mode="drop")
    return k_pages, v_pages


# ----------------------------------------------------------- prefill attn
def gather_pages(pages: jax.Array, page_table: jax.Array) -> jax.Array:
    """[num_pages, n_kv, ps, hd] x [B, max_pages] -> [B, max_pages*ps, n_kv, hd]."""
    g = pages[page_table]                     # [B, max_pages, n_kv, ps, hd]
    B, mp, n_kv, ps, hd = g.shape
    return g.transpose(0, 1, 3, 2, 4).reshape(B, mp * ps, n_kv, hd)


def prefill_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      k_pages: jax.Array, v_pages: jax.Array,
                      page_table: jax.Array,
                      prefix_lens: jax.Array, seq_lens: jax.Array,
                      scale: float | None = None,
                      softcap: float = 0.0, window: int = 0) -> jax.Array:
    """Causal attention for a (possibly prefix-cached) prefill chunk.

    q/k/v: [B, S, n(_kv), hd] for the *suffix* being prefilled; queries also
    attend to the cached prefix (first prefix_lens[b] tokens) read from the
    paged pool. seq_lens[b] = valid suffix length (padding masked out).
    Returns [B, S, n_heads, hd].

    softcap > 0 tanh-caps the attention scores; window > 0 restricts each
    query to the trailing `window` key positions (gemma-2 local layers).
    Both take the XLA path — the Pallas/ring kernels don't implement them.
    """
    B, S, n_heads, hd = q.shape
    n_kv = k.shape[2]
    n_rep = n_heads // n_kv

    # Pallas MQ gate BEFORE the default-scale computation: a caller
    # passing an EXPLICIT scale (the MLA latent path, whose cache layout
    # this GQA kernel must never see) is excluded by `scale is None`
    # rather than by float comparison against the default. Two users:
    # - the speculative-verify program (traced under `mq_paged_verify`,
    #   XLLM_MQ_PALLAS=1);
    # - chunked/prefix prefill (XLLM_PREFILL_PALLAS=1): the XLA fallback
    #   gathers every row's full page span dense — [B, H, S, prefix+S]
    #   scores in HBM, which at long contexts dwarfs the chunk itself.
    # Both share the kernel's invariant (block KV already written to the
    # pages — write_prefill_kv runs first in prefill_from_embeddings) and
    # both are excluded under the ring-attention (sp) trace context. The
    # rows cap keeps the kernel's [S*n_heads, hd] f32 accumulator and
    # m/l scratch inside VMEM; bigger chunks fall back to XLA.
    if k_pages is not None and scale is None \
            and softcap == 0.0 and window == 0 \
            and getattr(_sp_ctx, "cfg", None) is None:
        import os

        in_verify = bool(getattr(_mq_ctx, "on", None))
        mq_on = in_verify and os.environ.get("XLLM_MQ_PALLAS", "") == "1"
        # The prefill flag must not bypass the verify path's own opt-in:
        # each has a separate Mosaic-validation gate.
        pf_on = (not in_verify
                 and os.environ.get("XLLM_PREFILL_PALLAS", "") == "1"
                 and S * n_heads <= 4096)
        if (mq_on or pf_on) and _mosaic_kernel_ok(q, k_pages):
            from .pallas_mq_paged_attention import mq_paged_attention_pallas

            return mq_paged_attention_pallas(q, k_pages, v_pages,
                                             page_table, prefix_lens,
                                             seq_lens,
                                             interpret=_pallas_interpret())

    if scale is None:
        scale = 1.0 / (hd ** 0.5)

    sp = getattr(_sp_ctx, "cfg", None)
    if sp is not None and (softcap != 0.0 or window != 0):
        raise NotImplementedError(
            "ring attention does not support attn softcap/sliding window; "
            "the engine must not enable sequence-parallel prefill for "
            "gemma-2-style models")
    if sp is not None:
        # Context-parallel path: ring attention over the seq mesh axis.
        # Queries past seq_lens are end-padding; causal masking keeps them
        # out of every valid query's window and the engine discards their
        # outputs, so the pure-causal ring is exact here. K/V go in at
        # their GQA head count — the ring repeats them only at use, so the
        # ppermute traffic stays n_rep times smaller.
        from .ring_attention import ring_attention

        mesh, seq_axis = sp
        return ring_attention(q, k, v, mesh, seq_axis=seq_axis, scale=scale)

    kf = _repeat_kv(k, n_rep).astype(jnp.float32)
    vf = _repeat_kv(v, n_rep).astype(jnp.float32)
    qf = q.astype(jnp.float32) * scale

    def cap(s):
        return softcap * jnp.tanh(s / softcap) if softcap > 0 else s

    # Suffix-suffix scores, causal + padding mask. Absolute positions:
    # query row r sits at prefix_lens[b] + r, key col c at prefix_lens[b]
    # + c — their distance is r - c, so the sliding-window mask here is
    # prefix-independent.
    ss = cap(jnp.einsum("bqhd,bkhd->bhqk", qf, kf))
    rows = jnp.arange(S)[None, :, None]
    cols = jnp.arange(S)[None, None, :]
    mask = (cols <= rows) & (cols < seq_lens[:, None, None])
    if window > 0:
        mask = mask & (rows - cols < window)
    ss = jnp.where(mask[:, None, :, :], ss, _NEG_INF)

    def _suffix_only(_):
        probs = jax.nn.softmax(ss, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", probs, vf)

    if k_pages is None:
        return _suffix_only(None).astype(q.dtype)

    def _attend_prefix(pt_prefix):
        pk = _repeat_kv(gather_pages(k_pages, pt_prefix),
                        n_rep).astype(jnp.float32)
        pv = _repeat_kv(gather_pages(v_pages, pt_prefix),
                        n_rep).astype(jnp.float32)
        T = pk.shape[1]
        ps_scores = cap(jnp.einsum("bqhd,bkhd->bhqk", qf, pk))
        pmask = (jnp.arange(T)[None, :] < prefix_lens[:, None])  # [B, T]
        pmask = pmask[:, None, :]                                # [B, 1, T]
        if window > 0:
            # Query row r (abs pos prefix_lens + r) sees prefix key c
            # (abs pos c) iff prefix_lens + r - c < window.
            dist = (prefix_lens[:, None, None] + rows
                    - jnp.arange(T)[None, None, :])   # [B, S, T]
            pmask = pmask & (dist < window)
        else:
            pmask = jnp.broadcast_to(pmask, (B, S, T))
        ps_scores = jnp.where(pmask[:, None, :, :], ps_scores, _NEG_INF)
        scores = jnp.concatenate([ps_scores, ss], axis=-1)
        values = jnp.concatenate([pv, vf], axis=1)
        probs = jax.nn.softmax(scores, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", probs, values)

    def _with_prefix(_):
        # Span-bucketed prefix gather (same ladder as paged_attention_xla):
        # the prefix term only needs pages covering positions < prefix_len,
        # so a chunked long prefill stops re-gathering its table's FULL
        # span on every chunk. Accelerator-gated like the decode ladder —
        # each span is a compiled variant, noise the CPU suite can't pay.
        page_size = k_pages.shape[2]
        max_pages = page_table.shape[1]
        spans = []
        if _span_buckets_on():
            s_ = max_pages
            while s_ > 1 and len(spans) < 3:
                spans.append(s_)
                s_ = -(-s_ // 2)
        spans = sorted(set(spans + [max_pages]))
        if len(spans) == 1:
            return _attend_prefix(page_table)
        need = jnp.max(-(-prefix_lens // page_size))
        idx = sum((need > sp).astype(jnp.int32) for sp in spans[:-1])
        branches = [lambda _, sp=sp: _attend_prefix(page_table[:, :sp])
                    for sp in spans]
        return jax.lax.switch(idx, branches, operand=None)

    # The prefix term gathers the row's whole page span and scores
    # against it — real bandwidth and FLOPs that a no-cache-hit prefill
    # (prefix 0, the common serving admission) would spend entirely on
    # fully-masked keys. Runtime-branch it: XLA compiles both sides, the
    # device executes only the live one.
    out = jax.lax.cond(jnp.any(prefix_lens > 0), _with_prefix,
                       _suffix_only, operand=None)
    return out.astype(q.dtype)


_warned_writeback_modes: set[str] = set()


def kv_writeback_mode() -> str:
    """The single reader for the XLLM_KV_WRITEBACK decode A/B switch.

    Valid values: "" (per-layer slice/stack/update), "slice" (two static
    .at[l, 0/1].set updates — skips materializing the [2, P, n_kv, ps,
    hd] stack temp), "scatter" (direct write into the full stacked pool —
    handled at the model layer, which owns the [L, 2, ...] array),
    "fused" (single Pallas append+attend kernel,
    `decode_attention_step`). An unrecognized value falls back to the
    default with a one-time warning instead of silently acting like an
    unset flag."""
    import logging
    import os

    mode = os.environ.get("XLLM_KV_WRITEBACK", "")
    if mode not in ("", "slice", "scatter", "fused"):
        if mode not in _warned_writeback_modes:
            _warned_writeback_modes.add(mode)
            logging.getLogger(__name__).warning(
                "XLLM_KV_WRITEBACK=%r is not one of '', 'slice', "
                "'scatter', 'fused'; using the default writeback", mode)
        return ""
    return mode


def _pallas_interpret() -> bool:
    """XLLM_PALLAS_INTERPRET=1 runs the Pallas kernels in interpret mode
    and lets the dispatch gates treat the CPU backend as kernel-capable —
    so tests exercise the REAL kernel routing hermetically (slow; tiny
    shapes only)."""
    import os

    return os.environ.get("XLLM_PALLAS_INTERPRET", "") == "1"


def _mosaic_kernel_ok(q: jax.Array, k_pages: jax.Array) -> bool:
    """Shared eligibility gate for the hand-written attention kernels:
    Mosaic tiling needs the head dim to be a lane-width multiple and GQA
    an integer group size; the kill switch and CPU backend exclude all
    Pallas paths at once."""
    import os

    n_heads, hd = q.shape[-2], q.shape[-1]
    n_kv = k_pages.shape[1]
    return (hd % 128 == 0 and n_heads % n_kv == 0
            and q.dtype in (jnp.bfloat16, jnp.float32)
            and (jax.default_backend() != "cpu" or _pallas_interpret())
            and os.environ.get("XLLM_DISABLE_PALLAS_ATTENTION", "")
            in ("", "0"))


def decode_attention_step(q: jax.Array, k: jax.Array, v: jax.Array,
                          k_pages: jax.Array, v_pages: jax.Array,
                          page_table: jax.Array, context_lens: jax.Array,
                          scale: float | None = None,
                          softcap: float = 0.0, window: int = 0,
                          ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Append one token's K/V and attend, as one step.

    q: [B, n_heads, hd]; k/v: [B, n_kv, hd] — the new token, written at
    position ``context_lens[b] - 1`` (context_lens INCLUDE it, matching
    the engine decode path's ``positions = clens - 1``); attention covers
    positions < ``context_lens[b]``. Returns (attn [B, n_heads, hd],
    k_pages, v_pages).

    Under ``XLLM_KV_WRITEBACK=fused`` on an accelerator this routes
    through the single fused Pallas kernel (one HBM append DMA overlapped
    with the page walk, no separate scatter); otherwise scatter-then-
    attend with identical numerics (parity-tested). The CP-decode context
    keeps the unfused path — the pool is sharded there and the write must
    land on the owning shard via the XLA scatter.
    """
    if (kv_writeback_mode() == "fused"
            and softcap == 0.0 and window == 0 and scale is None
            and getattr(_cp_ctx, "cfg", None) is None
            and _mosaic_kernel_ok(q, k_pages)):
        from .pallas_fused_decode_attention import (
            fused_decode_attention_pallas,
        )

        return fused_decode_attention_pallas(
            q, k, v, k_pages, v_pages, page_table, context_lens,
            interpret=_pallas_interpret())
    positions = context_lens - 1
    k_pages, v_pages = write_decode_kv(k_pages, v_pages, k, v,
                                       page_table, positions)
    attn = paged_attention(q, k_pages, v_pages, page_table, context_lens,
                           scale=scale, softcap=softcap, window=window)
    return attn, k_pages, v_pages


# ------------------------------------------------------------ decode attn
def _span_buckets_on() -> bool:
    """Span-bucketed gathers compile up to 4 variants of the attention
    subgraph per program — worth it on accelerators (bandwidth saved
    every step), pure compile-time cost on the CPU test backend (the
    suite pays minutes). XLLM_XLA_SPAN_BUCKETS=1/0 overrides."""
    import os

    v = os.environ.get("XLLM_XLA_SPAN_BUCKETS", "")
    if v in ("0", "1"):
        return v == "1"
    return jax.default_backend() != "cpu"


def paged_attention_xla(q: jax.Array, k_pages: jax.Array, v_pages: jax.Array,
                        page_table: jax.Array,
                        context_lens: jax.Array,
                        scale: float | None = None,
                        softcap: float = 0.0, window: int = 0) -> jax.Array:
    """One-token-per-sequence paged attention (XLA path).

    q: [B, n_heads, hd]; returns [B, n_heads, hd]. Assumes the new token's
    K/V are already written (attends to positions < context_lens[b] + 1 ...
    callers pass context_lens *including* the new token). softcap/window:
    gemma-2 score capping and sliding-window (the query sits at position
    context_lens[b]-1, so the window keeps keys >= context_lens[b]-window).

    The gather is span-bucketed: a static pow2 ladder of page-table
    prefixes compiles once each and `lax.switch` picks the shortest one
    covering the longest live context — families on this path (the MLA
    latent cache, whose fused head dim doesn't fit the Pallas kernel's
    tiling) no longer pay a FULL-table gather per layer per step when
    the table is sized for contexts far beyond current occupancy.
    """
    B, n_heads, hd = q.shape
    n_kv = k_pages.shape[1]
    n_rep = n_heads // n_kv
    page_size = k_pages.shape[2]
    if scale is None:
        scale = 1.0 / (hd ** 0.5)
    qf = q.astype(jnp.float32) * scale

    def attend(pt_prefix):
        k = _repeat_kv(gather_pages(k_pages, pt_prefix), n_rep)
        v = _repeat_kv(gather_pages(v_pages, pt_prefix), n_rep)
        T = k.shape[1]
        scores = jnp.einsum("bhd,bkhd->bhk", qf, k.astype(jnp.float32))
        if softcap > 0:
            scores = softcap * jnp.tanh(scores / softcap)
        mask = jnp.arange(T)[None, :] < context_lens[:, None]
        if window > 0:
            mask = mask & (jnp.arange(T)[None, :]
                           >= context_lens[:, None] - window)
        scores = jnp.where(mask[:, None, :], scores, _NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("bhk,bkhd->bhd", probs, v.astype(jnp.float32))
        return out.astype(q.dtype)

    max_pages = page_table.shape[1]
    # Pow2 span ladder, smallest-first (at most 4 variants; tiny tables
    # — and the CPU test backend — keep the single full-span branch).
    spans = []
    if _span_buckets_on():
        s = max_pages
        while s > 1 and len(spans) < 3:
            spans.append(s)
            s = -(-s // 2)
    spans = sorted(set(spans + [max_pages]))
    if len(spans) == 1:
        return attend(page_table)

    need = jnp.max(-(-context_lens // page_size))    # pages to cover
    idx = sum((need > s).astype(jnp.int32) for s in spans[:-1])
    branches = [lambda _, s_=s_: attend(page_table[:, :s_])
                for s_ in spans]
    return jax.lax.switch(idx, branches, operand=None)


def paged_attention(q: jax.Array, k_pages: jax.Array, v_pages: jax.Array,
                    page_table: jax.Array,
                    context_lens: jax.Array,
                    scale: float | None = None,
                    softcap: float = 0.0, window: int = 0) -> jax.Array:
    """Backend dispatcher: context-parallel op when the engine traced
    under `decode_context_parallel` (pool sharded over the seq axis),
    hand-written Pallas kernel on TPU, XLA gather fallback elsewhere (CPU
    test meshes) and for shapes outside the kernel's tiling constraints.
    Selection happens at trace time — all paths are numerically
    equivalent (tested). softcap/window (gemma-2) ride the Pallas
    kernel as static params when the shape qualifies, falling back to
    XLA otherwise; CP meshes refuse such models (the partial-stats
    merge has no softcap/window support)."""
    cp = getattr(_cp_ctx, "cfg", None)
    if cp is not None:
        if softcap != 0.0 or window != 0:
            raise NotImplementedError(
                "context-parallel decode does not support attn "
                "softcap/sliding window")
        from .cp_paged_attention import cp_paged_attention

        mesh, seq_axis = cp
        return cp_paged_attention(q, k_pages, v_pages, page_table,
                                  context_lens, mesh, seq_axis=seq_axis,
                                  scale=scale)

    if _mosaic_kernel_ok(q, k_pages):
        from .pallas_paged_attention import paged_attention_pallas

        # softcap/window/scale are static kernel params (gemma-2 decodes
        # through the kernel too — the XLA fallback gathers every row's
        # FULL page span dense per layer per step).
        return paged_attention_pallas(q, k_pages, v_pages, page_table,
                                      context_lens,
                                      interpret=_pallas_interpret(),
                                      scale=scale, softcap=softcap,
                                      window=window)
    return paged_attention_xla(q, k_pages, v_pages, page_table, context_lens,
                               scale=scale, softcap=softcap, window=window)
