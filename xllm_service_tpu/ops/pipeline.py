"""Pipeline parallelism: GPipe-style layer staging over a `pipe` mesh
axis (SURVEY.md §2.12's PP entry; the reference's engine is an empty
submodule, so the TPU-native design is ours to define).

Mechanism: the stacked layer parameters (leading ``L`` dim) are sharded
over the pipe axis — stage *s* holds layers ``[s*L/P, (s+1)*L/P)``. The
batch is split into microbatches; on schedule tick *t*, stage *s* runs
microbatch ``t - s`` through its local layer block, then the activations
``ppermute`` one hop down the ring. After ``P + M - 1`` ticks every
microbatch has passed through every stage; outputs are collected on the
last stage and ``psum``-broadcast (the off-stage contribution is zero).

All control flow is static (fixed tick count, masked inactivity) — the
compiler-friendly schedule shape (same trade as ring attention's masked
hops). Latency note: PP adds pipeline-fill bubbles and is the *capacity*
axis of the mesh taxonomy; TP/SP remain the latency axes. It exists so
models deeper than one slice's HBM can still serve.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
try:
    from jax import shard_map
except ImportError:   # pre-0.5 spelling of the same API
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def _pipeline_local(local_layers, x_mb, layer_fn: Callable,
                    axis_name: str) -> jax.Array:
    """Per-stage body. local_layers: this stage's layer-param shard
    (leading dim L/P); x_mb: [M, mb, ...] microbatched input, replicated
    across stages."""
    n_stage = jax.lax.psum(1, axis_name)
    stage = jax.lax.axis_index(axis_name)
    M = x_mb.shape[0]

    def run_block(x):
        def one(x, lp):
            return layer_fn(x, lp), None
        y, _ = jax.lax.scan(one, x, local_layers)
        return y

    perm = [(i, (i + 1) % n_stage) for i in range(n_stage)]

    if hasattr(jax.lax, "pcast"):
        def _vary(v):
            return jax.lax.pcast(v, axis_name, to="varying")
    else:
        # Older jax: no varying-axes typing, the zeros carry unifies as-is.
        def _vary(v):
            return v

    state = _vary(jnp.zeros_like(x_mb[0]))          # in-flight activation
    outputs = _vary(jnp.zeros_like(x_mb))

    def tick(carry, t):
        state, outputs = carry
        mb = t - stage                               # my microbatch index
        active = (mb >= 0) & (mb < M)
        # Stage 0 injects fresh microbatches; others consume the permuted
        # activation from the previous stage.
        inject = jnp.clip(t, 0, M - 1)
        cur = jnp.where(stage == 0, x_mb[inject], state)
        y = run_block(cur)
        y = jnp.where(active, y, cur)
        # Last stage records its finished microbatch.
        out_idx = jnp.clip(mb, 0, M - 1)
        take = active & (stage == n_stage - 1)
        outputs = outputs.at[out_idx].set(
            jnp.where(take, y, outputs[out_idx]))
        state = jax.lax.ppermute(y, axis_name, perm)
        return (state, outputs), None

    (_, outputs), _ = jax.lax.scan(
        tick, (state, outputs), jnp.arange(n_stage + M - 1))
    # Only the last stage holds real outputs; zero elsewhere -> psum is a
    # broadcast of the result to every stage (out_specs replicate).
    outputs = jnp.where(stage == n_stage - 1, outputs,
                        jnp.zeros_like(outputs))
    return jax.lax.psum(outputs, axis_name)


def pipeline_forward(layer_fn: Callable, stacked_layers, x: jax.Array,
                     mesh: Mesh, n_microbatches: int,
                     pipe_axis: str = "pipe") -> jax.Array:
    """Run ``x`` through the stacked layers, pipelined over `pipe_axis`.

    layer_fn(x, layer_params) -> x      (one transformer layer)
    stacked_layers: pytree with leading L dim divisible by the stage count
    x: [B, ...] with B divisible by n_microbatches.
    """
    B = x.shape[0]
    assert B % n_microbatches == 0, "batch not divisible by microbatches"
    x_mb = x.reshape(n_microbatches, B // n_microbatches, *x.shape[1:])
    layer_spec = P(pipe_axis)        # shard leading L dim into stages
    fn = shard_map(
        functools.partial(_pipeline_local, layer_fn=layer_fn,
                          axis_name=pipe_axis),
        mesh=mesh,
        in_specs=(jax.tree.map(lambda _: layer_spec, stacked_layers),
                  P()),
        out_specs=P(),
    )
    out = fn(stacked_layers, x_mb)
    return out.reshape(B, *out.shape[2:])
