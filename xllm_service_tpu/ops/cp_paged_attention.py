"""Context-parallel paged DECODE attention: the KV page pool sharded over
the mesh `seq` axis (SURVEY §5.7 — ring attention covers prefill; this
covers decode once a sequence's context outgrows one device's HBM).

Mechanism: pages are sharded round-robin-by-range across the seq axis
(device d owns pages [d*P/n, (d+1)*P/n)). Each device computes flash
statistics (m, l, acc) for every query over ONLY the pages it owns
(page-table entries outside its range are masked), then the per-device
partials merge with a log-sum-exp reduction over the axis:

    m_g   = pmax(m)
    l_g   = psum(l * exp(m - m_g))
    acc_g = psum(acc * exp(m - m_g))
    out   = acc_g / l_g

One psum pair over ICI per decode step — no device ever materializes
another shard's pages.

Two per-shard bodies, selected by the shared Mosaic gate:
- Pallas partial kernel (accelerators): each shard compacts its owned
  page-table entries to the front and walks ONLY those pages with the
  chunked double-buffered page DMA shared with the decode kernel
  (ops/pallas_page_dma.py) — per-step HBM traffic is the occupied,
  locally-owned pages, nothing else, and it returns raw (m, l, acc) for
  the cross-shard merge.
- Dense XLA fallback (CPU tests / non-Mosaic shapes): gathers the local
  page span to a dense tensor per step — correctness-first (this was the
  only body in round 2; VERDICT r2 weak #6).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
try:
    from jax import shard_map
except ImportError:   # pre-0.5 spelling of the same API
    from jax.experimental.shard_map import shard_map
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

from .pallas_page_dma import (
    NEG_INF,
    chunked_page_walk,
    flash_accumulate,
    masked_kv_f32_pos,
    page_chunk_size,
    tpu_compiler_params,
)

_NEG_INF = NEG_INF


def _local_partial(q, k_pages, v_pages, page_table, context_lens,
                   axis_name: str, scale):
    """Per-device body. k/v_pages: the LOCAL page shard
    [P_loc, n_kv, ps, hd]; page ids in page_table are global."""
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    P_loc = k_pages.shape[0]
    lo = my * P_loc

    B, H, hd = q.shape
    n_kv = k_pages.shape[1]
    ps = k_pages.shape[2]
    n_rep = H // n_kv
    if scale is None:
        scale = 1.0 / (hd ** 0.5)

    # Local gather: clamp global ids into the local shard; out-of-range
    # entries keep index 0 and are masked out of the softmax.
    local_idx = page_table - lo                         # [B, max_pages]
    owned = (local_idx >= 0) & (local_idx < P_loc)
    safe_idx = jnp.where(owned, local_idx, 0)
    g = k_pages[safe_idx]                               # [B, mp, n_kv, ps, hd]
    gv = v_pages[safe_idx]
    mp = safe_idx.shape[1]
    k = g.transpose(0, 1, 3, 2, 4).reshape(B, mp * ps, n_kv, hd)
    v = gv.transpose(0, 1, 3, 2, 4).reshape(B, mp * ps, n_kv, hd)
    if n_rep > 1:
        k = jnp.repeat(k, n_rep, axis=2)
        v = jnp.repeat(v, n_rep, axis=2)

    qf = q.astype(jnp.float32) * scale
    scores = jnp.einsum("bhd,bkhd->bhk", qf, k.astype(jnp.float32))
    pos = jnp.arange(mp * ps)[None, :]
    valid = (pos < context_lens[:, None]) & \
        jnp.repeat(owned, ps, axis=1)                   # [B, mp*ps]
    scores = jnp.where(valid[:, None, :], scores, _NEG_INF)

    m = jnp.max(scores, axis=-1, keepdims=True)          # [B, H, 1]
    p = jnp.exp(scores - m)
    p = jnp.where(scores <= _NEG_INF / 2, 0.0, p)
    l = jnp.sum(p, axis=-1, keepdims=True)
    acc = jnp.einsum("bhk,bkhd->bhd", p, v.astype(jnp.float32))

    # Merge flash stats across the seq axis.
    m_g = jax.lax.pmax(m, axis_name)
    w = jnp.exp(jnp.where(m <= _NEG_INF / 2, _NEG_INF, m) - m_g)
    w = jnp.where(m <= _NEG_INF / 2, 0.0, w)
    l_g = jax.lax.psum(l * w, axis_name)
    acc_g = jax.lax.psum(acc * w[..., 0][..., None], axis_name)
    out = acc_g / jnp.maximum(l_g[..., 0][..., None], 1e-9)
    return out.astype(q.dtype)


def _partial_kernel(local_pt_ref, starts_ref, n_local_ref, clens_ref,
                    q_ref,                       # VMEM block [1, n_q, hd]
                    k_hbm, v_hbm,                # LOCAL pool shard in HBM
                    m_out, l_out, acc_out,
                    k_buf, v_buf, sems, m_scr, l_scr, acc_scr,
                    *, page_size: int, n_kv: int, group: int, scale: float,
                    max_pages: int, chunk: int, pipeline_rows: bool):
    """Flash partial stats over this shard's owned pages only.

    local_pt_ref: [B, mp] LOCAL page indices, owned entries compacted to
    the front (n_local_ref[b] of them); starts_ref: [B, mp] each entry's
    global token start (ctx for non-owned → fully masked)."""
    b = pl.program_id(0)
    nb = pl.num_programs(0)
    ctx = clens_ref[b]

    def n_pages_of(row):
        return jnp.minimum(n_local_ref[row], max_pages)

    n_pages = n_pages_of(b)

    m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
    l_scr[...] = jnp.zeros_like(l_scr)
    acc_scr[...] = jnp.zeros_like(acc_scr)

    def compute(c, slot):
        # Per-row global token positions: compacted pages are not
        # contiguous, so each page contributes start_j + iota(ps).
        base = c * chunk
        rows = []
        for j in range(chunk):
            # Chunk-padding entries (base+j >= n_pages) were never
            # DMA'd — their buffer rows are stale. Position them at
            # ctx so both masks reject them (clamping the table read
            # instead would alias a REAL page's positions and let
            # stale K/V through). (Covers the pipelined walk's whole
            # pad chunk too: every entry sits past n_pages.)
            st = jnp.where(
                base + j < n_pages,
                starts_ref[b, jnp.minimum(base + j, max_pages - 1)],
                ctx)
            rows.append(st + jax.lax.broadcasted_iota(
                jnp.int32, (1, page_size), 1))
        pos = jnp.concatenate(rows, axis=0)          # [chunk, ps]
        span = chunk * page_size
        pos_row = pos.reshape(1, span)
        pos_col = pos.reshape(span, 1)
        mask = pos_row < ctx
        q = q_ref[0].astype(jnp.float32) * scale     # [n_q, hd]
        for kv in range(n_kv):
            qh = q[kv * group:(kv + 1) * group, :]   # [G, hd]
            k, v = masked_kv_f32_pos(k_buf, v_buf, slot, kv,
                                     pos_col, ctx)
            s = jax.lax.dot_general(
                qh, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)  # [G, span]
            s = jnp.where(mask, s, _NEG_INF)
            flash_accumulate(slice(kv * group, (kv + 1) * group),
                             s, v, m_scr, l_scr, acc_scr)

    chunked_page_walk(local_pt_ref, b, nb, n_pages, n_pages_of, chunk,
                      k_hbm, v_hbm, k_buf, v_buf, sems, compute,
                      pipeline_rows)

    m_out[0] = m_scr[...]
    l_out[0] = l_scr[...]
    acc_out[0] = acc_scr[...]


def _paged_partial_pallas(q, k_pages, v_pages, local_pt, starts, n_local,
                          context_lens, scale: float,
                          interpret: bool = False):
    """Per-shard raw flash stats: returns (m [B, n_q, 128],
    l [B, n_q, 128], acc [B, n_q, hd]) — only column 0 of m/l is live.

    XLLM_PAGE_CHUNK is resolved here, OUTSIDE jit, and passed static — a
    shape-keyed cache would silently pin the first-traced chunk."""
    import os

    return _paged_partial_impl(q, k_pages, v_pages, local_pt, starts,
                               n_local, context_lens, scale=scale,
                               chunk=page_chunk_size(local_pt.shape[1]),
                               pipeline_rows=os.environ.get(
                                   "XLLM_PAGE_PIPELINE", "") == "row",
                               interpret=interpret)


@functools.partial(jax.jit,
                   static_argnames=("scale", "chunk", "pipeline_rows",
                                    "interpret"))
def _paged_partial_impl(q, k_pages, v_pages, local_pt, starts, n_local,
                        context_lens, *, scale: float, chunk: int,
                        pipeline_rows: bool = False,
                        interpret: bool = False):
    B, n_q, hd = q.shape
    _, n_kv, page_size, _ = k_pages.shape
    max_pages = local_pt.shape[1]
    group = n_q // n_kv
    kernel = functools.partial(_partial_kernel, page_size=page_size,
                               n_kv=n_kv, group=group, scale=scale,
                               max_pages=max_pages, chunk=chunk,
                               pipeline_rows=pipeline_rows)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, n_q, hd), lambda b, *_: (b, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),   # local k shard in HBM
            pl.BlockSpec(memory_space=pl.ANY),   # local v shard in HBM
        ],
        out_specs=[
            pl.BlockSpec((1, n_q, 128), lambda b, *_: (b, 0, 0)),
            pl.BlockSpec((1, n_q, 128), lambda b, *_: (b, 0, 0)),
            pl.BlockSpec((1, n_q, hd), lambda b, *_: (b, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, chunk, n_kv, page_size, hd), k_pages.dtype),
            pltpu.VMEM((2, chunk, n_kv, page_size, hd), v_pages.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
            pltpu.VMEM((n_q, 128), jnp.float32),   # m
            pltpu.VMEM((n_q, 128), jnp.float32),   # l
            pltpu.VMEM((n_q, hd), jnp.float32),    # acc
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, n_q, 128), jnp.float32),
            jax.ShapeDtypeStruct((B, n_q, 128), jnp.float32),
            jax.ShapeDtypeStruct((B, n_q, hd), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(local_pt, starts, n_local, context_lens, q, k_pages, v_pages)


def _local_partial_kernelized(q, k_pages, v_pages, page_table,
                              context_lens, axis_name: str, scale,
                              interpret: bool):
    """Pallas per-shard body: compact owned page-table entries, walk only
    those pages (chunked double-buffered DMA), merge raw stats over the
    seq axis."""
    my = jax.lax.axis_index(axis_name)
    P_loc = k_pages.shape[0]
    lo = my * P_loc
    ps = k_pages.shape[2]
    hd = q.shape[-1]
    if scale is None:
        scale = 1.0 / (hd ** 0.5)

    local_idx = page_table - lo                          # [B, mp]
    owned = (local_idx >= 0) & (local_idx < P_loc)
    # Walk only the OCCUPIED span (cdiv(ctx, ps) entries), matching the
    # single-device decode kernel: the table tail is garbage-page padding
    # (id 0 — which would otherwise count as "owned" on shard 0 and be
    # DMA'd every step just to be masked out).
    mp = page_table.shape[1]
    owned &= (jnp.arange(mp, dtype=jnp.int32)[None, :] * ps
              < context_lens[:, None])
    # Stable sort brings owned entries to the front in table order.
    order = jnp.argsort(~owned, axis=1, stable=True)     # [B, mp]
    local_pt = jnp.take_along_axis(
        jnp.where(owned, local_idx, 0), order, axis=1).astype(jnp.int32)
    # Each entry's global token start; non-owned → ctx (fully masked and
    # never DMA'd — they sit past n_local).
    starts = jnp.where(jnp.take_along_axis(owned, order, axis=1),
                       order * ps, context_lens[:, None]).astype(jnp.int32)
    n_local = owned.sum(axis=1).astype(jnp.int32)

    m, l, acc = _paged_partial_pallas(q, k_pages, v_pages, local_pt,
                                      starts, n_local, context_lens,
                                      scale=float(scale),
                                      interpret=interpret)
    m = m[..., :1]                                       # live column
    l = l[..., :1]
    m_g = jax.lax.pmax(m, axis_name)
    w = jnp.exp(jnp.where(m <= _NEG_INF / 2, _NEG_INF, m) - m_g)
    w = jnp.where(m <= _NEG_INF / 2, 0.0, w)
    l_g = jax.lax.psum(l * w, axis_name)
    acc_g = jax.lax.psum(acc * w, axis_name)
    out = acc_g / jnp.maximum(l_g, 1e-9)
    return out.astype(q.dtype)


def cp_paged_attention(q: jax.Array, k_pages: jax.Array,
                       v_pages: jax.Array, page_table: jax.Array,
                       context_lens: jax.Array, mesh: Mesh,
                       seq_axis: str = "seq",
                       scale: float | None = None) -> jax.Array:
    """q: [B, n_heads, hd]; k/v_pages: [num_pages, n_kv, ps, hd] sharded
    (or shardable) on the page axis over `seq_axis`; num_pages must divide
    by the axis size. Returns [B, n_heads, hd], identical to
    single-device paged attention (parity-tested)."""
    from .attention import _mosaic_kernel_ok, _pallas_interpret

    if _mosaic_kernel_ok(q, k_pages):
        body = functools.partial(_local_partial_kernelized,
                                 axis_name=seq_axis, scale=scale,
                                 interpret=_pallas_interpret())
    else:
        body = functools.partial(_local_partial, axis_name=seq_axis,
                                 scale=scale)
    # pallas_call's out_shape carries no varying-mesh-axes metadata,
    # which trips shard_map's replication/vma check on the kernel body —
    # disable it under whichever name this jax spells it.
    import inspect

    relax = ("check_vma" if "check_vma"
             in inspect.signature(shard_map).parameters else "check_rep")
    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(P(), P(seq_axis), P(seq_axis), P(), P()),
        out_specs=P(),
        **{relax: False},
    )
    return fn(q, k_pages, v_pages, page_table, context_lens)
