"""Context-parallel paged DECODE attention: the KV page pool sharded over
the mesh `seq` axis (SURVEY §5.7 — ring attention covers prefill; this
covers decode once a sequence's context outgrows one device's HBM).

Mechanism: pages are sharded round-robin-by-range across the seq axis
(device d owns pages [d*P/n, (d+1)*P/n)). Each device computes flash
statistics (m, l, acc) for every query over ONLY the pages it owns
(page-table entries outside its range are masked), then the per-device
partials merge with a log-sum-exp reduction over the axis:

    m_g   = pmax(m)
    l_g   = psum(l * exp(m - m_g))
    acc_g = psum(acc * exp(m - m_g))
    out   = acc_g / l_g

One psum pair over ICI per decode step — no device ever materializes
another shard's pages.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -1e30


def _local_partial(q, k_pages, v_pages, page_table, context_lens,
                   axis_name: str, scale):
    """Per-device body. k/v_pages: the LOCAL page shard
    [P_loc, n_kv, ps, hd]; page ids in page_table are global."""
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    P_loc = k_pages.shape[0]
    lo = my * P_loc

    B, H, hd = q.shape
    n_kv = k_pages.shape[1]
    ps = k_pages.shape[2]
    n_rep = H // n_kv
    if scale is None:
        scale = 1.0 / (hd ** 0.5)

    # Local gather: clamp global ids into the local shard; out-of-range
    # entries keep index 0 and are masked out of the softmax.
    local_idx = page_table - lo                         # [B, max_pages]
    owned = (local_idx >= 0) & (local_idx < P_loc)
    safe_idx = jnp.where(owned, local_idx, 0)
    g = k_pages[safe_idx]                               # [B, mp, n_kv, ps, hd]
    gv = v_pages[safe_idx]
    mp = safe_idx.shape[1]
    k = g.transpose(0, 1, 3, 2, 4).reshape(B, mp * ps, n_kv, hd)
    v = gv.transpose(0, 1, 3, 2, 4).reshape(B, mp * ps, n_kv, hd)
    if n_rep > 1:
        k = jnp.repeat(k, n_rep, axis=2)
        v = jnp.repeat(v, n_rep, axis=2)

    qf = q.astype(jnp.float32) * scale
    scores = jnp.einsum("bhd,bkhd->bhk", qf, k.astype(jnp.float32))
    pos = jnp.arange(mp * ps)[None, :]
    valid = (pos < context_lens[:, None]) & \
        jnp.repeat(owned, ps, axis=1)                   # [B, mp*ps]
    scores = jnp.where(valid[:, None, :], scores, _NEG_INF)

    m = jnp.max(scores, axis=-1, keepdims=True)          # [B, H, 1]
    p = jnp.exp(scores - m)
    p = jnp.where(scores <= _NEG_INF / 2, 0.0, p)
    l = jnp.sum(p, axis=-1, keepdims=True)
    acc = jnp.einsum("bhk,bkhd->bhd", p, v.astype(jnp.float32))

    # Merge flash stats across the seq axis.
    m_g = jax.lax.pmax(m, axis_name)
    w = jnp.exp(jnp.where(m <= _NEG_INF / 2, _NEG_INF, m) - m_g)
    w = jnp.where(m <= _NEG_INF / 2, 0.0, w)
    l_g = jax.lax.psum(l * w, axis_name)
    acc_g = jax.lax.psum(acc * w[..., 0][..., None], axis_name)
    out = acc_g / jnp.maximum(l_g[..., 0][..., None], 1e-9)
    return out.astype(q.dtype)


def cp_paged_attention(q: jax.Array, k_pages: jax.Array,
                       v_pages: jax.Array, page_table: jax.Array,
                       context_lens: jax.Array, mesh: Mesh,
                       seq_axis: str = "seq",
                       scale: float | None = None) -> jax.Array:
    """q: [B, n_heads, hd]; k/v_pages: [num_pages, n_kv, ps, hd] sharded
    (or shardable) on the page axis over `seq_axis`; num_pages must divide
    by the axis size. Returns [B, n_heads, hd], identical to
    single-device paged attention (parity-tested)."""
    fn = shard_map(
        functools.partial(_local_partial, axis_name=seq_axis, scale=scale),
        mesh=mesh,
        in_specs=(P(), P(seq_axis), P(seq_axis), P(), P()),
        out_specs=P(),
    )
    return fn(q, k_pages, v_pages, page_table, context_lens)
