"""Fleet continuous-profiling plane (docs/observability.md).

Always-on wall-clock sampling profiler (``sampler.py``) with per-thread-
role folded stacks served at ``GET /admin/profile`` on every process,
plus the span-tree critical-path decomposition (``critical_path.py``)
behind ``/admin/trace`` and ``/admin/hotpath``.
"""

from .critical_path import (CRITICAL_STAGES, aggregate_critical_paths,
                            critical_path)
from .sampler import (PROFILER, SamplingProfiler, handle_admin_profile,
                      parse_folded, summarize_stacks)

__all__ = [
    "CRITICAL_STAGES",
    "PROFILER",
    "SamplingProfiler",
    "aggregate_critical_paths",
    "critical_path",
    "handle_admin_profile",
    "parse_folded",
    "summarize_stacks",
]
