"""Always-on wall-clock sampling profiler with per-thread-role folding.

PR 13's ``CPU_ATTR`` says which coarse loop (ingest/route/stream) burns
the master's CPU; nothing in the repo can say which *frames* inside them
do. This module is the continuous-profiling layer every production
serving fleet runs (Google-Wide Profiling / Parca / py-spy shape),
adapted to the repo's registry discipline:

- A daemon thread (``profiler-sampler``, registered in
  ``THREAD_ROLES``) walks ``sys._current_frames()`` at ``profile_hz``
  (default ~19 Hz — a prime-ish rate so the sampler never phase-locks
  with periodic loops) and folds each thread's stack into a bounded
  per-role aggregate. Roles come from the existing ``THREAD_ROLES``
  registry (``devtools/ownership.py``); unregistered threads group
  under their sanitized thread-name stem, so ``gen-streamer-...`` /
  executor workers still aggregate sensibly.
- Aggregates rotate on a window cadence (``profile_window_s``): the
  last complete window stays queryable next to the live one, and
  :meth:`SamplingProfiler.anomaly_context` snapshots it into every
  flight-recorder bundle (registered as the ``profile`` context
  provider while the sampler runs).
- Served as flamegraph-compatible folded stacks
  (``GET /admin/profile?format=folded`` — pipe straight into
  flamegraph.pl or speedscope) and as a top-N JSON summary. The
  master's handler adds ``?scope=fleet`` riding the PR-9 federation
  fan-out (http_service/service.py).

Per-tick cost is one ``sys._current_frames()`` call plus a cached
dict-lookup per frame (labels are memoized per code object), merged
under one leaf lock — gated ≤1% of the serve bench by
``benchmarks/bench_profile_overhead.py``. Start/stop is refcounted (the
master HTTP service and an in-process engine agent share one sampler)
and registered as the strict ``profiler-thread`` lifecycle pair.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Any, Optional

from ..devtools import lifecycle as _lifecycle
from ..devtools import ownership as _ownership
from ..devtools.locks import make_lock
from ..utils import get_logger

logger = get_logger(__name__)

#: Aggregate bucket charged once a role's distinct-stack table is full —
#: memory stays bounded at any churn, and the overflow is visible rather
#: than silently dropped.
OVERFLOW_FRAME = "(overflow)"

#: Role assigned to the main thread (it matches no THREAD_ROLES prefix
#: but is the test drivers' stand-in for everything).
MAIN_ROLE = "main"

#: Role-table bound: samples from threads beyond this many distinct
#: roles aggregate under ``(otherrole)`` — role cardinality (not just
#: per-role stacks) stays bounded under adversarial thread naming.
MAX_ROLES = 64

_LABEL_CACHE_MAX = 4096


def _role_prefixes() -> list[tuple[str, str]]:
    """(thread-name prefix, role) rows from the ownership registry."""
    rows: list[tuple[str, str]] = []
    for role, decl in _ownership.THREAD_ROLES.items():
        for prefix in decl.get("threads", ()):
            rows.append((prefix, role))
    return rows


def _name_stem(name: str) -> str:
    """Fallback role for unregistered threads: the thread-name stem with
    trailing pool/worker numbering stripped (``ThreadPoolExecutor-0_3``
    -> ``ThreadPoolExecutor``). CPython's default ``Thread-N (target)``
    names collapse to the target — per-request worker threads must
    aggregate under one role, not one role per thread."""
    if name.startswith("Thread-") and name.endswith(")"):
        lp = name.find("(")
        if lp != -1 and name[lp + 1:-1]:
            return name[lp + 1:-1]
    stem = name
    while stem and stem[-1] in "0123456789-_ ":
        stem = stem[:-1]
    return stem or "other"


@_ownership.verify_state
class SamplingProfiler:
    """Refcounted process-global sampling profiler (see module doc)."""

    def __init__(self) -> None:
        self._lock = make_lock("profiling.sampler", order=824)  # lock-order: 824
        self._refs = 0
        self._thread: Optional[threading.Thread] = None
        self._stop_evt: Optional[threading.Event] = None
        self._hz = 19.0
        self._window_s = 30.0
        self._max_stacks = 256
        self._max_depth = 24
        # Live window: role -> {stack tuple (root..leaf) -> count}.
        self._agg: dict[str, dict[tuple, int]] = {}
        self._ticks = 0
        self._window_started = time.monotonic()
        # Last complete window (the anomaly snapshot's preferred source).
        self._prev: dict[str, dict[tuple, int]] = {}
        self._prev_ticks = 0
        self._prev_window_s = 0.0
        # Sampler-thread heartbeat (liveness surfaced in snapshots).
        self._last_tick_mono = 0.0
        # Per-code-object label memo (sampler thread only mutates it; a
        # bounded dict keyed by the code objects themselves).
        self._label_cache: dict[Any, str] = {}
        self._roles = _role_prefixes()

    # ------------------------------------------------------------ lifecycle
    def configure(self, hz: Optional[float] = None,
                  window_s: Optional[float] = None,
                  max_stacks: Optional[int] = None,
                  max_depth: Optional[int] = None) -> None:
        """Apply options. ``hz <= 0`` disables sampling (the next start()
        spawns no thread); a running sampler keeps its spawn-time rate
        but honors the new window/bounds at the next merge."""
        with self._lock:
            if hz is not None:
                self._hz = float(hz)
            if window_s is not None:
                self._window_s = max(1.0, float(window_s))
            if max_stacks is not None:
                self._max_stacks = max(16, int(max_stacks))
            if max_depth is not None:
                self._max_depth = max(2, int(max_depth))

    def start(self) -> None:
        """Refcounted start: the first owner with a positive rate spawns
        the ``profiler-sampler`` thread and registers the flight-recorder
        ``profile`` context provider; later owners only take a ref."""
        spawned = None
        with self._lock:
            self._refs += 1
            if self._thread is None and self._hz > 0:
                evt = threading.Event()
                t = threading.Thread(
                    target=self._loop,
                    args=(evt, max(0.5, self._hz)),
                    name="profiler-sampler", daemon=True)
                self._stop_evt = evt
                self._thread = t
                self._window_started = time.monotonic()
                spawned = t
        if spawned is not None:
            _lifecycle.note_acquire("profiler-thread")
            from ..common.flightrecorder import RECORDER

            RECORDER.add_context_provider("profile", self.anomaly_context)
            spawned.start()

    def stop(self) -> None:
        """Refcounted stop: the last owner joins the sampler thread and
        deregisters the anomaly provider. Idempotent — a stop with no
        outstanding start is a no-op."""
        joined = None
        with self._lock:
            if self._refs == 0:
                return
            self._refs -= 1
            if self._refs:
                return
            joined = self._thread
            evt = self._stop_evt
            self._thread = None
            self._stop_evt = None
            if evt is not None:
                evt.set()
        if joined is not None:
            joined.join(timeout=5.0)
            from ..common.flightrecorder import RECORDER

            RECORDER.remove_context_provider("profile", self.anomaly_context)
            _lifecycle.note_release("profiler-thread")

    def running(self) -> bool:
        with self._lock:
            return self._thread is not None

    # ------------------------------------------------------------- sampling
    def _loop(self, stop_evt: threading.Event, hz: float) -> None:
        interval = 1.0 / hz
        own_ident = threading.get_ident()
        while not stop_evt.wait(interval):
            self._last_tick_mono = time.monotonic()
            try:
                self._sample_once(own_ident)
            except Exception:  # noqa: BLE001 — the sampler must outlive any one bad tick
                logger.exception("profiler sample tick failed")

    def _sample_once(self, own_ident: int) -> None:
        names = {t.ident: t.name for t in threading.enumerate()}
        ticks: list[tuple[str, tuple]] = []
        for ident, frame in sys._current_frames().items():
            if ident == own_ident:
                continue
            name = names.get(ident)
            if name is None:
                continue   # a thread born between enumerate() and here
            ticks.append((self._role_of(name), self._fold(frame)))
        self._merge(ticks, time.monotonic())

    def _merge(self, ticks: list[tuple[str, tuple]], now: float) -> None:
        """Fold one tick's (role, stack) samples into the live window,
        bounded per role at ``_max_stacks`` distinct stacks (the rest is
        charged to a visible overflow bucket), rotating the window on
        the ``_window_s`` cadence."""
        with self._lock:
            self._ticks += 1
            max_stacks = self._max_stacks
            for role, stack in ticks:
                stacks = self._agg.get(role)
                if stacks is None:
                    if len(self._agg) >= MAX_ROLES:
                        role = "(otherrole)"
                        stacks = self._agg.setdefault(role, {})
                    else:
                        stacks = {}
                        self._agg[role] = stacks
                if stack in stacks:
                    stacks[stack] += 1
                elif len(stacks) < max_stacks:
                    stacks[stack] = 1
                else:
                    key = (OVERFLOW_FRAME,)
                    stacks[key] = stacks.get(key, 0) + 1
            if now - self._window_started >= self._window_s:
                self._rotate_locked(now)

    def _role_of(self, name: str) -> str:
        if name == "MainThread":
            return MAIN_ROLE
        for prefix, role in self._roles:
            if name.startswith(prefix):
                return role
        return _name_stem(name)

    def _fold(self, frame: Any) -> tuple:
        """Leaf frame -> bounded (root..leaf) label tuple. Labels memoize
        per code object; deep stacks keep the leaf side."""
        cache = self._label_cache
        labels: list[str] = []
        depth = self._max_depth
        f = frame
        while f is not None and len(labels) < depth:
            code = f.f_code
            label = cache.get(code)
            if label is None:
                base = code.co_filename.rsplit("/", 1)[-1]
                qual = getattr(code, "co_qualname", code.co_name)
                label = f"{base}:{qual}".replace(";", ":").replace(" ", "")
                if len(cache) < _LABEL_CACHE_MAX:
                    cache[code] = label
            labels.append(label)
            f = f.f_back
        labels.reverse()
        return tuple(labels)

    def _rotate_locked(self, now: float) -> None:
        self._prev = self._agg
        self._prev_ticks = self._ticks
        self._prev_window_s = now - self._window_started
        self._agg = {}
        self._ticks = 0
        self._window_started = now

    # -------------------------------------------------------------- reading
    def _merged_locked(self) -> dict[tuple, int]:
        """(role, frame..., leaf) -> count over prev + live windows."""
        merged: dict[tuple, int] = {}
        for window in (self._prev, self._agg):
            for role, stacks in window.items():
                for stack, n in stacks.items():
                    key = (role,) + stack
                    merged[key] = merged.get(key, 0) + n
        return merged

    def snapshot(self, top_n: int = 30) -> dict[str, Any]:
        """Top-N JSON view over the last two windows (the live one plus
        the last complete one)."""
        now = time.monotonic()
        with self._lock:
            merged = self._merged_locked()
            meta = {
                "enabled": self._thread is not None,
                "hz": self._hz,
                "window_s": self._window_s,
                "ticks": self._ticks + self._prev_ticks,
                "covered_s": round(
                    self._prev_window_s + (now - self._window_started), 3),
                "last_tick_age_s": round(
                    now - self._last_tick_mono, 3)
                if self._last_tick_mono else None,
            }
        out = summarize_stacks(merged, top_n=top_n)
        out.update(meta)
        return out

    def folded(self) -> str:
        """Flamegraph folded-stack text: one ``role;frame;...;leaf N``
        line per distinct stack (flamegraph.pl / speedscope input)."""
        with self._lock:
            merged = self._merged_locked()
        lines = [f"{';'.join(stack)} {n}"
                 for stack, n in sorted(merged.items())]
        return "\n".join(lines) + ("\n" if lines else "")

    def anomaly_context(self) -> dict[str, Any]:
        """Flight-recorder context provider: a compact profile of the
        last complete window (or the live one while the first window is
        still filling) — every breach/error/failover bundle carries it."""
        with self._lock:
            if self._thread is None:
                return {"enabled": False}
            window = self._prev or self._agg
            window_s = self._prev_window_s if self._prev else \
                (time.monotonic() - self._window_started)
            ticks = self._prev_ticks if self._prev else self._ticks
            merged: dict[tuple, int] = {}
            for role, stacks in window.items():
                for stack, n in stacks.items():
                    merged[(role,) + stack] = n
        summary = summarize_stacks(merged, top_n=12)
        return {
            "enabled": True,
            "window_s": round(window_s, 3),
            "ticks": ticks,
            "role_samples": {role: r["samples"]
                             for role, r in summary["roles"].items()},
            "top_frames": summary["top_frames"],
        }

    def clear(self) -> None:
        """Bench/test hook: drop both windows (bounds and rate keep)."""
        with self._lock:
            self._agg = {}
            self._prev = {}
            self._ticks = 0
            self._prev_ticks = 0
            self._prev_window_s = 0.0
            self._window_started = time.monotonic()


# --------------------------------------------------- folded-stack helpers
def parse_folded(text: str) -> dict[tuple, int]:
    """Inverse of :meth:`SamplingProfiler.folded` — the fleet merge path
    (counts sum exactly across peers, no top-N loss)."""
    out: dict[tuple, int] = {}
    for line in text.splitlines():
        stack_text, _, count_text = line.rpartition(" ")
        if not stack_text:
            continue
        try:
            n = int(count_text)
        except ValueError:
            continue
        key = tuple(stack_text.split(";"))
        out[key] = out.get(key, 0) + n
    return out


def summarize_stacks(counts: dict[tuple, int],
                     top_n: int = 30) -> dict[str, Any]:
    """Top-N summary of ``(role, frame..., leaf) -> count`` aggregates:
    per-role sample totals, hottest leaf frames (self samples), hottest
    stacks, and the cross-role frame table the CPU_ATTR alignment drill
    reads."""
    top_n = max(1, int(top_n))
    roles: dict[str, dict[str, Any]] = {}
    per_role_frames: dict[str, dict[str, int]] = {}
    per_role_stacks: dict[str, dict[tuple, int]] = {}
    global_frames: dict[str, int] = {}
    total = 0
    for key, n in counts.items():
        if not key:
            continue
        role = key[0]
        stack = key[1:]
        leaf = stack[-1] if stack else "(unknown)"
        total += n
        per_role_frames.setdefault(role, {})
        per_role_frames[role][leaf] = per_role_frames[role].get(leaf, 0) + n
        per_role_stacks.setdefault(role, {})
        per_role_stacks[role][stack] = \
            per_role_stacks[role].get(stack, 0) + n
        global_frames[leaf] = global_frames.get(leaf, 0) + n

    def top_items(d: dict, k: int) -> list:
        return sorted(d.items(), key=lambda kv: (-kv[1], str(kv[0])))[:k]

    for role, frames in per_role_frames.items():
        samples = sum(frames.values())
        roles[role] = {
            "samples": samples,
            "top_frames": [
                {"frame": frame, "self": n,
                 "pct": round(100.0 * n / samples, 2)}
                for frame, n in top_items(frames, top_n)],
            "top_stacks": [
                {"stack": ";".join(stack), "count": n}
                for stack, n in top_items(per_role_stacks[role], top_n)],
        }
    return {
        "samples": total,
        "roles": dict(sorted(roles.items())),
        "top_frames": [
            {"frame": frame, "self": n,
             "pct": round(100.0 * n / total, 2) if total else 0.0}
            for frame, n in top_items(global_frames, top_n)],
    }


#: Process-global profiler (master HTTP service and engine agent share
#: it — start/stop is refcounted).
PROFILER = SamplingProfiler()


async def handle_admin_profile(request):
    """Shared aiohttp handler: ``GET /admin/profile`` — local scope.
    ``?format=folded`` returns the full folded-stack text;
    ``?top=N`` bounds the JSON summary tables. The master's fleet-scope
    wrapper (http_service/service.py) fans this endpoint out and merges
    the folded counts."""
    from aiohttp import web

    try:
        top = int(request.query.get("top", 30))
    except ValueError:
        return web.json_response({"error": "top must be an integer"},
                                 status=400)
    if request.query.get("format") == "folded":
        return web.Response(text=PROFILER.folded(),
                            content_type="text/plain")
    return web.json_response(PROFILER.snapshot(top_n=top))
