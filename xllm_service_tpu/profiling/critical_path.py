"""Request critical-path attribution over federated span trees.

``/admin/trace/<id>`` (PR 9/13) shows a request's spans; this module
answers the question the spans only imply: *where did the TTFT go?*
:func:`critical_path` decomposes the window from request arrival at the
owner frontend to the first streamed token into exclusive stage waits —
admission, schedule, handoff (relay hop), dispatch wait, prefill,
failover, first delta — that sum exactly to the window by construction
(an event sweep charges every millisecond to exactly one stage).
:func:`aggregate_critical_paths` rolls per-request decompositions into
the fleet-level ``/admin/hotpath`` stage table.

The functions are pure over span *dicts* (``Span.to_dict`` /
``merge_fleet_spans`` output), so the same code serves a local trace, a
federated trace with relay + failover hops, and the hotpath aggregate.
"""

from __future__ import annotations

from typing import Any, Iterable, Optional

#: Exclusive TTFT stages, in causal order. Every sweep segment lands in
#: exactly one of these, so ``sum(stages_ms.values()) == ttft window``.
CRITICAL_STAGES = (
    "admission_wait",   # arrival -> first scheduler span starts
    "schedule",         # scheduler.schedule/template/tokenize/route/bind
    "handoff",          # relay hop: non-owner frontend.request forwarding
    "dispatch_wait",    # scheduled but no engine span covering yet
    "prefill",          # engine.prefill
    "failover",         # scheduler.failover re-routing
    "first_delta",      # prefill done -> first token observed at owner
)

#: Span points that claim sweep coverage, mapped to their stage.
#: frontend.request spans are NOT intervals — the owner-side one covers
#: the whole window and would swallow every gap; the relay hop is
#: instead charged as the gap from the root's start to the owner span's
#: start (see the sweep's gap rules).
_STAGE_OF = {
    "scheduler.schedule": "schedule",
    "scheduler.template": "schedule",
    "scheduler.tokenize": "schedule",
    "scheduler.route": "schedule",
    "scheduler.bind": "schedule",
    "scheduler.failover": "failover",
    "engine.prefill": "prefill",
}

#: Priority when intervals overlap: the most specific (latest-starting
#: wins first; ties break by this stage precedence, most specific last).
_STAGE_RANK = {stage: i for i, stage in enumerate(CRITICAL_STAGES)}


def _num(v: Any) -> Optional[float]:
    try:
        if v is None:
            return None
        return float(v)
    except (TypeError, ValueError):
        return None


def critical_path(spans: Iterable[dict]) -> Optional[dict]:
    """Decompose one trace's TTFT window into exclusive stage waits.

    Returns ``None`` when the trace has no root ``frontend.request``
    span or no TTFT observation (request never produced a first token).
    """
    spans = [s for s in spans if isinstance(s, dict)]
    if not spans:
        return None
    ids = {s.get("span_id") for s in spans if s.get("span_id")}
    fronts = [s for s in spans if s.get("point") == "frontend.request"]
    roots = [s for s in fronts
             if s.get("parent_span_id") not in ids and
             _num(s.get("start_ms")) is not None]
    if not roots:
        return None
    root = min(roots, key=lambda s: _num(s.get("start_ms")))

    # TTFT is observed on the owner-side frontend span (on a relayed
    # request that is a child hop, not the root).
    ttft_spans = [s for s in fronts
                  if _num((s.get("attrs") or {}).get("ttft_ms")) is not None
                  and _num(s.get("start_ms")) is not None]
    if not ttft_spans:
        return None
    ttft_src = min(ttft_spans, key=lambda s: _num(s.get("start_ms")))
    t0 = _num(root.get("start_ms"))
    t1 = _num(ttft_src.get("start_ms")) + \
        _num((ttft_src.get("attrs") or {}).get("ttft_ms"))
    if t1 <= t0:
        return None
    # A relayed request's TTFT is observed by the owner-side frontend
    # span, a child hop of the accepting frontend's relay root; the
    # forwarding leg is the window from the root's start to that span's.
    relayed = ttft_src is not root
    owner_start = _num(ttft_src.get("start_ms"))

    # Build clipped (start, end, stage) intervals from covering spans.
    intervals: list[tuple[float, float, str]] = []
    for s in spans:
        stage = _STAGE_OF.get(s.get("point"))
        if stage is None:
            continue
        a = _num(s.get("start_ms"))
        b = _num(s.get("end_ms"))
        if a is None:
            continue
        if b is None:
            b = t1   # still-open span covers to the end of the window
        a, b = max(a, t0), min(b, t1)
        if b > a:
            intervals.append((a, b, stage))

    # Event sweep: charge each segment to the latest-starting covering
    # interval (the most nested span wins), gaps to the causal filler.
    # owner_start is a gap-rule boundary (not an interval edge), so it
    # must split sweep segments too.
    points = sorted({t0, t1, *((owner_start,) if relayed else ()),
                     *[a for a, _, _ in intervals],
                     *[b for _, b, _ in intervals]})
    first_sched = min((a for a, _, st in intervals
                       if st in ("schedule", "failover")), default=None)
    first_prefill = min((a for a, _, st in intervals if st == "prefill"),
                        default=None)
    stages_ms = {stage: 0.0 for stage in CRITICAL_STAGES}
    segments: list[dict] = []
    for a, b in zip(points, points[1:]):
        if b <= t0 or a >= t1:
            continue
        covering = [(ia, _STAGE_RANK[st], st) for ia, ib, st in intervals
                    if ia <= a and ib >= b]
        if covering:
            stage = max(covering)[2]
        elif relayed and a < owner_start:
            stage = "handoff"
        elif first_sched is not None and a < first_sched:
            stage = "admission_wait"
        elif first_prefill is None or a < first_prefill:
            stage = "dispatch_wait"
        else:
            stage = "first_delta"
        stages_ms[stage] += b - a
        if segments and segments[-1]["stage"] == stage:
            segments[-1]["end_ms"] = b
        elif len(segments) < 64:
            segments.append({"stage": stage, "start_ms": a, "end_ms": b})

    ttft_ms = t1 - t0
    # Failover attempts live on the owner-side span (the scheduler sets
    # them there); on a relayed request the root is the relay hop.
    attrs = {**(root.get("attrs") or {}), **{
        k: v for k, v in (ttft_src.get("attrs") or {}).items()
        if v is not None}}
    return {
        "trace_id": root.get("trace_id"),
        "request_id": root.get("request_id"),
        "window_start_ms": t0,
        "ttft_ms": round(ttft_ms, 3),
        "relayed": relayed,
        "failover_attempts": int(_num(attrs.get("failover_attempts")) or 0),
        "stages_ms": {k: round(v, 3) for k, v in stages_ms.items()},
        "stage_share": {
            k: round(v / ttft_ms, 4) if ttft_ms else 0.0
            for k, v in stages_ms.items()},
        "segments": [
            {"stage": s["stage"],
             "start_ms": round(s["start_ms"] - t0, 3),
             "duration_ms": round(s["end_ms"] - s["start_ms"], 3)}
            for s in segments],
    }


def _quantile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


def aggregate_critical_paths(paths: Iterable[Optional[dict]]) -> dict:
    """Fleet stage table for ``/admin/hotpath``: per-stage mean/p50/p90
    milliseconds and mean TTFT share over recent decomposed requests."""
    rows = [p for p in paths if p]
    out: dict[str, Any] = {"requests": len(rows), "stages": {}}
    if not rows:
        return out
    ttfts = sorted(p["ttft_ms"] for p in rows)
    out["ttft_ms"] = {
        "mean": round(sum(ttfts) / len(ttfts), 3),
        "p50": round(_quantile(ttfts, 0.50), 3),
        "p90": round(_quantile(ttfts, 0.90), 3),
    }
    for stage in CRITICAL_STAGES:
        vals = sorted(p["stages_ms"].get(stage, 0.0) for p in rows)
        shares = [p["stage_share"].get(stage, 0.0) for p in rows]
        out["stages"][stage] = {
            "mean_ms": round(sum(vals) / len(vals), 3),
            "p50_ms": round(_quantile(vals, 0.50), 3),
            "p90_ms": round(_quantile(vals, 0.90), 3),
            "mean_share": round(sum(shares) / len(shares), 4),
        }
    return out
