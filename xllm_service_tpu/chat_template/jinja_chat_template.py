"""Jinja2 chat-template renderer (reference `jinja_chat_template.cpp`)."""

from __future__ import annotations

from typing import Any, Optional

import jinja2

# Generic ChatML-style fallback for models shipping no template.
DEFAULT_CHAT_TEMPLATE = (
    "{% for message in messages %}"
    "{{ '<|im_start|>' + message['role'] + '\n' + message['content'] + '<|im_end|>' + '\n' }}"
    "{% endfor %}"
    "{% if add_generation_prompt %}{{ '<|im_start|>assistant\n' }}{% endif %}"
)

# Reference placeholder for non-text content items
# (`jinja_chat_template.cpp:119-137` inserts "mm place holder").
MM_PLACEHOLDER = "<|multimodal_placeholder|>"


def _flatten_content(content: Any) -> str:
    """OpenAI content can be a string or a list of typed parts; flatten
    non-text parts to placeholders."""
    if content is None:
        return ""
    if isinstance(content, str):
        return content
    if isinstance(content, list):
        parts = []
        for item in content:
            if isinstance(item, dict):
                if item.get("type") == "text":
                    parts.append(item.get("text", ""))
                else:
                    parts.append(MM_PLACEHOLDER)
            else:
                parts.append(str(item))
        return "".join(parts)
    return str(content)


class JinjaChatTemplate:
    def __init__(self, template: Optional[str] = None,
                 bos_token: str = "", eos_token: str = ""):
        self._env = jinja2.Environment(
            loader=jinja2.BaseLoader(),
            trim_blocks=True, lstrip_blocks=True,
            extensions=["jinja2.ext.loopcontrols"],
        )
        # Helpers HF templates commonly use.
        self._env.filters["tojson"] = lambda v, **kw: __import__("json").dumps(v, **kw)
        self._env.globals["raise_exception"] = _raise_exception
        self._template = self._env.from_string(template or DEFAULT_CHAT_TEMPLATE)
        self._bos = bos_token
        self._eos = eos_token

    def apply(self, messages: list[dict[str, Any]],
              tools: Optional[list[dict[str, Any]]] = None,
              chat_template_kwargs: Optional[dict[str, Any]] = None,
              add_generation_prompt: bool = True) -> str:
        """Render the prompt (reference `jinja_chat_template.cpp:105-117`:
        messages + tools + extra kwargs, add_generation_prompt=true)."""
        norm_messages = [
            {**m, "content": _flatten_content(m.get("content"))}
            for m in messages
        ]
        ctx: dict[str, Any] = {
            "messages": norm_messages,
            "add_generation_prompt": add_generation_prompt,
            "bos_token": self._bos,
            "eos_token": self._eos,
        }
        if tools:
            ctx["tools"] = tools
        if chat_template_kwargs:
            ctx.update(chat_template_kwargs)
        return self._template.render(**ctx)


def _raise_exception(msg: str):
    raise jinja2.TemplateError(msg)
