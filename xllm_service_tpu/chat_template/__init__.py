"""Jinja chat templating.

Parity: reference `chat_template/jinja_chat_template.{h,cpp}` (minja-based;
SURVEY.md §2.8): renders the model's `chat_template` with messages, a tools
array, and extra `chat_template_kwargs` context, with
`add_generation_prompt=true` (`jinja_chat_template.cpp:26-37,105-117`).
Multimodal content parts are flattened to text + placeholders
(`jinja_chat_template.cpp:119-137`).
"""

from .jinja_chat_template import (
    DEFAULT_CHAT_TEMPLATE,
    JinjaChatTemplate,
    MM_PLACEHOLDER,
)

__all__ = ["JinjaChatTemplate", "DEFAULT_CHAT_TEMPLATE", "MM_PLACEHOLDER"]
