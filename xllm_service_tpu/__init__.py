"""xllm_service_tpu — a TPU-native LLM serving-orchestration framework.

Brand-new implementation of the capability surface of
jd-opensource/xllm-service (reference surveyed in SURVEY.md), designed
TPU-first:

- **Orchestration plane**: OpenAI-compatible HTTP frontend, fleet management
  with lease/incarnation failure detection, PD-disaggregated routing with
  dynamic role flipping, global prefix-KV-cache-aware + SLO-aware load
  balancing, master HA — mirrors the behavioral contract of the reference's
  `xllm_service/` C++ service (see SURVEY.md §2).
- **Engine plane**: JAX/XLA/Pallas continuous-batching runtime with a paged
  KV cache in HBM, prefill/decode as separately compiled jit programs over a
  `jax.sharding.Mesh`, Pallas paged-attention decode kernels, and ICI/DCN
  KV handoff — replaces the reference's empty `third_party/xllm` engine
  (reference: SURVEY.md §0, §7).
"""

__version__ = "0.1.0"
