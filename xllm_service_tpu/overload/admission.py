"""Admission control + priority shedding: the bounded gate in front of
the schedule executor.

Before this gate, a burst beyond fleet capacity queued unboundedly —
first in the schedule executor, then in the engines — until TTFT
collapsed for EVERY request (the PR-11 bench's static control pinned at
burn 100/100 for the whole burst). The gate bounds the in-flight set at
a per-priority watermark derived from the live fleet size, so a request
that cannot be served within its SLO is refused in microseconds with a
``429`` + ``Retry-After`` instead of being served in seconds:

- the ADMISSION LIMIT is ``admission_max_inflight_per_instance ×
  live_instances`` (live from the lock-free RCU routing snapshot — the
  limit tracks scale-out/in automatically);
- **batch** priority is admitted only below ``admission_batch_watermark
  × limit``, and not at all while the SLO burn is hot (brownout state)
  — interactive traffic keeps the full limit;
- **interactive** priority is shed only when the limit itself is hit.

The decision is a pure function (:func:`decide_admission`) over an
immutable input row — unit-testable as a table, like the autoscaler
kernel. The controller adds the mutable half: the in-flight count
(acquired at admission, released by the scheduler's exactly-once exit
path), per-second shed buckets (the shed RATE feeds the autoscaler
kernel so shedding and scale-out cooperate rather than mask each
other), and the counters behind ``GET /admin/overload``.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Optional

from ..common.slo import WindowCounts
from ..devtools import lifecycle as _lifecycle
from ..devtools import ownership as _ownership
from ..devtools.locks import make_lock
from .deadline import PRIORITY_BATCH

#: Sliding window for the shed-rate signal the autoscaler consumes.
_SHED_WINDOW_S = 10.0


@dataclass(frozen=True)
class AdmissionInputs:
    """One admission decision's immutable view."""

    pending: int = 0               # in-flight admitted requests
    live: int = 0                  # schedulable instances (RCU snapshot)
    per_instance_limit: int = 0    # 0 = admission control disabled
    batch_watermark: float = 0.5
    burn_hot: bool = False         # SLO burn breaching (brownout state)
    priority: str = "interactive"


def decide_admission(inp: AdmissionInputs) -> tuple[bool, str]:
    """(admit, reason). Pure — no clocks, no locks."""
    if inp.per_instance_limit <= 0:
        return True, "admission control disabled"
    limit = inp.per_instance_limit * max(1, inp.live)
    if inp.pending >= limit:
        return False, (f"admission queue full ({inp.pending}/{limit} "
                       f"over {max(1, inp.live)} live instance(s))")
    if inp.priority == PRIORITY_BATCH:
        cap = 0 if inp.burn_hot else int(limit * inp.batch_watermark)
        if inp.pending >= cap:
            return False, (
                "batch shed: SLO burn hot — batch admission closed"
                if inp.burn_hot else
                f"batch shed: over batch watermark ({inp.pending}/{cap} "
                f"of limit {limit})")
    return True, "admitted"


@_ownership.verify_state
class AdmissionController:
    """Process-global admission gate. ``try_admit`` runs on the request
    hot path: one leaf-lock hold around integer math and a deque
    append — no RPC, no fleet walk (``live`` comes in from the caller's
    snapshot read)."""

    def __init__(self) -> None:
        self._lock = make_lock("overload.admission", order=832)  # lock-order: 832
        self._per_instance_limit = 0
        self._batch_watermark = 0.5
        self._retry_after_s = 1.0
        self._pending = 0
        self._admitted_total = 0
        self._shed_total: dict[str, int] = {}
        # Rolling shed window (the shared per-second bucket helper from
        # common/slo.py; mutated only under self._lock).
        self._shed_window = WindowCounts(_SHED_WINDOW_S)

    def configure(self, per_instance_limit: int = 0,
                  batch_watermark: float = 0.5,
                  retry_after_s: float = 1.0) -> None:
        with self._lock:
            self._per_instance_limit = max(0, int(per_instance_limit))
            self._batch_watermark = min(1.0, max(0.0, batch_watermark))
            self._retry_after_s = max(0.0, retry_after_s)

    def reset(self) -> None:
        """Test hook: zero the counters and the in-flight count."""
        with self._lock:
            self._pending = 0
            self._admitted_total = 0
            self._shed_total = {}
            self._shed_window = WindowCounts(_SHED_WINDOW_S)
        _lifecycle.note_reset("admission-slot")

    @property
    def enabled(self) -> bool:
        return self._per_instance_limit > 0

    # ------------------------------------------------------------- hot path
    def try_admit(self, priority: str, live: int,
                  burn_hot: bool) -> tuple[bool, str, float]:
        """(admit, reason, retry_after_s). Admission increments the
        in-flight count; the caller MUST pair every admit with exactly
        one :meth:`release` (the scheduler's exit accounting)."""
        with self._lock:
            inp = AdmissionInputs(
                pending=self._pending, live=live,
                per_instance_limit=self._per_instance_limit,
                batch_watermark=self._batch_watermark,
                burn_hot=burn_hot, priority=priority)
            admit, reason = decide_admission(inp)
            if admit:
                self._pending += 1
                self._admitted_total += 1
                _lifecycle.note_acquire("admission-slot")
            else:
                self._shed_total[priority] = \
                    self._shed_total.get(priority, 0) + 1
                self._shed_window.record(bad=True)
            return admit, reason, self._retry_after_s

    def release(self) -> None:
        """One admitted request exited (any path — finish, error,
        cancel). Clamped at zero: direct-scheduler callers that never
        admitted must not be able to underflow the gate."""
        with self._lock:
            self._pending = max(0, self._pending - 1)
            _lifecycle.note_release("admission-slot")

    # -------------------------------------------------------------- signals
    def shed_rate(self, now: Optional[float] = None) -> float:
        """Sheds per second over the recent window — the autoscaler
        kernel's coupling signal (shedding is unserved demand: it must
        drive scale-out, not mask the need for it)."""
        now = now if now is not None else time.time()
        with self._lock:
            _, shed = self._shed_window.counts(now)
        return shed / _SHED_WINDOW_S

    def pending(self) -> int:
        with self._lock:
            return self._pending

    def report(self) -> dict[str, Any]:
        # The shed rate re-takes the (non-reentrant) leaf lock — compute
        # it before the locked field snapshot.
        rate = self.shed_rate()
        with self._lock:
            return {
                "enabled": self._per_instance_limit > 0,
                "per_instance_limit": self._per_instance_limit,
                "batch_watermark": self._batch_watermark,
                "retry_after_s": self._retry_after_s,
                "pending": self._pending,
                "admitted_total": self._admitted_total,
                "shed_total": dict(self._shed_total),
                "shed_rate_per_s": rate,
            }


#: Process-global gate; the HTTP service configures it from options.
ADMISSION = AdmissionController()
