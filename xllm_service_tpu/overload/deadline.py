"""Per-request deadline + priority parsing (pure helpers, no state).

A deadline enters the system as a RELATIVE budget — the body's OpenAI-
client-style ``timeout`` (seconds) or the ``x-request-deadline-ms``
header (milliseconds), falling back to
``ServiceOptions.default_request_deadline_ms`` — and is immediately
converted to an ABSOLUTE wall-clock ms value (``Request.deadline_ms``).
Absolute is what propagates: the enriched engine payload carries
``deadline_ms`` and the multimaster relay forwards it as the
``x-xllm-deadline-ms`` header, so every downstream hop naturally
"subtracts elapsed budget" by comparing against its own clock instead
of re-starting the budget from its own arrival time (which would extend
the deadline by the relay/queueing delay it was meant to bound).

Priority classes are two-valued by design (interactive | batch): the
admission gate's per-priority watermarks only need "sheddable first"
vs "shed last", and two classes keep the watermark math and the metric
cardinality trivial. ``offline`` requests default to batch.
"""

from __future__ import annotations

from typing import Any, Mapping, Optional

from ..common.types import now_ms

#: Client-supplied relative deadline budget in milliseconds.
DEADLINE_HEADER = "x-request-deadline-ms"
#: Relay-hop ABSOLUTE deadline (epoch ms) — internal, set by the
#: multimaster handoff relay so the owner enforces the original budget.
ABS_DEADLINE_HEADER = "x-xllm-deadline-ms"
#: Client-supplied priority class.
PRIORITY_HEADER = "x-request-priority"

PRIORITY_INTERACTIVE = "interactive"
PRIORITY_BATCH = "batch"


def parse_deadline_ms(body: Mapping[str, Any],
                      headers: Mapping[str, str],
                      default_ms: float,
                      now: Optional[int] = None) -> int:
    """Absolute deadline (epoch ms; 0 = none) for a new accept. Header
    wins over body ``timeout`` wins over the configured default; a
    malformed value falls through to the next source rather than
    failing the request (a deadline is a bound, not an argument)."""
    now = now if now is not None else now_ms()
    raw = headers.get(DEADLINE_HEADER)
    if raw is not None:
        try:
            budget = float(raw)
            if budget > 0:
                return now + int(budget)
        except (TypeError, ValueError):
            pass
    timeout = body.get("timeout")
    if timeout is not None and not isinstance(timeout, bool):
        try:
            budget_s = float(timeout)
            if budget_s > 0:
                return now + int(budget_s * 1000)
        except (TypeError, ValueError):
            pass
    if default_ms and default_ms > 0:
        return now + int(default_ms)
    return 0


def parse_priority(body: Mapping[str, Any],
                   headers: Mapping[str, str]) -> str:
    """interactive | batch. Header wins over the body's
    ``priority_class``; unknown values clamp to interactive (a typo'd
    priority must not silently demote someone to sheddable); requests
    marked ``offline`` default to batch."""
    raw = headers.get(PRIORITY_HEADER) or body.get("priority_class") or ""
    if isinstance(raw, str) and raw.lower() == PRIORITY_BATCH:
        return PRIORITY_BATCH
    if not raw and body.get("offline"):
        return PRIORITY_BATCH
    return PRIORITY_INTERACTIVE


def remaining_ms(deadline_ms: int, now: Optional[int] = None) -> float:
    """Budget left (ms); +inf when no deadline is set."""
    if not deadline_ms:
        return float("inf")
    return float(deadline_ms - (now if now is not None else now_ms()))


def deadline_expired(deadline_ms: int, now: Optional[int] = None) -> bool:
    return bool(deadline_ms) and \
        (now if now is not None else now_ms()) > deadline_ms
