"""Brownout mode: degrade before refusing.

Between "serving everything at full quality" and "shedding load" there
is a cheaper middle gear: serve everything, but serve less of the
optional parts. The brownout controller watches the SLO burn-rate
monitor (common/slo.py) on the scheduler's sync cadence and flips a
process-wide degradation state when ANY objective breaches on BOTH
windows (the same multi-window rule that gates paging and autoscaling):

- **batch-priority ``max_tokens`` is clamped** to
  ``brownout_batch_max_tokens`` — bulk work finishes sooner and returns
  decode capacity to interactive traffic without refusing anyone;
- **optional work is shed**: trace head-sampling drops to
  ``brownout_trace_sample_rate`` (tail-based keep still promotes
  anomalies, so debuggability degrades, not disappears).

Brownout LIFTS with hysteresis: ``brownout_recover_ticks`` consecutive
non-breaching sync passes (a single good tick inside a burst must not
flap the state). Every transition is logged with the burn numbers that
caused it, captured as a flight-recorder anomaly bundle, and kept in a
bounded transition log behind ``GET /admin/overload``.

Each frontend runs its own controller off its own burn monitor — like
admission, brownout protects the local process; no coordination writes,
no write-lease gating.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Callable, Optional

from ..devtools import ownership as _ownership
from ..devtools.locks import make_lock
from ..utils import get_logger
from .deadline import PRIORITY_BATCH

logger = get_logger(__name__)


@_ownership.verify_state
class BrownoutController:
    """Process-global degradation state. ``active()`` /
    ``clamp_max_tokens()`` are the hot-path reads: one attribute load
    (GIL-atomic bool), no lock."""

    def __init__(self) -> None:
        self._lock = make_lock("overload.brownout", order=834)  # lock-order: 834
        self._enabled = True
        self._batch_max_tokens = 32
        self._recover_ticks = 2
        self._trace_sample_rate = 0.0
        self._restore_rate_fn: Optional[Callable[[], float]] = None
        self._active = False
        self._since_s = 0.0
        self._recover_streak = 0
        self._entered_total = 0
        self._log: deque = deque(maxlen=32)

    def configure(self, enabled: bool = True, batch_max_tokens: int = 32,
                  recover_ticks: int = 2, trace_sample_rate: float = 0.0,
                  restore_rate_fn: Optional[Callable[[], float]] = None
                  ) -> None:
        """`restore_rate_fn` returns the sampling rate to restore on
        lift (a callable, not a value: /admin/config may have changed
        the configured rate while brownout held it down)."""
        with self._lock:
            self._enabled = bool(enabled)
            self._batch_max_tokens = max(1, int(batch_max_tokens))
            self._recover_ticks = max(1, int(recover_ticks))
            self._trace_sample_rate = min(1.0, max(0.0, trace_sample_rate))
            self._restore_rate_fn = restore_rate_fn

    def reset(self) -> None:
        """Test hook: back to NORMAL without side effects."""
        with self._lock:
            self._active = False
            self._recover_streak = 0
            self._entered_total = 0
            self._log.clear()

    # ------------------------------------------------------------- hot path
    def active(self) -> bool:
        return self._active

    def clamp_max_tokens(self, priority: str, max_tokens: int) -> int:
        """Brownout cap for batch-priority work (identity for
        interactive traffic and outside brownout)."""
        if self._active and priority == PRIORITY_BATCH:
            return min(max_tokens, self._batch_max_tokens)
        return max_tokens

    # ------------------------------------------------------------ sync tick
    def tick(self, report: Optional[dict[str, Any]] = None,
             now: Optional[float] = None) -> bool:
        """One evaluation pass (scheduler sync cadence). `report` is an
        SLO_MONITOR report (fetched here when not supplied — callers on
        the sync thread pass the one they already computed). Returns the
        post-tick active state."""
        if not self._enabled:
            return False
        if report is None:
            from ..common.slo import SLO_MONITOR

            report = SLO_MONITOR.report()
        breaching = sorted(report.get("breaching", ()))
        worst = report.get("worst_fast_burn_rate", 0.0)
        now = now if now is not None else time.monotonic()
        transition: Optional[dict[str, Any]] = None
        with self._lock:
            if breaching and not self._active:
                self._active = True
                self._since_s = now
                self._recover_streak = 0
                self._entered_total += 1
                transition = self._log_locked(
                    "enter", breaching, worst,
                    f"objectives {','.join(breaching)} breaching on both "
                    f"burn windows (worst fast burn {worst:.1f}); clamping "
                    f"batch max_tokens to {self._batch_max_tokens}, trace "
                    f"sampling to {self._trace_sample_rate}")
            elif self._active and not breaching:
                self._recover_streak += 1
                if self._recover_streak >= self._recover_ticks:
                    self._active = False
                    transition = self._log_locked(
                        "exit", breaching, worst,
                        f"burn recovered for {self._recover_streak} "
                        f"consecutive tick(s); restoring full service")
                    self._recover_streak = 0
            elif self._active:
                self._recover_streak = 0
        if transition is not None:
            self._apply_transition(transition)
        return self._active

    def _log_locked(self, kind: str, breaching: list, worst: float,
                    reason: str) -> dict[str, Any]:
        rec = {"ts_s": round(time.time(), 3), "kind": kind,
               "breaching": list(breaching),
               "worst_fast_burn": round(worst, 3), "reason": reason}
        self._log.append(rec)
        return rec

    def _apply_transition(self, rec: dict[str, Any]) -> None:
        """Side effects OUTSIDE the lock: tracer reconfig + flight
        recorder capture + logging (all leaf-locked elsewhere)."""
        from ..common.flightrecorder import RECORDER
        from ..common.tracing import TRACER

        entering = rec["kind"] == "enter"
        if entering:
            logger.warning("BROWNOUT entered: %s", rec["reason"])
            TRACER.configure(sample_rate=self._trace_sample_rate)
        else:
            logger.info("brownout lifted: %s", rec["reason"])
            restore = self._restore_rate_fn
            TRACER.configure(
                sample_rate=restore() if restore is not None else 1.0)
        RECORDER.record("brownout", detail=dict(rec))

    # ------------------------------------------------------------ reporting
    def report(self) -> dict[str, Any]:
        with self._lock:
            return {
                "enabled": self._enabled,
                "active": self._active,
                "batch_max_tokens": self._batch_max_tokens,
                "recover_ticks": self._recover_ticks,
                "brownout_trace_sample_rate": self._trace_sample_rate,
                "recover_streak": self._recover_streak,
                "entered_total": self._entered_total,
                "transitions": list(self._log),
            }


#: Process-global brownout state; the HTTP service configures it, the
#: scheduler's sync loop ticks it.
BROWNOUT = BrownoutController()
