"""Overload-hardening plane (docs/robustness.md).

The serving stack's graceful-degradation layer, built from four
cooperating mechanisms — each independently togglable, all reported at
``GET /admin/overload``:

- **End-to-end deadlines** (:mod:`.deadline`): a per-request deadline
  (body ``timeout`` seconds / ``x-request-deadline-ms`` header /
  ``ServiceOptions.default_request_deadline_ms``) is carried as an
  ABSOLUTE wall-clock ms value through the enriched engine payload and
  the multimaster handoff wire, and enforced at every hop — admission
  rejects already-expired work, the scheduler cancels mid-stream
  expiries, and engines stop decoding past-deadline requests.
- **Admission control + priority shedding** (:mod:`.admission`): a
  bounded admission gate in front of the schedule executor with
  per-priority (``x-request-priority``: interactive/batch) watermarks
  derived from the RCU routing snapshot's live fleet size and the SLO
  burn state; rejected requests get a fast 429 with ``Retry-After``
  instead of queueing, and the shed rate feeds the autoscaler kernel so
  shedding and scale-out cooperate.
- **Brownout mode** (:mod:`.brownout`): when both SLO burn windows
  breach, degrade before refusing — batch-priority ``max_tokens`` is
  clamped and optional work (trace head-sampling) is shed, lifting as
  burn recovers; every transition is logged with reasons and captured
  by the flight recorder.
- **Global retry budget** (:mod:`.retry_budget`): one token bucket
  shared by the failover and multimaster-relay retry paths caps retry
  amplification during partial outages (per-instance circuit breakers
  live in :mod:`..rpc.breaker`).

All four state holders are process-global singletons configured by the
HTTP service from :class:`..common.config.ServiceOptions` — the same
pattern as ``SLO_MONITOR`` / ``RECORDER``.
"""

from .admission import ADMISSION, AdmissionController, decide_admission
from .brownout import BROWNOUT, BrownoutController
from .deadline import (
    ABS_DEADLINE_HEADER,
    DEADLINE_HEADER,
    PRIORITY_BATCH,
    PRIORITY_HEADER,
    PRIORITY_INTERACTIVE,
    deadline_expired,
    parse_deadline_ms,
    parse_priority,
    remaining_ms,
)
from .retry_budget import RETRY_BUDGET, RetryBudget

__all__ = [
    "ADMISSION",
    "AdmissionController",
    "decide_admission",
    "BROWNOUT",
    "BrownoutController",
    "RETRY_BUDGET",
    "RetryBudget",
    "DEADLINE_HEADER",
    "ABS_DEADLINE_HEADER",
    "PRIORITY_HEADER",
    "PRIORITY_INTERACTIVE",
    "PRIORITY_BATCH",
    "parse_deadline_ms",
    "parse_priority",
    "remaining_ms",
    "deadline_expired",
]
