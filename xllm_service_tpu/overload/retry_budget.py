"""Global retry budget: one token bucket capping retry amplification.

During a partial outage every retry layer is locally rational — the
failover loop replays dead instances' requests, the multimaster relay
re-owns streams off dead frontends — but their PRODUCT is not: N layers
of "try 3 times" turn one unit of offered load into 3^N units of fleet
load exactly when the fleet can least afford it (the classic retry-storm
amplification; Google SRE "Handling Overload"). The budget makes the
total retry volume proportional to the total request volume:

- every accepted request DEPOSITS ``retry_budget_ratio`` tokens
  (capped at ``retry_budget_cap`` — the burst allowance);
- every failover re-dispatch attempt and every relay re-ownership
  recovery WITHDRAWS one token first, and fails the request fast when
  the bucket is empty.

So steady-state retries are bounded at ~ratio × request rate, a healthy
fleet keeps a full burst allowance, and a mass failure degrades into
bounded, budgeted recovery instead of a self-sustaining storm. Channel-
level transport retries (rpc/channel.py) stay outside the budget: they
are already bounded per call and back off with jitter; the budget
governs the layers that multiply them.

``retry_budget_cap <= 0`` disables the budget (every spend allowed).
"""

from __future__ import annotations

from typing import Any

from ..devtools import lifecycle as _lifecycle
from ..devtools import ownership as _ownership
from ..devtools.locks import make_lock


@_ownership.verify_state
class RetryBudget:
    """Process-global token bucket. Both paths are a leaf-lock hold
    around float math."""

    def __init__(self) -> None:
        self._lock = make_lock("overload.retry_budget", order=838)  # lock-order: 838
        self._ratio = 0.1
        self._cap = 50.0
        self._tokens = 50.0
        self._spent_total = 0
        self._denied_total = 0

    def configure(self, ratio: float = 0.1, cap: float = 50.0) -> None:
        """Re-arm with a full bucket and fresh counters (a healthy boot
        starts with its whole burst allowance)."""
        with self._lock:
            self._ratio = max(0.0, ratio)
            self._cap = max(0.0, cap)
            self._tokens = self._cap
            self._spent_total = 0
            self._denied_total = 0

    def reset(self) -> None:
        """Test hook: refill and zero the counters."""
        with self._lock:
            self._tokens = self._cap
            self._spent_total = 0
            self._denied_total = 0
        _lifecycle.note_reset("retry-budget")

    @property
    def enabled(self) -> bool:
        return self._cap > 0

    def note_request(self) -> None:
        """One accepted request: deposit the per-request retry
        allowance."""
        with self._lock:
            if self._cap > 0:
                self._tokens = min(self._cap, self._tokens + self._ratio)
                _lifecycle.note_release("retry-budget")

    def try_spend(self, n: float = 1.0) -> bool:
        """Withdraw `n` tokens for a retry; False = budget exhausted,
        the caller must fail fast instead of retrying."""
        with self._lock:
            if self._cap <= 0:
                return True
            if self._tokens >= n:
                self._tokens -= n
                self._spent_total += 1
                _lifecycle.note_acquire("retry-budget")
                return True
            self._denied_total += 1
            return False

    def tokens(self) -> float:
        with self._lock:
            return self._tokens if self._cap > 0 else float("inf")

    def report(self) -> dict[str, Any]:
        with self._lock:
            return {
                "enabled": self._cap > 0,
                "ratio": self._ratio,
                "cap": self._cap,
                "tokens": round(self._tokens, 3),
                "spent_total": self._spent_total,
                "denied_total": self._denied_total,
            }


#: Process-global budget shared by failover + relay recovery.
RETRY_BUDGET = RetryBudget()
