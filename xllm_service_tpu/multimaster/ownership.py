"""Sticky request ownership over the live service-replica set.

Every service replica registers under ``XLLM:SERVICE:<rpc_addr>`` with a
TTL lease (scheduler ctor); this router watches that prefix and publishes
the live member set as an immutable tuple (RCU, like instance_mgr's
``RoutingSnapshot``). Ownership of a request is decided by rendezvous
(highest-random-weight) hashing of its id over the members:

- **deterministic** — any node resolves the same owner from the id alone
  (no ownership table to replicate),
- **minimally disruptive** — when a master dies, only the requests it
  owned move, each to its deterministic successor (the next-highest
  scoring survivor); everyone else's ownership is untouched. That is the
  re-ownership rule the handoff relay uses to drain a dead owner's
  in-flight requests onto survivors.

The accepting frontend *mines* the ids it generates so that, in the
common case, it owns what it accepts (expected ``N`` draws over an
``N``-replica plane — one blake2b per member per draw) and no forward
hop is paid; the rendezvous map then only has to carry the exceptions:
client-pinned ``ownership_key`` affinity, membership races, and
owner-death recovery.

``owner_of`` runs on the request hot path → registered in xlint's
``HOT_PATH_FUNCTIONS``.
"""

from __future__ import annotations

import threading
from hashlib import blake2b
from typing import Callable, Iterable, Optional

from ..common import native as _native
from ..coordination.base import CoordinationClient, KeyEvent, WatchEventType
from ..devtools import ownership as _ownership
from ..devtools import rcu
from ..devtools.locks import make_lock
from ..rpc import MASTER_KEY, SERVICE_KEY_PREFIX
from ..utils import generate_service_request_id, get_logger

logger = get_logger(__name__)

#: Bounded id-mining draws. P(all misses) = (1-1/N)^tries — at N=8 still
#: under 2%; a miss just means this request pays one handoff hop.
MINE_TRIES = 32

#: Telemetry-ingest shard salt: heartbeat/LOADMETRICS ownership of an
#: instance hashes ``<member>|hb|<instance name>`` so the telemetry shard
#: map is independent of (but as deterministic as) request ownership.
#: Engines compute the same owner from the mirrored SERVICE membership —
#: the salt is the one constant both sides must share.
TELEMETRY_SALT = "hb"


def _rendezvous_score(member: str, key: str) -> int:
    return int.from_bytes(
        blake2b(f"{member}|{key}".encode(), digest_size=8).digest(), "big")


def rendezvous_owner(members: Iterable[str], key: str,
                     exclude: Iterable[str] = ()) -> str:
    """Highest-random-weight owner of ``key`` over ``members`` (""
    when no member survives ``exclude``). Module-level so the ENGINE
    side (agent heartbeat routing, fake engine) resolves the same owner
    from a mirrored member list without an OwnershipRouter instance;
    ``exclude`` is the deterministic-successor rule the handoff relay
    uses (multimaster/handoff.py ``_recover``)."""
    excluded = set(exclude)
    if excluded:
        members = [m for m in members if m not in excluded]
    elif not isinstance(members, (tuple, list)):
        members = list(members)
    # One native call walks the whole member set (libhotcore; identical
    # winner — blake2b-8 big-endian scores, first strict max).
    best = _native.rendezvous(members, key)
    if best is not _native.MISS:
        return best
    best, best_score = "", -1
    for m in members:
        s = _rendezvous_score(m, key)
        if s > best_score:
            best, best_score = m, s
    return best


def telemetry_owner(members: Iterable[str], instance_name: str,
                    exclude: Iterable[str] = ()) -> str:
    """The master that owns an instance's heartbeat/load ingest under
    the telemetry shard map ("" when no members survive)."""
    return rendezvous_owner(members, f"{TELEMETRY_SALT}|{instance_name}",
                            exclude)


class TelemetryOwnerResolver:
    """ENGINE-side owner resolution for the multiplexed telemetry
    session: polls the SERVICE membership (cached — one get_prefix per
    ``cache_s``, amortized across every heartbeat and delta flush),
    applies the shared rendezvous map to this instance's name, and
    honors observed-dead exclusions (`note_failure`) until membership
    catches up — the engine-side mirror of the handoff relay's
    deterministic-successor recovery. Falls back to the elected master
    when no membership records exist (legacy / bootstrap).

    Thread contract: called from the heartbeat thread and the streamer
    thread; all state updates are single-assignment tuple/dict stores
    (GIL-atomic), and a stale cached answer is self-correcting within
    one cache window."""

    FAILURE_TTL_S = 10.0

    def __init__(self, coord, instance_name: str, cache_s: float = 2.0,
                 hold_last_owner: bool = True):
        self._coord = coord
        self._name = instance_name
        self._cache_s = cache_s
        self._cached: tuple[str, float] = ("", 0.0)
        self._failed: dict[str, float] = {}
        # Static stability: during a total coordination outage both the
        # membership read and the MASTER_KEY fallback come back empty —
        # with nothing else to go on, keep reporting the last owner that
        # DID resolve, so heartbeats/deltas keep flowing over the
        # (outage-immune) telemetry sessions instead of going silent.
        # note_failure still overrides: an owner observed dead is dead.
        self._hold_last_owner = hold_last_owner
        self._last_good = ""

    def __call__(self) -> str:
        import time

        now = time.monotonic()
        owner, expires = self._cached
        if owner and now < expires:
            return owner
        try:
            members = [k[len(SERVICE_KEY_PREFIX):]
                       for k in self._coord.get_prefix(SERVICE_KEY_PREFIX)
                       if k != MASTER_KEY]
        except Exception:  # noqa: BLE001  # xlint: allow-broad-except(a coordination blip degrades to the cached/master fallback; the next window re-resolves)
            members = []
        exclude = {o for o, ts in self._failed.items()
                   if now - ts < self.FAILURE_TTL_S}
        owner = telemetry_owner(members, self._name, exclude)
        if not owner:
            try:
                owner = self._coord.get(MASTER_KEY) or ""
            except Exception:  # noqa: BLE001  # xlint: allow-broad-except(same degradation contract as the membership read above)
                owner = ""
        if owner:
            self._last_good = owner
        elif self._hold_last_owner and \
                self._last_good not in exclude:
            owner = self._last_good
        self._cached = (owner, now + self._cache_s)
        return owner

    def note_failure(self, owner: str) -> None:
        """The caller observed this owner dead (connect/POST failure):
        exclude it and drop the cache so the next resolution lands on
        the rendezvous successor immediately."""
        import time

        self._failed[owner] = time.monotonic()
        self._cached = ("", 0.0)

    def pin(self, owner: str) -> None:
        """A master answered a beat with an authoritative `owner` hint
        (its view of the shard map — fresher than our mirrored
        membership on a race): adopt it for one cache window."""
        import time

        if owner:
            self._cached = (owner, time.monotonic() + self._cache_s)


@_ownership.verify_state
class OwnershipRouter:
    """Rendezvous-hash request→master ownership (lock-free reads)."""

    def __init__(self, coord: CoordinationClient, self_addr: str,
                 enabled: bool = True, mine_ids: bool = True,
                 start_watch: bool = True):
        self._coord = coord
        self.self_addr = self_addr
        self.enabled = enabled
        self.mine_ids = mine_ids
        # Writers (watch callbacks, self-addr updates) serialize here and
        # publish an immutable sorted tuple; readers never take the lock.
        self._lock = make_lock("multimaster.ownership", order=28)  # lock-order: 28
        self._addrs: set[str] = {self_addr}
        self._members: tuple[str, ...] = (self_addr,)
        self.mined = 0          # ids mined to self-ownership
        self.mine_misses = 0    # draws exhausted -> foreign owner accepted
        # Telemetry-shard verdict memo: (member tuple it was computed
        # against, {instance name -> owner addr}). Keyed by IDENTITY of
        # the RCU-published member tuple — _publish_locked always builds
        # a fresh tuple, so any membership change invalidates the whole
        # memo on the next read without coordination (the "membership
        # epoch" is the tuple object itself).
        self._own_cache: tuple[tuple[str, ...], dict] = (self._members, {})
        self._watch_id: Optional[int] = None
        if enabled and start_watch:
            self._watch_id = coord.add_watch(SERVICE_KEY_PREFIX,
                                             self._on_service_event)
            self._bootstrap()

    # ------------------------------------------------------------ membership
    def _bootstrap(self) -> None:
        addrs = {k[len(SERVICE_KEY_PREFIX):]
                 for k in self._coord.get_prefix(SERVICE_KEY_PREFIX)
                 if k != MASTER_KEY}
        with self._lock:
            self._addrs |= addrs
            self._publish_locked()

    def _on_service_event(self, events: list[KeyEvent], _prefix: str) -> None:
        with self._lock:
            for ev in events:
                if ev.key == MASTER_KEY:
                    continue   # election key shares the prefix
                addr = ev.key[len(SERVICE_KEY_PREFIX):]
                if ev.type == WatchEventType.PUT:
                    self._addrs.add(addr)
                elif addr != self.self_addr:
                    # Self stays a member even through a lease blip: this
                    # process is alive by construction, and dropping it
                    # would stampede every mined id into handoffs.
                    self._addrs.discard(addr)
            self._publish_locked()

    def _publish_locked(self) -> None:
        self._members = rcu.publish(tuple(sorted(self._addrs)),
                                    "ownership.members")

    def update_self_addr(self, addr: str) -> None:
        """Follow the scheduler's post-bind re-registration (ephemeral
        ports are only known after the RPC site binds)."""
        with self._lock:
            self._addrs.discard(self.self_addr)
            with _ownership.escape("post-bind re-registration: rebinds "
                                   "the init-only self_addr once, before "
                                   "traffic"):
                self.self_addr = addr
            self._addrs.add(addr)
            self._publish_locked()

    def members(self) -> tuple[str, ...]:
        """Live service-replica addresses (lock-free, immutable)."""
        return self._members

    # ------------------------------------------------------------- ownership
    _score = staticmethod(_rendezvous_score)

    def owner_of(self, key: str,
                 exclude: Iterable[str] = ()) -> str:
        """The owning master's rpc address for a request id (or explicit
        affinity key). ``exclude`` drops members the caller has observed
        dead but whose lease has not lapsed yet — the result is the
        deterministic rendezvous successor. Falls back to self when the
        plane is empty or ownership is disabled."""
        if not self.enabled:
            return self.self_addr
        members = self._members
        if exclude:
            excluded = set(exclude)
            members = tuple(m for m in members if m not in excluded)
        if not members:
            return self.self_addr
        if len(members) == 1:
            return members[0]
        best = _native.rendezvous(members, key)
        if best is not _native.MISS:
            return best
        best, best_score = members[0], -1
        for m in members:
            s = self._score(m, key)
            if s > best_score:
                best, best_score = m, s
        return best

    def is_self(self, key: str, exclude: Iterable[str] = ()) -> bool:
        return self.owner_of(key, exclude) == self.self_addr

    # ---------------------------------------------------- telemetry shard map
    #: Verdict-memo safety bound: far above any real fleet's instance
    #: count; a runaway name space (chaos drills mint random names)
    #: resets the memo instead of growing it.
    OWN_CACHE_MAX = 65536

    def instance_owner(self, instance_name: str,
                       exclude: Iterable[str] = ()) -> str:
        """The master owning an instance's heartbeat/load ingest
        (telemetry shard map; falls back to self when ownership is
        disabled or the plane is empty). Lock-free, and memoized per
        (published member tuple, instance): every heartbeat of every
        instance consults this verdict — often twice (in-lock ingest
        gate + bare-beat kv relay) — so the rendezvous walk runs once
        per instance per membership epoch, not per beat. ``exclude`` is
        the rare failover path and bypasses the memo."""
        if not self.enabled:
            return self.self_addr
        members = self._members
        if exclude:
            return telemetry_owner(members, instance_name,
                                   exclude) or self.self_addr
        cache = self._own_cache  # xlint: allow-state-read(verdict memo: GIL-atomic snapshot read; a stale pair fails the identity check below and is rebuilt)
        if cache[0] is not members or len(cache[1]) >= self.OWN_CACHE_MAX:
            cache = (members, {})
            with _ownership.escape("verdict-memo swap on the beat hot "
                                   "path: single-assignment publish of a "
                                   "fresh (members, {}) pair; racing "
                                   "readers rebuild identical entries"):
                self._own_cache = cache
        owner = cache[1].get(instance_name)
        if owner is None:
            owner = telemetry_owner(members, instance_name) or self.self_addr
            with _ownership.escape("verdict-memo fill: GIL-atomic item "
                                   "store of a deterministic value — "
                                   "every racer computes the same owner "
                                   "for the same member tuple"):
                cache[1][instance_name] = owner
        return owner

    def owns_instance(self, instance_name: str) -> bool:
        """Does THIS master own the instance's telemetry ingest?"""
        return self.instance_owner(instance_name) == self.self_addr

    def mine(self, kind: str,
             gen: Optional[Callable[[str], str]] = None) -> tuple[str, str]:
        """Generate a service request id, preferring one THIS node owns
        (bounded draws). Returns ``(sid, owner_addr)``; the caller hands
        off when ``owner_addr != self_addr`` (draws exhausted against an
        unlucky membership, or mining disabled)."""
        gen = gen or generate_service_request_id
        if not self.enabled or len(self._members) <= 1:
            return gen(kind), self.self_addr
        if not self.mine_ids:
            sid = gen(kind)
            return sid, self.owner_of(sid)
        sid = gen(kind)
        with _ownership.escape("stat counters on the accept hot path: "
                               "GIL-atomic int adds; losing a rare "
                               "increment beats a lock per accept"):
            for _ in range(MINE_TRIES):
                if self.owner_of(sid) == self.self_addr:
                    self.mined += 1
                    return sid, self.self_addr
                sid = gen(kind)
            self.mine_misses += 1
        return sid, self.owner_of(sid)

    def stats(self) -> dict:
        return {"self": self.self_addr, "members": list(self._members),
                "enabled": self.enabled, "mine_ids": self.mine_ids,
                "mined": self.mined, "mine_misses": self.mine_misses}

    def stop(self) -> None:
        if self._watch_id is not None:
            self._coord.remove_watch(self._watch_id)
            self._watch_id = None
