"""Active-active multi-master service plane.

The reference runs exactly one *active* master elected via etcd; passive
replicas mirror state through watches and only serve after winning an
election (`scheduler.cpp:72-102`, PAPER.md §7). This package goes beyond
that: every service replica is an active frontend. The pieces:

- :mod:`ownership` — rendezvous-hash request ownership over the live
  service-replica set (`XLLM:SERVICE:` records), so every in-flight
  request has exactly ONE owning master for failover bookkeeping, trace
  assembly and cancel-on-instance-death, resolvable from the request id
  alone by any node.
- :mod:`handoff` — the thin forward path for the minority of requests an
  accepting frontend does not own: relay the client call to the owner's
  `/rpc/handoff` endpoint and stream the response back, with
  deterministic re-ownership (re-forward to the rendezvous successor)
  when the owner dies mid-stream, and a seq-numbered owner-side delta
  journal so a reconnect to a surviving owner replays the exact frames
  already generated instead of re-running the stream.
- telemetry-ingest sharding (``telemetry_owner`` + the InstanceMgr
  sharded-ingest plane): each active master ingests heartbeats/load only
  for the instances it owns under the rendezvous shard map, and
  publishes coalesced load/lease frames (`XLLM:LOADFRAME:<owner>`) that
  every other frontend mirrors — the elected master's ingest funnel
  (the single-process ceiling NOTES_ROUND8 measured at ~40% CPU) is
  spread 1/N across the plane.

Write-lease discipline: mutating coordination writes (KV frame
publishing, load-metric uploads, planner hints, PD-role flips, instance
eviction records) stay funneled through the *elected* master so the
PR-5 frame-log invariants hold; replicas proxy their flip hints to the
master (`/rpc/flip_hint`) instead of writing themselves. Telemetry
load frames are the one deliberate exception: each frame key is
single-writer by construction (the key IS the owner's address), so
sharded publication cannot conflict with the lease. See
docs/multi_master.md.
"""

from .ownership import (
    OwnershipRouter,
    TelemetryOwnerResolver,
    rendezvous_owner,
    telemetry_owner,
)

__all__ = ["OwnershipRouter", "TelemetryOwnerResolver", "rendezvous_owner",
           "telemetry_owner"]
