"""Thin owner-forward path for requests the accepting frontend does not
own.

The accepting frontend relays the ORIGINAL client body to the owner's
``/rpc/handoff`` endpoint and streams the owner's response (SSE frames or
one JSON document) straight back to the client — the owner runs the full
schedule/dispatch/failover pipeline; the relay never parses payloads
beyond SSE frame boundaries.

Owner-death recovery (the "drain to successors" half of sticky
ownership): if the owner connection dies mid-stream, the relay recomputes
ownership over the surviving members (rendezvous successor — every relay
holding requests of the dead owner lands them on the same survivors,
deterministically), re-forwards with the count of data frames already
delivered, and drops exactly that many frames from the replacement stream
before resuming the client copy. The replacement owner re-runs the
request through the normal pipeline; with the engine-side prefix cache
warm, the replay prefills from cache. Frame-skip dedup assumes the
upstream stream is reproducible for the same request (true of the
fake-engine drills; a temperature>0 real engine may splice a divergent
continuation — same contract as the reference's cancel-and-surface, but
the stream *completes*).

Trace correlation: the relay roots the request's trace and sends the
context as ``x-xllm-*`` headers; the owner parents its ``frontend.request``
span under it, so ``/admin/trace`` assembles one tree across the relay,
every owner incarnation, and the engines they dispatched to.
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, Optional

import aiohttp
from aiohttp import web

from ..common.flightrecorder import RECORDER
from ..common.metrics import (
    HANDOFF_FORWARDED_TOTAL,
    HANDOFF_RECOVERIES_TOTAL,
)
from ..common.tracing import TRACER
from ..devtools import lifecycle as _lifecycle
from ..devtools.locks import make_lock
from ..overload import RETRY_BUDGET
from ..overload.deadline import ABS_DEADLINE_HEADER, PRIORITY_HEADER
from ..utils import get_logger
from .ownership import OwnershipRouter

logger = get_logger(__name__)

_DATA_PREFIX = b"data: "


class _JournalEntry:
    """One relayed stream's journaled SSE data frames. ``frames`` is
    append-only (writer: the owner's SSE emit loop; readers: replay
    handlers polling length under the GIL); ``finished`` flips once,
    after the last frame."""

    __slots__ = ("frames", "finished", "created", "touched", "sid")

    def __init__(self, now: float, sid: str = ""):
        self.frames: list[bytes] = []
        self.finished = False
        self.created = now
        self.touched = now
        self.sid = sid


class DeltaJournal:
    """Owner-side seq-numbered delta journal for relayed streams
    (NOTES_ROUND8 follow-up). The old recovery contract re-ran the whole
    pipeline on the replacement owner and dropped ``skip`` frames of the
    NEW stream — exact only if streams are reproducible, which
    temperature>0 sampling breaks (the relay would splice a divergent
    continuation). With the journal, the owner records every SSE data
    frame it emits for a relayed request (frame index IS the seq — the
    same count the relay's ``skip`` uses), keeps absorbing engine deltas
    for ``grace_s`` after the relay's connection breaks instead of
    cancelling, and serves a reconnect (same sid, ``skip=N``) the EXACT
    recorded frames ``N:`` — no re-run, no splice risk. The relay's
    first recovery attempt retries the SAME owner to hit this path; a
    genuinely dead owner fails that attempt fast (RST) and recovery
    falls back to the rendezvous successor with the legacy
    reproducible-stream contract."""

    def __init__(self, grace_s: float = 10.0, max_requests: int = 256,
                 ttl_s: float = 120.0):
        self.grace_s = grace_s
        self.max_requests = max_requests
        self.ttl_s = ttl_s
        self._lock = make_lock("multimaster.journal", order=30)  # lock-order: 30
        self._entries: dict[str, _JournalEntry] = {}

    @property
    def enabled(self) -> bool:
        return self.grace_s > 0

    def start(self, sid: str) -> Optional[_JournalEntry]:
        """Open (or resume) the journal for a relayed stream; returns
        None when journaling is disabled or the table is full (the
        stream still serves — it just loses exact-replay recovery)."""
        if not self.enabled:
            return None
        now = time.monotonic()
        with self._lock:
            self._gc_locked(now)
            entry = self._entries.get(sid)
            if entry is None:
                if len(self._entries) >= self.max_requests:
                    return None
                entry = self._entries[sid] = _JournalEntry(now, sid)
                _lifecycle.note_acquire("journal-session", key=sid)
            return entry

    def get(self, sid: str) -> Optional[_JournalEntry]:
        with self._lock:
            entry = self._entries.get(sid)
            if entry is not None:
                entry.touched = time.monotonic()
            return entry

    @staticmethod
    def record(entry: Optional[_JournalEntry], frame: bytes) -> None:
        """Tee one emitted SSE frame (only ``data:`` frames — the exact
        set the relay's delivered-frame counter increments on, so
        journal index == relay skip)."""
        if entry is not None and frame.startswith(_DATA_PREFIX):
            entry.frames.append(frame)

    @staticmethod
    def finish(entry: Optional[_JournalEntry]) -> None:
        if entry is not None:
            if not entry.finished:
                _lifecycle.note_release("journal-session", key=entry.sid)
            entry.finished = True

    def _gc_locked(self, now: float) -> None:
        dead = [sid for sid, e in self._entries.items()
                if now - e.touched > self.ttl_s]
        for sid in dead:
            # Idempotent pair: a finished entry already released; this
            # only balances entries the grace window abandoned.
            _lifecycle.note_release("journal-session", key=sid)
            del self._entries[sid]

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries),
                    "grace_s": self.grace_s}


def _passthrough_headers(r) -> dict[str, str]:
    """Owner-response headers the relay must not swallow: Retry-After
    carries the admission gate's backoff hint on a shed 429 — without
    it well-behaved clients retry immediately instead of backing off,
    and X-Request-Id carries the internal service id the trace plane
    keys by."""
    out = {}
    for h in ("Retry-After", "X-Request-Id"):
        v = r.headers.get(h)
        if v:
            out[h] = v
    return out


class HandoffRelay:
    """Relays one frontend's foreign-owned requests to their owners."""

    def __init__(self, ownership: OwnershipRouter, max_attempts: int = 3,
                 stall_timeout_s: float = 60.0,
                 same_owner_retry: bool = True):
        self._ownership = ownership
        self.max_attempts = max(1, max_attempts)
        # Read deadline per response chunk: a killed-but-not-closed owner
        # (hung event loop, SIGKILL mid-handler) leaves the TCP stream
        # open and silent — without this the relay would stall forever
        # instead of re-owning. Found by the kill-the-owner chaos drill.
        self.stall_timeout_s = stall_timeout_s
        # First stream recovery retries the SAME owner before excluding
        # it: a transport blip against a LIVE owner hits its delta
        # journal (exact frame replay, no pipeline re-run); a dead owner
        # fails the retry fast and the next attempt re-owns as before.
        self.same_owner_retry = same_owner_retry

    def _url(self, owner: str, kind: str, sid: str) -> str:
        return f"http://{owner}/rpc/handoff?kind={kind}&sid={sid}"

    async def relay(self, http_req: web.Request, client: aiohttp.ClientSession,
                    body: bytes, kind: str, sid: str, owner: str,
                    owner_key: str, stream: bool,
                    timeout_s: float, deadline_ms: int = 0,
                    priority: str = "") -> web.StreamResponse:
        """Forward ``body`` to ``owner`` and copy the response back to the
        client of ``http_req``. Returns the prepared client response."""
        span = TRACER.start_span("frontend.request", request_id=sid,
                                 kind=kind, stream=stream, relay=True,
                                 owner=owner)
        headers = {"Content-Type": "application/json"}
        if deadline_ms:
            # The ABSOLUTE deadline computed at accept rides the hop —
            # the owner must enforce the original budget, not restart it
            # (overload/deadline.py). The relay's own total timeout is
            # clamped to the remaining budget below.
            headers[ABS_DEADLINE_HEADER] = str(deadline_ms)
            timeout_s = max(0.05, min(
                timeout_s, deadline_ms / 1000.0 - time.time() + 0.5))
        if priority:
            headers[PRIORITY_HEADER] = priority
        if span:
            headers.update(span.context().to_headers())
        HANDOFF_FORWARDED_TOTAL.labels(owner=owner).inc()
        try:
            if stream:
                return await self._relay_stream(
                    http_req, client, body, kind, sid, owner, owner_key,
                    headers, timeout_s, span)
            return await self._relay_unary(
                http_req, client, body, kind, sid, owner, owner_key,
                headers, timeout_s, span)
        finally:
            if span:
                span.end()

    # ----------------------------------------------------------- non-stream
    async def _relay_unary(self, http_req, client, body, kind, sid, owner,
                           owner_key, headers, timeout_s,
                           span) -> web.Response:
        failed: list[str] = []
        last_err: Any = None
        for attempt in range(self.max_attempts):
            if attempt:
                if not RETRY_BUDGET.try_spend():
                    # Global retry budget (overload plane): a mass owner
                    # outage must degrade into bounded recovery, not a
                    # relay retry storm across every accepting frontend.
                    last_err = f"{last_err} (retry budget exhausted)"
                    break
                owner = self._recover(owner, failed, owner_key, sid, span)
                HANDOFF_RECOVERIES_TOTAL.labels(owner=owner).inc()
            url = self._url(owner, kind, sid) + f"&attempt={attempt}"
            try:
                # No per-read stall deadline here (unlike the stream
                # relay): a unary owner legitimately sends ZERO bytes
                # until the whole generation is done, which can far
                # exceed any silence threshold that would still catch a
                # hung owner usefully. A SIGKILLed owner closes its
                # sockets (kernel teardown) and fails fast below; the
                # rare hung-but-open owner is bounded by `total`.
                async with client.post(
                        url, data=body, headers=headers,
                        timeout=aiohttp.ClientTimeout(
                            total=timeout_s, sock_connect=10)) as r:
                    payload = await r.read()
                    # Any HTTP status from the owner is an answer (client
                    # errors replay identically anywhere; 5xx came from
                    # the owner's own pipeline, which already ran its
                    # failover budget) — only transport failures recover.
                    return web.Response(
                        body=payload, status=r.status,
                        content_type=(r.content_type or "application/json"),
                        headers=_passthrough_headers(r))
            except (aiohttp.ClientError, asyncio.TimeoutError) as e:
                last_err = e
                failed.append(owner)
                logger.warning("handoff of %s to %s failed: %s",
                               sid, owner, e)
        if span:
            span.set(error=str(last_err), error_code=503)
            span.status = "ERROR: 503"
        return web.json_response(
            {"error": {"message": f"request owner unreachable: {last_err}",
                       "type": "service_unavailable", "code": 503}},
            status=503)

    # --------------------------------------------------------------- stream
    async def _relay_stream(self, http_req, client, body, kind, sid, owner,
                            owner_key, headers, timeout_s,
                            span) -> web.StreamResponse:
        resp = web.StreamResponse()
        resp.headers["Content-Type"] = "text/event-stream"
        resp.headers["Cache-Control"] = "no-cache"
        # Same contract as the owner-served path: the internal service id
        # (what /admin/trace and the flight recorder key by) rides a
        # response header — the deltas only carry the OpenAI cmpl- id.
        resp.headers["X-Request-Id"] = sid
        prepared = False
        delivered = 0          # data frames already copied to the client
        failed: list[str] = []
        last_err: Any = None
        for attempt in range(self.max_attempts):
            if attempt:
                if not RETRY_BUDGET.try_spend():
                    last_err = f"{last_err} (retry budget exhausted)"
                    break
                if attempt == 1 and self.same_owner_retry \
                        and owner not in failed:
                    # Same-owner-first: a live owner serves the reconnect
                    # from its delta journal — the exact frames already
                    # generated, no re-run (exact dedup even under
                    # temperature>0 sampling). A dead owner RSTs this
                    # attempt immediately and the next one re-owns.
                    logger.info("retrying %s against the same owner %s "
                                "(journal reconnect, %d frames delivered)",
                                sid, owner, delivered)
                else:
                    owner = self._recover(owner, failed, owner_key, sid,
                                          span)
                    HANDOFF_RECOVERIES_TOTAL.labels(owner=owner).inc()
            url = (self._url(owner, kind, sid)
                   + f"&attempt={attempt}&skip={delivered}")
            skip = delivered
            try:
                async with client.post(
                        url, data=body, headers=headers,
                        timeout=aiohttp.ClientTimeout(
                            total=timeout_s, sock_connect=10,
                            sock_read=self.stall_timeout_s)) as r:
                    if r.status != 200:
                        # The owner answered (error body, non-stream): an
                        # authoritative reply, not a transport failure.
                        payload = await r.read()
                        if prepared:
                            # Frames already went out — append an SSE
                            # error frame instead of rewriting the status.
                            await resp.write(
                                _DATA_PREFIX + payload + b"\n\n")
                            await resp.write_eof()
                            return resp
                        return web.Response(
                            body=payload, status=r.status,
                            content_type=(r.content_type
                                          or "application/json"),
                            headers=_passthrough_headers(r))
                    # Client-facing writes are guarded INDIVIDUALLY: a
                    # dead client raises ClientConnectionResetError,
                    # which is an aiohttp.ClientError too — letting it
                    # reach the owner-failure handler below would
                    # misclassify the disconnect as owner death and
                    # re-run the whole generation on the rendezvous
                    # successor (up to max_attempts times) for a client
                    # that is gone. OSError covers it: the reset is a
                    # ConnectionResetError subclass.
                    try:
                        if not prepared:
                            await resp.prepare(http_req)
                            prepared = True
                    except OSError:
                        # CLIENT went away before prepare: abort the
                        # owner connection NOW — a graceful release
                        # would drain the stream, hiding the disconnect
                        # from the owner (whose next write is what
                        # triggers its mark_disconnected →
                        # _cancel_on_engines chain).
                        r.close()
                        await self._abort_owner(client, owner, sid)
                        return resp
                    async for frame in self._frames(r.content):
                        if frame.startswith(_DATA_PREFIX) and skip > 0:
                            # Replay dedup: this frame was already
                            # delivered by a previous owner incarnation.
                            skip -= 1
                            continue
                        try:
                            await resp.write(frame)
                        except OSError:
                            # CLIENT went away mid-copy: abort the owner
                            # connection so the disconnect PROPAGATES —
                            # the owner's next SSE write fails, it marks
                            # the connection dead, and the engines get
                            # cancelled. Without this the relay could
                            # keep draining the owner stream to
                            # completion, burning engine tokens for a
                            # client that is gone.
                            r.close()
                            await self._abort_owner(client, owner, sid)
                            return resp
                        if frame.startswith(_DATA_PREFIX):
                            delivered += 1
                    try:
                        await resp.write_eof()
                    except OSError:
                        pass
                    return resp
            except (aiohttp.ClientError, asyncio.TimeoutError) as e:
                last_err = e
                if not (self.same_owner_retry and attempt == 0):
                    # First break with same-owner retry armed: keep the
                    # owner out of `failed` so the journal-reconnect
                    # attempt targets it; a second break condemns it.
                    failed.append(owner)
                logger.warning("handoff stream of %s via %s broke after "
                               "%d frames: %s", sid, owner, delivered, e)
        # Recovery budget exhausted mid-stream: surface in-band.
        if span:
            span.set(error=str(last_err), error_code=503)
        if prepared:
            try:
                await resp.write(
                    b'data: {"error": {"message": "request owner lost mid-'
                    b'stream; recovery budget exhausted", "code": 503}}\n\n')
                await resp.write_eof()
            except (ConnectionResetError, OSError):
                pass
            return resp
        return web.json_response(
            {"error": {"message": f"request owner unreachable: {last_err}",
                       "type": "service_unavailable", "code": 503}},
            status=503)

    @staticmethod
    async def _abort_owner(client: aiohttp.ClientSession, owner: str,
                           sid: str) -> None:
        """Tell the owner the CLIENT is gone (not just the relay
        transport): with the delta journal armed, a bare connection
        break makes the owner absorb deltas for the reconnect grace
        window — correct for a blip, wasted engine tokens for a real
        client abort. This explicit signal finishes the journal and
        cancels the request immediately. Best effort: a legacy owner
        404s and falls back to the grace-expiry cancel."""
        try:
            async with client.post(
                    f"http://{owner}/rpc/handoff_abort?sid={sid}",
                    timeout=aiohttp.ClientTimeout(total=2)) as r:
                await r.read()
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
            pass

    @staticmethod
    async def _frames(content: aiohttp.StreamReader):
        """Yield complete SSE frames (through the blank-line terminator) so
        the skip/count logic never sees a torn frame."""
        buf = bytearray()
        async for chunk, _ in content.iter_chunks():
            buf += chunk
            while True:
                i = buf.find(b"\n\n")
                if i < 0:
                    break
                yield bytes(buf[:i + 2])
                del buf[:i + 2]
        if buf:
            yield bytes(buf)

    def _recover(self, dead: str, failed: list[str], owner_key: str,
                 sid: str, span) -> str:
        """Deterministic re-ownership: the rendezvous successor over the
        members that have not failed this relay."""
        successor = self._ownership.owner_of(owner_key, exclude=failed)
        logger.info("re-owning %s: %s -> %s (failed: %s)",
                    sid, dead, successor, failed)
        if span:
            span.set(reowned_to=successor, attempt_failed=dead)
            # Owner death is an anomaly by definition: force the
            # tail-sampling keep so the relay-side spans survive, and
            # capture the re-ownership in the flight recorder (the
            # owner-kill chaos drill asserts on this bundle).
            TRACER.keep_trace(span.trace_id)
        RECORDER.record(
            "handoff_recovery", request_id=sid,
            trace_id=span.trace_id if span else "",
            detail={"dead_owner": dead, "successor": successor,
                    "failed_so_far": list(failed)})
        return successor
