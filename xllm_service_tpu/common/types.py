"""Core domain types for the orchestration plane.

Behavioral parity with the reference's `xllm_service/common/types.h` (461 LoC;
see SURVEY.md §2.9): InstanceMetaInfo, InstanceType, InstanceRuntimeState,
Routing, LoadMetrics, LatencyMetrics, KvCacheEvent, CacheLocations,
RequestAction/RequestMetrics, OverlapScores, LoadBalanceInfos — re-designed
for TPU: the reference's RDMA endpoint fields (`device_ips`, `ports`,
`cluster_ids`, reference `xllm_rpc_service.proto:38-43`) are replaced with an
explicit :class:`TpuTopology` (slice id, mesh shape, named axes, per-host DCN
addresses) so the scheduler can place prefill/decode roles topology-aware.

All types JSON-round-trip (``to_json``/``from_json``) because — like the
reference, which persists them to etcd (`types.h:224-318`) — they are stored
in the coordination service and mirrored by replica schedulers.
"""

from __future__ import annotations

import enum
import json
import time
from dataclasses import dataclass, field, asdict
from typing import Any, Sequence


def now_ms() -> int:
    return int(time.time() * 1000)


class InstanceType(str, enum.Enum):
    """Role of an engine instance in the PD(+E)-disaggregated fleet.

    Reference: `common/types.h:75-83` {DEFAULT, PREFILL, DECODE, MIX}; we add
    ENCODE for EPD three-stage multimodal disaggregation (the reference only
    claims the feature, README.md:47 — the mechanism is ours to define).
    """

    DEFAULT = "DEFAULT"
    PREFILL = "PREFILL"
    DECODE = "DECODE"
    MIX = "MIX"
    ENCODE = "ENCODE"

    @classmethod
    def parse(cls, v: "InstanceType | str | None") -> "InstanceType":
        if v is None:
            return cls.DEFAULT
        if isinstance(v, InstanceType):
            return v
        return cls(str(v).upper())


class InstanceRuntimeState(str, enum.Enum):
    """Liveness state machine (reference `common/types.h:85-89` has three
    states; DRAINING is ours — the reference has no graceful shutdown).

    ACTIVE -> LEASE_LOST (lease expired but health probe passed; still
    schedulable) -> SUSPECT (probe failed or heartbeat silence; excluded from
    scheduling) -> evicted. See SURVEY.md §3.4.

    DRAINING (planned retirement — autoscaler scale-in or an operator
    drain): excluded from new scheduling, in-flight requests finish, then
    the instance deregisters gracefully (no eviction alarm, no failover).
    A DRAINING instance that dies mid-drain transitions through the
    normal LEASE_LOST/SUSPECT failure path, so its remaining requests
    still fail over.

    BREAKER_OPEN (overload plane, rpc/breaker.py): the instance's
    engine channel tripped its circuit breaker — sick-but-leased, its
    lease keeps renewing while RPCs fail. Excluded from scheduling like
    SUSPECT, but NOT evicted on a timer: the reconcile thread's
    half-open probe restores it to ACTIVE when the channel recovers. A
    registration refresh must not resurrect it (same rule as DRAINING).
    """

    ACTIVE = "ACTIVE"
    LEASE_LOST = "LEASE_LOST"
    SUSPECT = "SUSPECT"
    DRAINING = "DRAINING"
    BREAKER_OPEN = "BREAKER_OPEN"


class RequestAction(str, enum.Enum):
    """SLO-accounting actions (reference `common/types.h:152-158`)."""

    SCHEDULE = "SCHEDULE"
    FINISH_PREFILL = "FINISH_PREFILL"
    DECODE_STEP = "DECODE_STEP"
    FINISH_DECODE = "FINISH_DECODE"
    # Request left before producing a token (error / disconnect / GC
    # timeout): reverse only the SCHEDULE increments. Using FINISH_PREFILL
    # here would credit the decode instance with load it never received and
    # permanently skew SLO/CAR routing.
    CANCEL = "CANCEL"


@dataclass
class TpuTopology:
    """TPU-native placement metadata, replacing the reference's RDMA NIC
    fields (`xllm_rpc_service.proto:38-43` device_ips/ports/cluster_ids).

    slice_id      — which TPU slice/pod this instance's mesh lives on; KV
                    handoff between instances on the same slice can ride ICI,
                    cross-slice handoff rides DCN.
    host          — physical host within the slice; two instances sharing a
                    non-empty host are link-class "local". A non-empty host is
                    also what marks the instance as *placed* for the topology
                    plane (common/topology.py) — slice_id alone never does,
                    so legacy default slice ids can't re-route a flat fleet.
    chip          — chip index within the host (-1 = unpinned).
    mesh_shape    — e.g. [2, 4] for a 2x4 sub-mesh.
    axis_names    — named mesh axes, e.g. ["data", "model"].
    host_addrs    — per-host DCN endpoints (host:port) for KV transfer.
    chip_coords   — optional per-chip coordinates within the slice.
    """

    slice_id: str = ""
    host: str = ""
    chip: int = -1
    mesh_shape: list[int] = field(default_factory=list)
    axis_names: list[str] = field(default_factory=list)
    host_addrs: list[str] = field(default_factory=list)
    chip_coords: list[list[int]] = field(default_factory=list)
    # JAX transfer-server endpoint for device-path KV pulls (the TPU
    # analog of the reference's RDMA device_ips/ports); "" = host path
    # only.
    kv_transfer_addr: str = ""

    def num_devices(self) -> int:
        n = 1
        for d in self.mesh_shape:
            n *= d
        return n if self.mesh_shape else 0


@dataclass
class InstanceMetaInfo:
    """Engine instance registration record.

    Reference: `xllm_rpc_service.proto:31-46` InstanceMetaInfo — name (the
    instance's HTTP address doubles as its identity), rpc_address, type,
    dp_size, kv-cache ids, profiling tables, incarnation_id, register_ts_ms.
    TPU changes: `topology` replaces cluster_ids/device_ips/ports;
    `max_context_len`/`cp_degree` advertise long-context capability
    (SURVEY.md §5.7); `kv_page_size`/`kv_dtype`/`num_layers`/`num_kv_heads`/
    `head_dim` advertise KV layout so PD peers can validate transfer
    compatibility before linking.
    """

    name: str = ""                       # identity; typically "host:http_port"
    rpc_address: str = ""
    type: InstanceType = InstanceType.DEFAULT
    dp_size: int = 1
    topology: TpuTopology = field(default_factory=TpuTopology)
    # KV layout contract for PD linking (replaces opaque k/v_cache_ids).
    kv_page_size: int = 128
    kv_dtype: str = "bfloat16"
    num_layers: int = 0
    num_kv_heads: int = 0
    head_dim: int = 0
    # Long-context capability (SURVEY.md §5.7).
    max_context_len: int = 8192
    cp_degree: int = 1
    # Offline-profiled latency tables: rows of [prompt_len, ttft_ms] and
    # [batch_size, total_tokens, tpot_ms] (reference `common/types.h:207-210`),
    # fitted by TimePredictor at registration.
    ttft_profiling_data: list[list[float]] = field(default_factory=list)
    tpot_profiling_data: list[list[float]] = field(default_factory=list)
    # Dispatch-wire formats this engine accepts, preference-ordered
    # (rpc/wire.py). Current builds advertise ["msgpack", "json"]; legacy
    # metadata without the field defaults to JSON-only, so the master
    # never sends binary to an engine that can't parse it.
    wire_formats: list[str] = field(default_factory=lambda: ["json"])
    # Graceful shutdown: a draining instance stays registered (in-flight
    # streams finish) but is excluded from scheduling.
    draining: bool = False
    # Lifecycle.
    incarnation_id: str = ""
    register_ts_ms: int = 0
    models: list[str] = field(default_factory=list)

    # ---- json ----
    def to_json(self) -> str:
        d = asdict(self)
        d["type"] = self.type.value
        return json.dumps(d)

    @classmethod
    def from_json(cls, s: str | bytes) -> "InstanceMetaInfo":
        d = json.loads(s)
        topo = d.pop("topology", None) or {}
        info = cls(**{k: v for k, v in d.items() if k in cls.__dataclass_fields__ and k not in ("type", "topology")})
        info.type = InstanceType.parse(d.get("type"))
        info.topology = TpuTopology(**{k: v for k, v in topo.items() if k in TpuTopology.__dataclass_fields__})
        return info


@dataclass
class LoadMetrics:
    """Per-instance load snapshot carried in heartbeats.

    Reference: `xllm_rpc_service.proto:54-58` {waiting_requests_num,
    gpu_cache_usage_perc}; renamed gpu→hbm for TPU.
    """

    waiting_requests_num: int = 0
    hbm_cache_usage_perc: float = 0.0
    running_requests_num: int = 0

    def to_dict(self) -> dict[str, Any]:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "LoadMetrics":
        return cls(**{k: v for k, v in d.items() if k in cls.__dataclass_fields__})


@dataclass
class LatencyMetrics:
    """Recent worst-case latencies from the engine (reference
    `xllm_rpc_service.proto:59-62`)."""

    recent_max_ttft: float = 0.0
    recent_max_tbt: float = 0.0

    def to_dict(self) -> dict[str, Any]:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "LatencyMetrics":
        return cls(**{k: v for k, v in d.items() if k in cls.__dataclass_fields__})


@dataclass
class KvCacheEvent:
    """Delta of the instance's prefix-cache content, carried in heartbeats.

    Reference: `xllm_rpc_service.proto:48-53` KvCacheEvent {stored/removed/
    offload_cache blobs}. Keys are the 16-byte chained block hash
    (common/hashing.py): raw ``bytes`` on the msgpack heartbeat wire
    (half the bytes, no hex codec on either end), hex ``str`` on the
    legacy JSON wire. Each list is homogeneous; consumers normalize via
    ``hashing.as_key``. ``to_dict`` renders hex (JSON-safe),
    ``to_wire_dict`` renders raw bytes (msgpack-only).
    """

    stored: list = field(default_factory=list)
    removed: list = field(default_factory=list)
    offloaded: list = field(default_factory=list)

    def empty(self) -> bool:
        return not (self.stored or self.removed or self.offloaded)

    @staticmethod
    def _hexes(keys: list) -> list[str]:
        return [k.hex() if isinstance(k, (bytes, bytearray)) else k
                for k in keys]

    @staticmethod
    def _raws(keys: list) -> list[bytes]:
        return [bytes(k) if isinstance(k, (bytes, bytearray))
                else bytes.fromhex(k) for k in keys]

    def merge(self, other: "KvCacheEvent") -> None:
        """Union of two replicas' deltas (dp_size>1: the instance-level
        event is the union of its replicas'; a block removed by one replica
        but stored by another stays stored). Same best-state rule for
        tiers: a block one replica offloaded cold but another holds in HBM
        ships stored-only — the index applies stored before offloaded, so
        shipping both would demote the instance below its best tier.
        Within ONE delta stored+offloaded is the donate-then-evict
        sequence (per-replica deltas are internally ordered) and the cold
        move must survive, hence the per-side stored-minus-offloaded
        sets."""
        hbm_only = (set(self.stored) - set(self.offloaded)) \
            | (set(other.stored) - set(other.offloaded))
        removed_here = set(other.stored)
        self.removed = [h for h in self.removed if h not in removed_here]
        stored_there = set(self.stored)
        self.stored += [h for h in other.stored if h not in stored_there]
        kept = set(self.stored)
        self.removed += [h for h in other.removed
                         if h not in kept and h not in set(self.removed)]
        offloaded = self.offloaded + [h for h in other.offloaded
                                      if h not in set(self.offloaded)]
        self.offloaded = [h for h in offloaded if h not in hbm_only]

    def to_dict(self) -> dict[str, Any]:
        """JSON-safe form: hex-string keys (legacy heartbeat wire)."""
        return {"stored": self._hexes(self.stored),
                "removed": self._hexes(self.removed),
                "offloaded": self._hexes(self.offloaded)}

    def to_wire_dict(self) -> dict[str, Any]:
        """msgpack form: raw 16-byte keys (binary heartbeat wire)."""
        return {"stored": self._raws(self.stored),
                "removed": self._raws(self.removed),
                "offloaded": self._raws(self.offloaded)}

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "KvCacheEvent":
        return cls(**{k: list(v) for k, v in d.items()
                      if k in cls.__dataclass_fields__ and v is not None})


class CacheTier(str, enum.Enum):
    """KV block residence tier (reference `common/types.h:320-365`
    CacheLocations{hbm,dram,ssd}). On TPU: HBM = device memory,
    DRAM = TPU-VM host memory, SSD = local disk."""

    HBM = "hbm"
    DRAM = "dram"
    SSD = "ssd"


@dataclass
class CacheLocations:
    """Which instances hold a given KV block, per tier."""

    hbm: set[str] = field(default_factory=set)
    dram: set[str] = field(default_factory=set)
    ssd: set[str] = field(default_factory=set)

    def empty(self) -> bool:
        return not (self.hbm or self.dram or self.ssd)

    def remove_instance(self, name: str) -> None:
        self.hbm.discard(name)
        self.dram.discard(name)
        self.ssd.discard(name)

    def to_dict(self) -> dict[str, Any]:
        return {"hbm": sorted(self.hbm), "dram": sorted(self.dram), "ssd": sorted(self.ssd)}

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "CacheLocations":
        return cls(hbm=set(d.get("hbm", ())), dram=set(d.get("dram", ())), ssd=set(d.get("ssd", ())))

    def to_row(self) -> list[list[str]]:
        """Compact positional [hbm, dram, ssd] form for binary KV frames
        (rpc/wire.py encode_kv_frame) — no per-entry field names."""
        return [sorted(self.hbm), sorted(self.dram), sorted(self.ssd)]

    @classmethod
    def from_row(cls, row: Sequence[Any]) -> "CacheLocations":
        return cls(hbm=set(row[0]), dram=set(row[1]), ssd=set(row[2]))


@dataclass
class OverlapScores:
    """Prefix-cache match result per candidate instance
    (reference `common/types.h:376-403`)."""

    # instance name -> number of matched KV blocks (per tier-weighted score).
    scores: dict[str, float] = field(default_factory=dict)
    max_block_num: int = 0
    # Depth of the matched prefix: how many leading full blocks were found
    # in the global index before the first miss (the radix-walk depth).
    matched_blocks: int = 0


@dataclass
class Routing:
    """Chosen (prefill, decode[, encode]) instance pair for a request
    (reference `common/types.h:43-55`)."""

    prefill_name: str = ""
    decode_name: str = ""
    encode_name: str = ""

    def valid(self) -> bool:
        return bool(self.prefill_name)


@dataclass
class RequestMetrics:
    """Per-request SLO accounting (reference `common/types.h:161-178`)."""

    prompt_tokens: int = 0
    generated_tokens: int = 0
    schedule_time_ms: int = 0
    prefill_finish_time_ms: int = 0
    finish_time_ms: int = 0
    estimated_ttft_ms: float = 0.0


@dataclass
class InstanceLoadInfo:
    """Aggregated per-instance info handed to LB policies
    (reference `common/types.h:405-437` LoadBalanceInfos)."""

    name: str = ""
    type: InstanceType = InstanceType.DEFAULT
    load: LoadMetrics = field(default_factory=LoadMetrics)
    latency: LatencyMetrics = field(default_factory=LatencyMetrics)
    schedulable: bool = True
    # When this entry's telemetry was last refreshed (heartbeat on the
    # master; LOADMETRICS mirror on replicas). 0 = never. Multi-master
    # frontends score routing off mirrored telemetry, so CAR/SLO scoring
    # discounts entries older than `loadinfo_stale_after_s`.
    updated_ms: int = 0
    # Effective placement coordinate (common/topology.py effective_coord):
    # synthetic per-host slice when the registration carried no host, so
    # the planner/policies can always compare slices without re-deriving.
    slice_id: str = ""
    host: str = ""
