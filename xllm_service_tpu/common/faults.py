"""Deterministic fault-injection plane.

The reference has no fault-injection facility at all — failure behavior is
only exercised by hand (kill a pod, watch the logs). This module is the
seeded, scriptable counterpart used by the chaos drills in
`tests/test_chaos_failover.py` and exposed operationally via the master's
`/admin/faults` endpoint.

Model: a registry of :class:`FaultRule`s evaluated at named **fault
points** compiled into the I/O layers:

===================  =========================================================
point                where it is checked
===================  =========================================================
``rpc.post``         `rpc/channel.py` before every POST attempt
``rpc.get``          `rpc/channel.py` before every GET attempt
``coord.call``       `coordination/client.py` before each request
``coord.connect``    `coordination/client.py` on every (re)connect
``coord.outage``     `coordination/memory.py` plane liveness ping
``kv_transfer.offer``  `engine/kv_transfer.py` prefill-side offer
``kv_transfer.pull``   `engine/kv_transfer.py` decode-side pull
``engine.accept``    `testing/fake_engine.py` request admission
``engine.token``     `testing/fake_engine.py` before each generated delta
``engine.heartbeat`` `testing/fake_engine.py` heartbeat loop
===================  =========================================================

Actions are interpreted per call site: ``error``/``drop`` raise
:class:`FaultInjected` from :meth:`FaultPlane.check` (drop = the request was
never sent, an *unambiguous* failure; error = it may have been processed, an
*ambiguous* one), ``delay`` sleeps, while ``crash``/``silence``/
``disconnect`` are returned from :meth:`FaultPlane.fire` for the caller to
enact (kill the engine, skip the heartbeat, sever the socket).

Determinism: rule matching is pure counting (`after`, `max_fires`) and the
only randomness — `probability` draws — comes from one seeded
`random.Random`, so a drill with a fixed seed replays the identical fault
schedule. `scripts/chaos_soak.sh` sweeps seeds via `XLLM_CHAOS_SEED`.
"""

from __future__ import annotations

import fnmatch
import os
import threading
import time
from dataclasses import asdict, dataclass, field
from random import Random
from typing import Any, Iterable, Optional

from ..utils import get_logger

logger = get_logger(__name__)

#: Actions understood by at least one fault point.
ACTIONS = ("error", "drop", "delay", "disconnect", "crash", "silence")

#: Registry of every fault point compiled into the I/O layers. xlint's
#: fault-point rule enforces the bidirectional contract: every
#: ``FAULTS.check("name")``/``FAULTS.fire("name")`` call site must name a
#: point registered here, and every registered point must have at least
#: one live call site (no dead fault points). Keep the table in the module
#: docstring in sync — it is the human-readable view of this dict.
FAULT_POINTS: dict[str, str] = {
    "rpc.post": "rpc/channel.py before every POST attempt",
    "rpc.get": "rpc/channel.py before every GET attempt",
    "coord.call": "coordination/client.py before each request",
    "coord.connect": "coordination/client.py on every (re)connect",
    "coord.outage": "coordination/memory.py plane liveness ping",
    "kv_transfer.offer": "engine/kv_transfer.py prefill-side offer",
    "kv_transfer.pull": "engine/kv_transfer.py decode-side pull",
    "engine.accept": "testing/fake_engine.py request admission",
    "engine.token": "testing/fake_engine.py before each generated delta",
    "engine.heartbeat": "testing/fake_engine.py heartbeat loop",
}

# Yield-point hook: every fire() marks a modeled blocking-I/O site. The
# instrumented-lock detector (devtools.locks) installs itself here under
# XLLM_LOCK_DEBUG=1 to flag locks held across I/O; None costs one attribute
# read per fault point.
_yield_hook = None


def set_yield_hook(hook) -> None:
    global _yield_hook
    _yield_hook = hook


class FaultInjected(RuntimeError):
    """Raised at a fault point whose matched rule demands a failure."""

    def __init__(self, point: str, rule: "FaultRule"):
        super().__init__(f"fault injected at {point}: {rule.action}")
        self.point = point
        self.rule = rule


@dataclass
class FaultRule:
    """One scripted fault. `point` may be a glob (``rpc.*``); `match`
    narrows by call-site context (e.g. ``{"instance": "host:port"}``);
    `after` skips the first N matching hits (crash-on-Nth-token);
    `max_fires` bounds how often the rule triggers."""

    point: str
    action: str = "error"
    probability: float = 1.0
    delay_s: float = 0.0
    after: int = 0
    max_fires: Optional[int] = None
    match: dict[str, Any] = field(default_factory=dict)
    # Runtime counters (exported via /admin/faults for observability).
    hits: int = 0
    fires: int = 0

    def __post_init__(self) -> None:
        if self.action not in ACTIONS:
            raise ValueError(f"unknown fault action {self.action!r} "
                             f"(expected one of {ACTIONS})")

    def to_dict(self) -> dict[str, Any]:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "FaultRule":
        return cls(**{k: v for k, v in d.items()
                      if k in cls.__dataclass_fields__})


class FaultPlane:
    """Thread-safe registry of fault rules with a seeded RNG."""

    def __init__(self, seed: Optional[int] = None):
        if seed is None:
            seed = int(os.environ.get("XLLM_CHAOS_SEED", "0"))
        self.seed = seed
        self._lock = threading.Lock()   # lock-order: 800
        self._rng = Random(seed)
        self._rules: list[FaultRule] = []

    # ------------------------------------------------------- configuration
    def configure(self, rules: Iterable[Any] = (),
                  seed: Optional[int] = None) -> None:
        """Replace all rules (and optionally reseed). Accepts FaultRule
        instances or plain dicts (the /admin/faults wire shape)."""
        parsed = [r if isinstance(r, FaultRule) else FaultRule.from_dict(r)
                  for r in rules]
        with self._lock:
            if seed is not None:
                self.seed = seed
            self._rng = Random(self.seed)
            self._rules = parsed

    def add(self, point: str, **kw: Any) -> FaultRule:
        rule = FaultRule(point=point, **kw)
        with self._lock:
            self._rules.append(rule)
        return rule

    def clear(self) -> None:
        with self._lock:
            self._rules = []

    def rules(self) -> list[FaultRule]:
        with self._lock:
            return list(self._rules)

    # ------------------------------------------------------------- firing
    def fire(self, point: str, **ctx: Any) -> Optional[FaultRule]:
        """Return the first rule that triggers at `point` (counters
        advanced), or None. Callers enact the returned rule's action."""
        hook = _yield_hook
        if hook is not None:
            # Lock-debug mode: every fault point is a blocking-I/O marker.
            hook(point)
        if not self._rules:   # fast path: the plane is almost always empty
            return None
        fired: Optional[FaultRule] = None
        with self._lock:
            for rule in self._rules:
                if rule.point != point and \
                        not fnmatch.fnmatchcase(point, rule.point):
                    continue
                if any(str(ctx.get(k)) != str(v)
                       for k, v in rule.match.items()):
                    continue
                rule.hits += 1
                if rule.hits <= rule.after:
                    continue
                if rule.max_fires is not None and \
                        rule.fires >= rule.max_fires:
                    continue
                if rule.probability < 1.0 and \
                        self._rng.random() >= rule.probability:
                    continue
                rule.fires += 1
                fired = rule
                break
        if fired is not None:
            logger.info("fault fired at %s: %s (ctx=%s)",
                        point, fired.action, ctx)
            # Self-explaining chaos drills: the injection lands as an event
            # on whatever span the calling thread is inside. Lazy import:
            # under XLLM_LOCK_DEBUG the lock factory imports this module at
            # its own import time, so a top-level tracing import would cycle
            # (faults -> tracing -> devtools.locks -> faults).
            from . import tracing

            tracing.add_event("fault", point=point, action=fired.action)
        return fired

    def check(self, point: str, **ctx: Any) -> None:
        """Convenience for I/O call sites: sleep on `delay`, raise
        :class:`FaultInjected` on `error`/`drop`, ignore actions the site
        doesn't model."""
        rule = self.fire(point, **ctx)
        if rule is None:
            return
        if rule.action == "delay":
            time.sleep(rule.delay_s)
            return
        if rule.action in ("error", "drop"):
            raise FaultInjected(point, rule)


#: Process-global plane. Components consult it directly; tests and the
#: `/admin/faults` endpoint configure it. Default state is empty (zero
#: overhead beyond one attribute read per fault point).
FAULTS = FaultPlane()
