"""End-to-end request tracing: hop-propagated spans with a live query surface.

The reference ships no cross-hop correlation at all — its request tracer is
a raw I/O JSONL (`http_service/request_tracer.cpp:38-61`) keyed only by
service_request_id, so multi-hop behavior (PD-disaggregated routing,
transparent failover, KV handoffs) is invisible. This module is the
Dapper-style counterpart: an explicit trace context (`trace_id`, `span_id`,
`parent_span_id`) is created in the HTTP frontend, carried in the enriched
engine payload (`trace_context` key) and in RPC channel headers
(`x-xllm-trace-id` / `x-xllm-parent-span-id`), and every hop records a
:class:`Span` into a bounded in-memory ring buffer (:class:`SpanStore`).

Query surface (served by the master's HTTP app and the engine agent):

- ``GET /admin/trace?request_id=...`` (or ``trace_id=...``) — the assembled
  span tree for one request, including failover re-dispatch attempts
  correlated by trace_id across instance incarnations.
- ``GET /admin/trace/recent[?sort=slowest&limit=N]`` — most-recent or
  slowest traces.

Fault-plane integration: :func:`add_event` stamps an event onto the calling
thread's *active* span (entered via ``with TRACER.span(...)``);
`common/faults.py` calls it on every fired rule, so chaos drills produce
self-explaining traces.

Overhead: with tracing disabled every ``span()``/``start_span()`` call is
one attribute read + a shared no-op singleton return (measured <2% on the
fake-engine request path, `benchmarks/bench_tracing_overhead.py`); enabled,
spans cost one dict append into the ring under a leaf lock.
"""

from __future__ import annotations

import threading
import time
import uuid
from collections import OrderedDict, deque
from dataclasses import dataclass
from hashlib import blake2b
from typing import Any, Callable, Optional

from ..devtools import lifecycle as _lifecycle
from ..devtools.locks import make_lock
from ..utils import get_logger

logger = get_logger(__name__)

#: Registry of every span point compiled into the request path. xlint's
#: span-point rule enforces the bidirectional contract: every
#: ``TRACER.span("name")``/``TRACER.start_span("name")`` call site must name
#: a point registered here, and every registered point must have at least
#: one live call site (no dead span points).
SPAN_POINTS: dict[str, str] = {
    "frontend.request": "http_service/service.py root span per API request "
                        "(fallback-created in scheduler.schedule for "
                        "direct-scheduler callers)",
    "scheduler.schedule": "scheduler dispatch: template + tokenize + route "
                          "+ incarnation bind",
    "scheduler.template": "chat-template apply sub-stage of schedule",
    "scheduler.tokenize": "prompt tokenization sub-stage of schedule",
    "scheduler.route": "LB-policy pair selection sub-stage of schedule "
                       "(lock-free routing-snapshot read)",
    "scheduler.bind": "incarnation bind + RCU re-validation sub-stage of "
                      "schedule",
    "scheduler.failover": "one transparent-failover re-dispatch attempt "
                          "(PR 1); children are the replayed engine spans",
    "engine.prefill": "engine-side prefill stage (accept -> first delta)",
    "engine.decode": "engine-side decode stage (first delta -> finish)",
    "kv_transfer.offer": "prefill-side KV offer/handoff to the decode peer",
    "kv_transfer.pull": "decode-side device KV pull",
    "autoscaler.tick": "one autoscaler enactment pass (only ticks that "
                       "act record a span; attrs carry the action kinds)",
}

#: Wire header names (RPC channel hop).
TRACE_ID_HEADER = "x-xllm-trace-id"
PARENT_SPAN_HEADER = "x-xllm-parent-span-id"


def _now_ms() -> float:
    return time.time() * 1000.0


def _new_trace_id() -> str:
    return uuid.uuid4().hex


def _new_span_id() -> str:
    return uuid.uuid4().hex[:16]


@dataclass(frozen=True)
class TraceContext:
    """The portable part of a span: what a downstream hop needs to parent
    its own spans correctly. `span_id` is the sender's span — it becomes
    the receiver's `parent_span_id`."""

    trace_id: str
    span_id: str

    def to_dict(self) -> dict[str, str]:
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    @classmethod
    def from_dict(cls, d: Any) -> Optional["TraceContext"]:
        """Tolerant parse of the `trace_context` wire key (None/malformed
        payloads from old senders simply disable parenting)."""
        if not isinstance(d, dict):
            return None
        tid, sid = d.get("trace_id"), d.get("span_id")
        if not tid or not sid:
            return None
        return cls(trace_id=str(tid), span_id=str(sid))

    def to_headers(self) -> dict[str, str]:
        return {TRACE_ID_HEADER: self.trace_id,
                PARENT_SPAN_HEADER: self.span_id}

    @classmethod
    def from_headers(cls, headers: Any) -> Optional["TraceContext"]:
        try:
            tid = headers.get(TRACE_ID_HEADER)
            sid = headers.get(PARENT_SPAN_HEADER)
        except AttributeError:
            return None
        if not tid or not sid:
            return None
        return cls(trace_id=str(tid), span_id=str(sid))


# Thread-local stack of entered spans (innermost last). `add_event` and
# `current_context` read the top; `with span:` pushes/pops.
_tls = threading.local()


def _active_stack() -> list["Span"]:
    stack = getattr(_tls, "spans", None)
    if stack is None:
        stack = _tls.spans = []
    return stack


def current_span() -> Optional["Span"]:
    stack = _active_stack()
    return stack[-1] if stack else None


def current_context() -> Optional[TraceContext]:
    sp = current_span()
    return sp.context() if sp is not None else None


def current_headers() -> dict[str, str]:
    """Propagation headers for the calling thread's active span ({} when
    none — the RPC channel stamps these on every outbound request)."""
    ctx = current_context()
    return ctx.to_headers() if ctx is not None else {}


def add_event(name: str, **attrs: Any) -> None:
    """Stamp an event onto the calling thread's active span (no-op without
    one). The fault plane calls this on every fired rule."""
    sp = current_span()
    if sp is not None:
        sp.event(name, **attrs)


class Span:
    """One timed hop of a request. Context-manager entry makes it the
    thread's active span (fault events land on it, RPC headers carry its
    context); exit ends it. `end()` is idempotent — the first call records
    the span into the store."""

    __slots__ = ("point", "trace_id", "span_id", "parent_span_id",
                 "request_id", "instance", "start_ms", "end_ms", "status",
                 "attrs", "events", "_tracer")

    def __init__(self, tracer: "Tracer", point: str,
                 ctx: Optional[TraceContext], request_id: str,
                 instance: str, attrs: dict[str, Any]):
        self._tracer = tracer
        self.point = point
        self.trace_id = ctx.trace_id if ctx else _new_trace_id()
        self.span_id = _new_span_id()
        self.parent_span_id = ctx.span_id if ctx else ""
        self.request_id = request_id
        self.instance = instance
        self.start_ms = _now_ms()
        self.end_ms: Optional[float] = None
        self.status = "OK"
        self.attrs = attrs
        self.events: list[dict[str, Any]] = []

    def context(self) -> TraceContext:
        """Context for children of THIS span (downstream hops, headers)."""
        return TraceContext(trace_id=self.trace_id, span_id=self.span_id)

    def set(self, **attrs: Any) -> "Span":
        self.attrs.update(attrs)
        return self

    def event(self, name: str, **attrs: Any) -> None:
        ev: dict[str, Any] = {"ts_ms": _now_ms(), "name": name}
        if attrs:
            ev.update(attrs)
        self.events.append(ev)

    def end(self, status: Optional[str] = None) -> None:
        if self.end_ms is not None:
            return
        if status is not None:
            self.status = status
        self.end_ms = _now_ms()
        self._tracer._record(self)

    def duration_ms(self) -> float:
        return (self.end_ms if self.end_ms is not None
                else _now_ms()) - self.start_ms

    def to_dict(self) -> dict[str, Any]:
        return {
            "point": self.point,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_span_id": self.parent_span_id,
            "request_id": self.request_id,
            "instance": self.instance,
            "start_ms": self.start_ms,
            "end_ms": self.end_ms,
            "duration_ms": round(self.duration_ms(), 3),
            "status": self.status,
            "attrs": dict(self.attrs),
            "events": list(self.events),
        }

    def __enter__(self) -> "Span":
        _active_stack().append(self)
        return self

    def __exit__(self, exc_type, exc, _tb) -> None:
        stack = _active_stack()
        if stack and stack[-1] is self:
            stack.pop()
        elif self in stack:        # unbalanced exit (shouldn't happen)
            stack.remove(self)
        self.end("ERROR: " + repr(exc) if exc is not None else None)


class _NoopSpan:
    """Shared do-nothing span returned while tracing is disabled: one
    attribute check + this singleton is the whole disabled-path cost."""

    __slots__ = ()

    def context(self) -> None:
        return None

    def set(self, **attrs: Any) -> "_NoopSpan":
        return self

    def event(self, name: str, **attrs: Any) -> None:
        pass

    def end(self, status: Optional[str] = None) -> None:
        pass

    def duration_ms(self) -> float:
        return 0.0

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass

    def __bool__(self) -> bool:
        return False


NOOP_SPAN = _NoopSpan()


class SpanStore:
    """Bounded per-process ring of finished spans, indexed by trace_id and
    request_id. Eviction is strictly FIFO over spans; a trace disappears
    from the index once its last span is evicted.

    Tail sampling support: spans of sampled-out traces park in a bounded
    side buffer (`_pending`, whole traces, FIFO evicted) instead of the
    ring. `promote()` moves a pending trace into the ring (the request
    ended anomalously — failover/error/SLO breach always record);
    `drop()` discards it (clean exit). Pending spans stay queryable by
    trace_id until evicted, so a live sampled-out request can still be
    debugged."""

    def __init__(self, capacity: int = 2048):
        self.capacity = max(1, int(capacity))
        self._lock = make_lock("tracing.span_store", order=820)  # lock-order: 820
        self._ring: deque[Span] = deque()
        self._by_trace: dict[str, list[Span]] = {}
        # request_id -> trace_id, insertion-ordered for bounded eviction.
        self._req_index: OrderedDict[str, str] = OrderedDict()
        # Sampled-out traces awaiting their tail-based keep/drop verdict.
        self._pending: OrderedDict[str, list[Span]] = OrderedDict()
        self._pending_traces_cap = max(16, self.capacity // 4)

    def add(self, span: Span) -> None:
        with self._lock:
            self._ring.append(span)
            self._by_trace.setdefault(span.trace_id, []).append(span)
            if span.request_id:
                self._req_index[span.request_id] = span.trace_id
                self._req_index.move_to_end(span.request_id)
                while len(self._req_index) > 4 * self.capacity:
                    self._req_index.popitem(last=False)
            while len(self._ring) > self.capacity:
                old = self._ring.popleft()
                spans = self._by_trace.get(old.trace_id)
                if spans is not None:
                    try:
                        spans.remove(old)
                    except ValueError:
                        pass
                    if not spans:
                        self._by_trace.pop(old.trace_id, None)

    def add_pending(self, span: Span) -> None:
        """Park a sampled-out trace's span pending the tail verdict."""
        with self._lock:
            spans = self._pending.get(span.trace_id)
            if spans is None:
                spans = self._pending[span.trace_id] = []
                _lifecycle.note_acquire("span-pending", key=span.trace_id)
                while len(self._pending) > self._pending_traces_cap:
                    old_tid, _ = self._pending.popitem(last=False)
                    _lifecycle.note_release("span-pending", key=old_tid)
            spans.append(span)
            if span.request_id:
                self._req_index[span.request_id] = span.trace_id
                self._req_index.move_to_end(span.request_id)
                while len(self._req_index) > 4 * self.capacity:
                    self._req_index.popitem(last=False)

    def promote(self, trace_id: str) -> None:
        """Tail-based keep: move a pending trace into the ring."""
        with self._lock:
            spans = self._pending.pop(trace_id, None)
            if spans is not None:
                _lifecycle.note_release("span-pending", key=trace_id)
        for s in spans or ():
            self.add(s)

    def drop(self, trace_id: str) -> None:
        """Tail-based drop: the request ended cleanly; discard."""
        with self._lock:
            if self._pending.pop(trace_id, None) is not None:
                _lifecycle.note_release("span-pending", key=trace_id)

    def trace(self, trace_id: str) -> list[dict[str, Any]]:
        with self._lock:
            spans = list(self._by_trace.get(trace_id, ()))
            spans += self._pending.get(trace_id, ())
        return [s.to_dict() for s in sorted(spans, key=lambda s: s.start_ms)]

    def trace_id_for_request(self, request_id: str) -> Optional[str]:
        with self._lock:
            return self._req_index.get(request_id)

    def summaries(self, limit: int = 20,
                  sort: str = "recent") -> list[dict[str, Any]]:
        """Per-trace one-liners. `sort`: "recent" (latest start first) or
        "slowest" (longest total duration first)."""
        with self._lock:
            traces = {tid: list(spans)
                      for tid, spans in self._by_trace.items()}
        rows = []
        for tid, spans in traces.items():
            start = min(s.start_ms for s in spans)
            end = max(s.end_ms if s.end_ms is not None else s.start_ms
                      for s in spans)
            root = next((s for s in spans if not s.parent_span_id), None)
            rid = next((s.request_id for s in spans if s.request_id), "")
            rows.append({
                "trace_id": tid,
                "request_id": rid,
                "root_point": root.point if root else "",
                "start_ms": start,
                "duration_ms": round(end - start, 3),
                "num_spans": len(spans),
                "status": (root.status if root else "OK"),
            })
        key = "duration_ms" if sort == "slowest" else "start_ms"
        rows.sort(key=lambda r: r[key], reverse=True)
        return rows[:max(0, int(limit))]

    def recent_trace_spans(self, limit: int = 50) -> list[list[dict]]:
        """Span dicts of the most recently started traces (newest last) —
        the /admin/hotpath critical-path aggregation input."""
        with self._lock:
            traces = [(min(s.start_ms for s in spans), list(spans))
                      for spans in self._by_trace.values() if spans]
        traces.sort(key=lambda t: t[0])
        return [[s.to_dict() for s in spans]
                for _, spans in traces[-max(1, int(limit)):]]

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._by_trace.clear()
            self._req_index.clear()
            self._pending.clear()
        _lifecycle.note_reset("span-pending")


def span_tree(spans: list[dict[str, Any]]) -> list[dict[str, Any]]:
    """Assemble flat span dicts into a parent/children forest, children
    ordered by start time. Spans whose parent was evicted from the ring
    become roots (the forest is still complete and ordered)."""
    by_id = {s["span_id"]: dict(s, children=[]) for s in spans}
    roots: list[dict[str, Any]] = []
    for s in sorted(by_id.values(), key=lambda s: s["start_ms"]):
        parent = by_id.get(s["parent_span_id"])
        if parent is not None and parent is not s:
            parent["children"].append(s)
        else:
            roots.append(s)
    return roots


class Tracer:
    """Process-global tracer façade. `enabled=False` turns every span call
    into a no-op; `mirror` (optional callable taking the span dict) lets
    the HTTP layer tee finished spans into the RequestTracer JSONL.

    `sample_rate` < 1.0 enables head sampling with a tail-based keep: the
    keep decision is a deterministic hash of the trace_id (so every
    process of the fleet samples the SAME traces without coordination),
    sampled-out spans park in the store's pending buffer, and
    `keep_trace()` (called by the request-exit path on failover / error /
    SLO breach) promotes them into the queryable ring — anomalies always
    record; `drop_trace()` discards a clean exit."""

    def __init__(self, capacity: int = 2048):
        self.enabled = True
        self.sample_rate = 1.0
        self.store = SpanStore(capacity)
        self._mirror: Optional[Callable[[dict[str, Any]], None]] = None
        # Traces force-kept by a tail decision: later spans of the same
        # trace (e.g. an engine decode ending after the promote) go
        # straight to the ring. Bounded ordered set.
        self._kept: OrderedDict[str, None] = OrderedDict()

    def configure(self, enabled: Optional[bool] = None,
                  capacity: Optional[int] = None,
                  mirror: Any = "__unset__",
                  sample_rate: Optional[float] = None) -> None:
        if enabled is not None:
            self.enabled = enabled
        if capacity is not None and capacity != self.store.capacity:
            self.store = SpanStore(capacity)
        if mirror != "__unset__":
            self._mirror = mirror
        if sample_rate is not None:
            self.sample_rate = min(1.0, max(0.0, float(sample_rate)))

    # ------------------------------------------------------- tail sampling
    def is_sampled(self, trace_id: str) -> bool:
        """Deterministic head-sampling verdict for a trace. Hash-based so
        every process in the fleet agrees from the trace_id alone."""
        rate = self.sample_rate
        if rate >= 1.0:
            return True
        if rate <= 0.0:
            return False
        h = int.from_bytes(blake2b(trace_id.encode(),
                                   digest_size=8).digest(), "big")
        return (h % 1_000_000) < rate * 1_000_000

    def keep_trace(self, trace_id: str) -> None:
        """Tail-based keep: the request ended in failover/error/SLO
        breach — promote its pending spans and record future ones."""
        if not trace_id:
            return
        if self.sample_rate >= 1.0 and not self.store._pending:
            # Fast path at full sampling (everything records directly) —
            # but a NON-empty pending buffer means the rate was just
            # raised live: parked traces must still get their verdict,
            # or they'd be stranded in memory forever.
            return
        self._kept[trace_id] = None
        while len(self._kept) > 1024:
            self._kept.popitem(last=False)
        self.store.promote(trace_id)

    def drop_trace(self, trace_id: str) -> None:
        """Tail-based drop: clean exit of a sampled-out trace."""
        if not trace_id:
            return
        if self.sample_rate >= 1.0 and not self.store._pending:
            return   # fast path; see keep_trace
        if trace_id not in self._kept:
            self.store.drop(trace_id)

    def start_span(self, point: str, ctx: Optional[TraceContext] = None,
                   request_id: str = "", instance: str = "",
                   require_ctx: bool = False, **attrs: Any):
        """New span (recorded on `end()`). Without `ctx` it roots a fresh
        trace — unless `require_ctx` is set, which returns the no-op span
        instead (for hops that must not root orphan single-span traces
        when the request carried no context). Enter it (`with`) to make
        it the thread's active span."""
        if not self.enabled or (require_ctx and ctx is None):
            return NOOP_SPAN
        return Span(self, point, ctx, request_id, instance, attrs)

    # Alias kept distinct in name for call sites that always use the span
    # as a context manager; same registry (xlint checks both).
    span = start_span

    def _record(self, span: Span) -> None:
        if not self.enabled:
            # The span outlived a live-disable (admin toggle, or a
            # process whose next master boots with tracing off while a
            # predecessor's straggler spans wind down): drop it, or
            # disabled-tracing runs observe phantom traces.
            return
        if self.sample_rate >= 1.0 or span.trace_id in self._kept \
                or self.is_sampled(span.trace_id):
            self.store.add(span)
        else:
            self.store.add_pending(span)
        mirror = self._mirror
        if mirror is not None:
            try:
                mirror(span.to_dict())
            except Exception:  # noqa: BLE001 — tracing must never break the request path
                logger.exception("span mirror failed")

    # ---------------------------------------------------------- query API
    def query_trace(self, request_id: str = "",
                    trace_id: str = "") -> tuple[int, dict[str, Any]]:
        """Shared backend for the /admin/trace endpoints (master + engine
        agent): returns (http_status, payload)."""
        tid = trace_id
        if not tid and request_id:
            tid = self.store.trace_id_for_request(request_id) or ""
        if not tid:
            return 404, {"error": "unknown request_id (pass request_id= or "
                                  "trace_id=)"}
        spans = self.store.trace(tid)
        if not spans:
            return 404, {"error": f"no spans recorded for trace {tid}"}
        from ..profiling import critical_path

        payload = {"trace_id": tid,
                   "request_id": request_id
                   or next((s["request_id"] for s in spans
                            if s["request_id"]), ""),
                   "num_spans": len(spans),
                   "spans": spans,
                   "tree": span_tree(spans)}
        cp = critical_path(spans)
        if cp is not None:
            payload["critical_path"] = cp
        return 200, payload

    def query_recent(self, limit: int = 20,
                     sort: str = "recent") -> dict[str, Any]:
        if sort not in ("recent", "slowest"):
            sort = "recent"
        return {"sort": sort, "traces": self.store.summaries(limit, sort)}


#: Process-global tracer. The service/agent configure it from options;
#: default is enabled with a modest ring (cheap: spans are small dicts).
TRACER = Tracer()


def merge_fleet_spans(span_lists: list[list[dict[str, Any]]]
                      ) -> list[dict[str, Any]]:
    """Merge per-process span dicts for ONE trace into a single ordered
    list, deduped by span_id (fleet fan-out targets may overlap — e.g. an
    in-process engine sharing the frontend's store)."""
    seen: dict[str, dict[str, Any]] = {}
    for spans in span_lists:
        for s in spans:
            sid = s.get("span_id", "")
            if sid and sid not in seen:
                seen[sid] = s
    return sorted(seen.values(), key=lambda s: s.get("start_ms", 0.0))


def make_trace_handlers(tracer: "Tracer"):
    """aiohttp handlers bound to a specific tracer instance (tests spin up
    standalone peer span-servers this way). Returns
    ``(handle_trace, handle_trace_recent)``."""

    async def handle_trace(request):
        from aiohttp import web

        status, payload = tracer.query_trace(
            request_id=request.query.get("request_id", ""),
            trace_id=request.query.get("trace_id", ""))
        return web.json_response(payload, status=status)

    async def handle_trace_recent(request):
        from aiohttp import web

        try:
            limit = int(request.query.get("limit", 20))
        except ValueError:
            return web.json_response({"error": "limit must be an integer"},
                                     status=400)
        return web.json_response(tracer.query_recent(
            limit=limit, sort=request.query.get("sort", "recent")))

    return handle_trace, handle_trace_recent


# Shared aiohttp handlers for the /admin/trace query surface — the master
# HTTP app, the engine agent and the fake engine all register these (each
# process serves its own SpanStore's view of a trace; the master's
# fleet-scope handler in http_service/service.py fans out to every
# peer's copy of this endpoint and merges).
handle_admin_trace, handle_admin_trace_recent = make_trace_handlers(TRACER)
