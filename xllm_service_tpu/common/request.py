"""Request and engine-output types.

Parity: reference `xllm_service/request/request.h:28-85` (Request) and
`common/xllm/output.h:68-133` / `status.h` (llm::RequestOutput, Status).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from .hashing import extend_prefix_block_hashes, prefix_block_hashes
from .types import Routing, RequestMetrics, now_ms


class StatusCode(enum.IntEnum):
    """Mirror of the reference's llm::StatusCode (`common/xllm/status.h`)."""

    OK = 0
    CANCELLED = 1
    UNKNOWN = 2
    INVALID_ARGUMENT = 3
    DEADLINE_EXCEEDED = 4
    RESOURCE_EXHAUSTED = 8
    UNAVAILABLE = 14


@dataclass
class Status:
    code: StatusCode = StatusCode.OK
    message: str = ""

    def ok(self) -> bool:
        return self.code == StatusCode.OK


@dataclass
class LogProbData:
    token: str = ""
    token_id: int = -1
    logprob: float = 0.0


@dataclass
class LogProb:
    """One generated token's logprob + top alternatives
    (reference `output.h` LogProb; proto `DisaggStreamGeneration.logprobs`)."""

    token: str = ""
    token_id: int = -1
    logprob: float = 0.0
    top_logprobs: list[LogProbData] = field(default_factory=list)


@dataclass
class SequenceOutput:
    """One choice's incremental output
    (reference proto `xllm_rpc_service.proto:126-142` SequenceOutput)."""

    index: int = 0
    text: str = ""
    token_ids: list[int] = field(default_factory=list)
    finish_reason: str = ""
    logprobs: list[LogProb] = field(default_factory=list)


@dataclass
class Usage:
    num_prompt_tokens: int = 0
    num_generated_tokens: int = 0

    @property
    def num_total_tokens(self) -> int:
        return self.num_prompt_tokens + self.num_generated_tokens


@dataclass
class RequestOutput:
    """Engine → service generation delta (reference `output.h:68-133`
    llm::RequestOutput / proto DisaggStreamGeneration)."""

    request_id: str = ""
    service_request_id: str = ""
    status: Status = field(default_factory=Status)
    outputs: list[SequenceOutput] = field(default_factory=list)
    usage: Optional[Usage] = None
    finished: bool = False
    # True when the request finished during the prefill stage (e.g. hit a stop
    # condition at first token) — lets the scheduler account FINISH_PREFILL
    # vs FINISH_DECODE (reference proto field `finished_on_prefill_instance`).
    finished_on_prefill: bool = False
    # Monotonic per-request delivery sequence number, assigned by the engine
    # agent's streamer. The Generations POST is retried on transient network
    # failure; the service dedupes on this so a retry whose original was in
    # fact processed (response lost) cannot double-deliver deltas.
    delta_seq: Optional[int] = None
    # Sender identity. After a transparent failover the request is bound to
    # new incarnations; deltas still in flight from the dead incarnation
    # must be dropped, which requires each delta to carry who produced it.
    instance: str = ""
    incarnation: str = ""

    def to_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {
            "request_id": self.request_id,
            "service_request_id": self.service_request_id,
            "status": {"code": int(self.status.code), "message": self.status.message},
            "outputs": [
                {
                    "index": o.index,
                    "text": o.text,
                    "token_ids": list(o.token_ids),
                    "finish_reason": o.finish_reason,
                    "logprobs": [
                        {
                            "token": lp.token,
                            "token_id": lp.token_id,
                            "logprob": lp.logprob,
                            "top_logprobs": [
                                {"token": t.token, "token_id": t.token_id, "logprob": t.logprob}
                                for t in lp.top_logprobs
                            ],
                        }
                        for lp in o.logprobs
                    ],
                }
                for o in self.outputs
            ],
            "finished": self.finished,
            "finished_on_prefill": self.finished_on_prefill,
        }
        if self.delta_seq is not None:
            d["delta_seq"] = self.delta_seq
        if self.instance:
            d["instance"] = self.instance
        if self.incarnation:
            d["incarnation"] = self.incarnation
        if self.usage is not None:
            d["usage"] = {
                "num_prompt_tokens": self.usage.num_prompt_tokens,
                "num_generated_tokens": self.usage.num_generated_tokens,
            }
        return d

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "RequestOutput":
        st = d.get("status") or {}
        usage = d.get("usage")
        return cls(
            request_id=d.get("request_id", ""),
            service_request_id=d.get("service_request_id", ""),
            status=Status(StatusCode(st.get("code", 0)), st.get("message", "")),
            outputs=[
                SequenceOutput(
                    index=o.get("index", 0),
                    text=o.get("text", ""),
                    token_ids=list(o.get("token_ids", ())),
                    finish_reason=o.get("finish_reason", "") or "",
                    logprobs=[
                        LogProb(
                            token=lp.get("token", ""),
                            token_id=lp.get("token_id", -1),
                            logprob=lp.get("logprob", 0.0),
                            top_logprobs=[
                                LogProbData(t.get("token", ""), t.get("token_id", -1), t.get("logprob", 0.0))
                                for t in lp.get("top_logprobs", ())
                            ],
                        )
                        for lp in o.get("logprobs", ())
                    ],
                )
                for o in d.get("outputs", ())
            ],
            usage=Usage(usage.get("num_prompt_tokens", 0), usage.get("num_generated_tokens", 0)) if usage else None,
            finished=bool(d.get("finished", False)),
            finished_on_prefill=bool(d.get("finished_on_prefill", False)),
            delta_seq=d.get("delta_seq"),
            instance=d.get("instance", ""),
            incarnation=d.get("incarnation", ""),
        )


# Called with each RequestOutput delta; returns False to request cancellation
# (mirrors reference OutputCallback semantics, `output.h`).
OutputCallback = Callable[[RequestOutput], bool]


@dataclass
class SamplingParams:
    """Generation controls parsed from the OpenAI request body."""

    max_tokens: int = 16
    temperature: float = 1.0
    top_p: float = 1.0
    top_k: int = -1
    n: int = 1
    logprobs: bool = False
    top_logprobs: int = 0
    frequency_penalty: float = 0.0
    presence_penalty: float = 0.0
    repetition_penalty: float = 1.0
    stop: list[str] = field(default_factory=list)
    stop_token_ids: list[int] = field(default_factory=list)
    seed: Optional[int] = None
    ignore_eos: bool = False
    echo: bool = False
    # OpenAI logit_bias: token id -> additive bias (first NUM_BIAS entries
    # applied device-side).
    logit_bias: dict[int, float] = field(default_factory=dict)

    def to_dict(self) -> dict[str, Any]:
        from dataclasses import asdict

        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "SamplingParams":
        sp = cls(**{k: v for k, v in d.items()
                    if k in cls.__dataclass_fields__})
        # JSON round-trips dict keys as strings.
        sp.logit_bias = {int(k): float(v)
                         for k, v in (sp.logit_bias or {}).items()}
        return sp


@dataclass
class Request:
    """Per-request record held by the service while the request is in flight.

    Parity: reference `request/request.h:28-85` — model, ids, stream flags,
    `offline` (online/offline hybrid scheduling hook), prompt/messages/tools,
    token_ids, routing + bound incarnation ids, prefill_stage_finished,
    num_generated_tokens, estimated ttft, callbacks, latest_generate_time.
    """

    service_request_id: str = ""
    request_id: str = ""          # client-visible id (cmpl-... / chatcmpl-...)
    model: str = ""
    stream: bool = False
    include_usage: bool = False   # stream_options.include_usage
    offline: bool = False         # online/offline hybrid scheduling hook
    priority: int = 0             # higher = more urgent (offline default 0)
    # Overload plane (overload/): admission priority class
    # ("interactive" | "batch" — batch is shed first under overload and
    # max_tokens-clamped under brownout), the absolute end-to-end
    # deadline (epoch ms; 0 = none — carried in the enriched payload and
    # the handoff wire, enforced at every hop), and whether this request
    # holds an admission-gate slot (released exactly once at exit).
    priority_class: str = "interactive"
    deadline_ms: int = 0
    admitted: bool = False
    # Inputs.
    prompt: str = ""
    messages: list[dict[str, Any]] = field(default_factory=list)
    tools: list[dict[str, Any]] = field(default_factory=list)
    has_images: bool = False
    chat_template_kwargs: dict[str, Any] = field(default_factory=dict)
    token_ids: list[int] = field(default_factory=list)
    sampling: SamplingParams = field(default_factory=SamplingParams)
    # Completions `echo`: prompt chunk already streamed back.
    echo_emitted: bool = False
    # Routing decision + bound incarnations (stale-output suppression).
    routing: Routing = field(default_factory=Routing)
    prefill_incarnation: str = ""
    decode_incarnation: str = ""
    # Progress.
    prefill_stage_finished: bool = False
    num_generated_tokens: int = 0
    metrics: RequestMetrics = field(default_factory=RequestMetrics)
    created_time_ms: int = field(default_factory=now_ms)
    latest_generate_time_ms: int = field(default_factory=now_ms)
    # Callbacks (installed by the HTTP layer / tests).
    output_callback: Optional[OutputCallback] = None
    trace_callback: Optional[Callable[[str, Any], None]] = None
    # Tracing (common/tracing.py): the root span owned by this request
    # (ended at exit accounting) and its portable context, carried in the
    # enriched engine payload / RPC headers so downstream hops parent
    # their spans correctly. `Any` to keep this module import-light.
    span: Optional[Any] = None
    trace: Optional[Any] = None
    # Memoized chained block hashes of token_ids (common/hashing.py):
    # computed once (scheduler.tokenize stage warms it for CAR) and reused
    # by the CAR match, failover re-selection and any writeback path —
    # token_ids only ever GROWS for a live request (failover prompt
    # extension), so the chain extends incrementally.
    _block_hashes: Optional[list] = field(default=None, init=False,
                                          repr=False)
    _hash_block_size: int = field(default=0, init=False, repr=False)

    def prefix_hashes(self, block_size: int) -> list:
        """Chained block hashes of ``token_ids``, memoized on the request.
        Safe because a request's token prefix is append-only; a different
        ``block_size`` (config reload between calls) recomputes."""
        n_blocks = len(self.token_ids) // block_size if block_size > 0 else 0
        cached = self._block_hashes
        if cached is not None and self._hash_block_size == block_size:
            if len(cached) == n_blocks:
                return cached
            if len(cached) < n_blocks:
                cached = extend_prefix_block_hashes(
                    cached, self.token_ids, block_size)
                self._block_hashes = cached
                return cached
        cached = prefix_block_hashes(self.token_ids, block_size)
        self._hash_block_size = block_size
        self._block_hashes = cached
        return cached

    def touch(self) -> None:
        self.latest_generate_time_ms = now_ms()
