"""Per-request client-connection abstraction.

Parity: reference `common/call_data.h` CallData / StreamCallData — the
response writer handed to the scheduler's output callbacks: `write()` frames
one SSE `data: <json>\n\n` chunk (`call_data.h:177-197`), `finish()` sends
`data: [DONE]` (`call_data.h:199-205`), `finish_with_error` maps to an HTTP
error body, `is_disconnected` surfaces client aborts so generation can be
cancelled upstream (`call_data.h:207-216`). The HTTP layer implements this
over aiohttp streaming responses; tests use :class:`CollectingConnection`.
"""

from __future__ import annotations

import abc
import json
from typing import Any, Optional


class ClientConnection(abc.ABC):
    stream: bool = False

    @abc.abstractmethod
    def write_event(self, event: str, obj: dict[str, Any]) -> bool:
        """Named SSE event (`event: <name>` framing — the Anthropic
        Messages stream shape). Default: plain data write."""
        return self.write(obj)

    def write(self, obj: dict[str, Any]) -> bool:
        """Deliver one payload (SSE chunk when streaming). Returns False if
        the client is gone."""

    @abc.abstractmethod
    def finish(self) -> bool:
        """Complete the response ([DONE] sentinel when streaming)."""

    def write_and_finish(self, obj: dict[str, Any]) -> bool:
        ok = self.write(obj)
        return self.finish() and ok

    @abc.abstractmethod
    def finish_with_error(self, code: int, message: str) -> bool: ...

    @abc.abstractmethod
    def is_disconnected(self) -> bool: ...


def sse_frame(obj: dict[str, Any] | str) -> bytes:
    data = obj if isinstance(obj, str) else json.dumps(obj, ensure_ascii=False)
    return f"data: {data}\n\n".encode()


SSE_DONE = b"data: [DONE]\n\n"


class CollectingConnection(ClientConnection):
    """Test double: records everything written."""

    def __init__(self, stream: bool = False):
        self.stream = stream
        self.payloads: list[dict[str, Any]] = []
        self.events: list[tuple[str, dict[str, Any]]] = []
        self.finished = False
        self.error: Optional[tuple[int, str]] = None
        self.disconnected = False

    def write(self, obj: dict[str, Any]) -> bool:
        if self.disconnected:
            return False
        self.payloads.append(obj)
        return True

    def write_event(self, event: str, obj: dict[str, Any]) -> bool:
        if self.disconnected:
            return False
        self.events.append((event, obj))
        self.payloads.append(obj)
        return True

    def finish(self) -> bool:
        self.finished = True
        return not self.disconnected

    def finish_with_error(self, code: int, message: str) -> bool:
        self.error = (code, message)
        self.finished = True
        return True

    def is_disconnected(self) -> bool:
        return self.disconnected
