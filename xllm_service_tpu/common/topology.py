"""ICI-topology link-cost kernel — the one shared model of what a KV
handoff between two instances costs.

Every instance registers a topology coordinate ``(slice_id, host, chip)``
(`TpuTopology.slice_id/host/chip`, common/types.py). The kernel below is
*pure*: no clocks, no locks, no I/O — routing policies (RR/CAR/SLO),
the planner, the autoscaler, the kv_transfer link derivation, and the
bench all call the same three functions so they can never disagree about
which pair rides ICI and which pays DCN.

Placement semantics (the "flat fleets behave exactly as today" rule):

* An instance is **placed** only when its topology carries a non-empty
  ``host``. ``slice_id`` alone does NOT place it — agents have always
  defaulted to ``slice_id="slice-0"`` and fake engines to
  ``"fake-slice"``, so keying off slice_id would silently re-route
  every existing deployment.
* An **unplaced** instance gets a synthetic per-host coordinate derived
  from its registered name (``host:port``): slice ``host:<h>``, host
  ``<h>``. A flat fleet on one box therefore collapses into ONE
  synthetic slice and the whole plane stays dormant
  (`fleet_topo_active` is False ⇒ zero routing behavior change).

Link classes and cost:

* ``local`` — same host: the handoff never leaves the machine.
* ``ici``   — same slice, different host: inter-chip interconnect.
* ``dcn``   — different slices: data-center network, the slow path.

``transfer_cost(nbytes, link)`` is seconds of modeled wire time, seeded
from the same per-class budgets the engine's ``BandwidthAccountant``
paces with (engine/kv_transfer.py). A budget of 0 means "account only,
don't throttle" on the engine side; here it falls back to class-default
bandwidths so the *ordering* local < ici < dcn survives even on
unthrottled fleets — the knob trades absolute accuracy for a stable
preference, which is what placement needs.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

LINK_LOCAL = "local"
LINK_ICI = "ici"
LINK_DCN = "dcn"

#: Class-default bandwidths (bytes/s) used when the matching accountant
#: budget is 0 (= account-only). Shaped after v5e numbers: ~1.6 Tbps ICI
#: per link vs ~25 Gbps DCN per host; `local` is host-memory speed. Only
#: the ORDER matters for placement — absolute values only matter when a
#: deployment actually throttles.
DEFAULT_BYTES_PER_S = {
    LINK_LOCAL: 400e9,
    LINK_ICI: 100e9,
    LINK_DCN: 3.125e9,
}

#: Normalized link penalty in [0, 1] for score-space consumers (CAR):
#: a knob value t means "a DCN pair must beat an ICI pair by > ~t score
#: units to win". Derived from the default-bandwidth ratios, then
#: clamped to a readable scale.
LINK_PENALTY = {LINK_LOCAL: 0.0, LINK_ICI: 0.03, LINK_DCN: 1.0}


class Coord(NamedTuple):
    """Effective placement coordinate. `placed` is False for synthetic
    (per-host fallback) coordinates — consumers that want to act only on
    operator-declared topology can check it."""

    slice_id: str
    host: str
    chip: int = -1
    placed: bool = False


def effective_coord(topology, instance_name: str) -> Coord:
    """Coordinate for an instance, synthesizing a per-host slice when the
    registration didn't place it (no ``host``).

    ``topology`` is a ``TpuTopology`` or None. ``instance_name`` is the
    registry identity (typically ``host:http_port``)."""
    host = getattr(topology, "host", "") if topology is not None else ""
    if host:
        slice_id = getattr(topology, "slice_id", "") or f"host:{host}"
        return Coord(slice_id, host, int(getattr(topology, "chip", -1)),
                     placed=True)
    h = instance_name.rsplit(":", 1)[0] if instance_name else ""
    return Coord(f"host:{h}", h, -1, placed=False)


def link_class(a: Coord, b: Coord) -> str:
    """Pure link classification between two effective coordinates."""
    if a.host and a.host == b.host:
        return LINK_LOCAL
    if a.slice_id and a.slice_id == b.slice_id:
        return LINK_ICI
    return LINK_DCN


def link_penalty(link: str) -> float:
    return LINK_PENALTY.get(link, LINK_PENALTY[LINK_DCN])


def transfer_cost(nbytes: int, link: str,
                  ici_bytes_per_s: float = 0.0,
                  dcn_bytes_per_s: float = 0.0) -> float:
    """Modeled seconds to move ``nbytes`` over ``link``.

    The two budget arguments mirror `BandwidthAccountant`'s constructor;
    0 (= account-only on the engine side) falls back to the class
    default so the cost ordering is preserved on unthrottled fleets.
    ``local`` always uses its class default — the accountant has no
    intra-host budget to borrow."""
    if nbytes <= 0:
        return 0.0
    if link == LINK_ICI and ici_bytes_per_s > 0:
        bps = ici_bytes_per_s
    elif link == LINK_DCN and dcn_bytes_per_s > 0:
        bps = dcn_bytes_per_s
    else:
        bps = DEFAULT_BYTES_PER_S.get(link, DEFAULT_BYTES_PER_S[LINK_DCN])
    return nbytes / bps


def kv_handoff_bytes(meta, tokens: int) -> int:
    """Estimated prefill→decode KV payload for ``tokens`` prompt tokens,
    from the KV-layout contract an instance advertises at registration
    (``InstanceMetaInfo.num_layers/num_kv_heads/head_dim/kv_dtype``).
    Returns 0 when the layout is unadvertised (fake engines) — callers
    then substitute their own modeled payload size."""
    if meta is None or tokens <= 0:
        return 0
    layers = int(getattr(meta, "num_layers", 0) or 0)
    heads = int(getattr(meta, "num_kv_heads", 0) or 0)
    head_dim = int(getattr(meta, "head_dim", 0) or 0)
    if layers <= 0 or heads <= 0 or head_dim <= 0:
        return 0
    dtype = str(getattr(meta, "kv_dtype", "") or "bfloat16").lower()
    itemsize = 1 if ("int8" in dtype or "fp8" in dtype or "e4m3" in dtype
                     or "e5m2" in dtype) else (4 if "32" in dtype else 2)
    # K and V planes.
    return 2 * layers * heads * head_dim * itemsize * tokens


def pair_link(topo_a, name_a: str, topo_b, name_b: str) -> str:
    """Convenience: link class straight from two registrations."""
    return link_class(effective_coord(topo_a, name_a),
                      effective_coord(topo_b, name_b))


def fleet_topo_active(coords) -> bool:
    """True when placement should engage: >= 2 distinct effective slices
    among the given coordinates. One slice (the flat-fleet collapse) ⇒
    every pair costs the same ⇒ stay dormant and keep legacy ordering."""
    first: Optional[str] = None
    for c in coords:
        if first is None:
            first = c.slice_id
        elif c.slice_id != first:
            return True
    return False
