"""Loader for the native hot-path core (csrc/hotcore.c → libhotcore.so).

One loader, four components, one kill switch. The continuous profiler
(BENCH_profile_r19.json) blamed four frame families for most of the
master's route/stream CPU; hotcore.c reimplements exactly those, and
this module is the only place that decides native-vs-Python:

==============  =========================================================
component       fast path (call sites keep a mandatory pure fallback)
==============  =========================================================
``wire``        msgpack pack/unpack + fused base64 form for LOADFRAME /
                telemetry frames (rpc/wire.py)
``sse``         SSE ``data: ...\\n\\n`` frame assembly + compact JSON
                (http_service/service.py _respond emit loop)
``rendezvous``  blake2b-8 highest-random-weight walk over the member set
                (multimaster/ownership.py)
``tokenizer``   SimpleTokenizer's utf8-byte+offset encode — the single
                hottest route frame (tokenizer/simple.py)
==============  =========================================================

Contract (mirrors common/hashing.py's optional-extension pattern):

- ``XLLM_NATIVE=0`` forces pure Python everywhere; absent .so or a
  failed per-component parity self-test disables just that component.
- Every wrapper returns :data:`MISS` when the native path cannot serve
  the input **bit-exactly** (unsupported type, lone surrogate, ext
  msgpack, non-canonical base64, ...). The call site then runs the
  pure-Python code, which either handles the input or raises the
  canonical library error. Native never produces bytes Python wouldn't.
- The differential property tests (tests/test_native_hotcore.py) pin
  byte-for-byte agreement; a tiny parity self-test re-runs at load so a
  stale/mismatched .so degrades to Python instead of corrupting a wire.

``native_path_active{component}`` gauges (common/metrics.py) and the
flight-recorder context provider (wired by the HTTP service) expose
which processes in a fleet run degraded.
"""

from __future__ import annotations

import ctypes
import os
from pathlib import Path
from typing import Any, Optional

from ..utils import get_logger

logger = get_logger(__name__)

COMPONENTS = ("wire", "sse", "rendezvous", "tokenizer")

#: Sentinel for "native could not serve this input — run the pure path".
#: Distinct from None because decoders legitimately return None.
MISS: Any = object()

_SO_PATH = Path(__file__).resolve().parents[2] / "csrc" / "libhotcore.so"


def _switch_on() -> bool:
    return os.environ.get("XLLM_NATIVE", "") not in ("0", "false", "off")


class _Core:
    """Bound entry points of one loaded libhotcore.so."""

    _PYOBJ_FNS = ("hc_json_bytes", "hc_sse_data_frame", "hc_packb",
                  "hc_unpackb", "hc_pack_b64", "hc_unpack_b64",
                  "hc_tok_encode")

    def __init__(self, so_path: Path):
        # PyDLL: the GIL stays held — every entry point uses CPython APIs.
        lib = ctypes.PyDLL(str(so_path))
        lib.hc_abi_version.argtypes = []
        lib.hc_abi_version.restype = ctypes.c_int
        if lib.hc_abi_version() != 1:
            raise OSError(f"libhotcore ABI {lib.hc_abi_version()} != 1")
        for name in self._PYOBJ_FNS:
            fn = getattr(lib, name)
            fn.argtypes = [ctypes.py_object]
            fn.restype = ctypes.py_object
            setattr(self, name[3:], fn)
        for name in ("hc_sse_event_frame", "hc_rendezvous"):
            fn = getattr(lib, name)
            fn.argtypes = [ctypes.py_object, ctypes.py_object]
            fn.restype = ctypes.py_object
            setattr(self, name[3:], fn)


def _self_test(core: _Core) -> dict[str, bool]:
    """Per-component parity pins against known-good literals: a stale or
    miscompiled .so must degrade to Python, never corrupt a wire."""
    ok = {}
    probe = {"s": "é\n", "i": [0, -33, 70000], "f": 1.5, "n": None}
    try:
        ok["wire"] = (
            core.packb(probe) ==
            b"\x84\xa1s\xa3\xc3\xa9\n\xa1i\x93\x00\xd0\xdf\xce\x00\x01"
            b"\x11p\xa1f\xcb?\xf8\x00\x00\x00\x00\x00\x00\xa1n\xc0"
            and core.unpackb(core.packb(probe)) == probe
            and core.unpack_b64(core.pack_b64(probe)) == probe)
    except Exception:  # noqa: BLE001  # xlint: allow-broad-except(any self-test failure means "disable this component", whatever the exception)
        ok["wire"] = False
    try:
        ok["sse"] = (
            core.sse_data_frame(probe) ==
            b'data: {"s":"\xc3\xa9\\n","i":[0,-33,70000],"f":1.5,'
            b'"n":null}\n\n'
            and core.sse_event_frame("telemetry", {"a": 1}) ==
            b'event: telemetry\ndata: {"a":1}\n\n')
    except Exception:  # noqa: BLE001  # xlint: allow-broad-except(see above)
        ok["sse"] = False
    try:
        # blake2b("a|k", digest_size=8) beats "b|k" for this key.
        ok["rendezvous"] = (core.rendezvous(("a", "b"), "k") == "a"
                            and core.rendezvous((), "k") == "")
    except Exception:  # noqa: BLE001  # xlint: allow-broad-except(see above)
        ok["rendezvous"] = False
    try:
        ok["tokenizer"] = core.tok_encode("hé") == [360, 451, 425]
    except Exception:  # noqa: BLE001  # xlint: allow-broad-except(see above)
        ok["tokenizer"] = False
    return ok


_CORE: Optional[_Core] = None
_ACTIVE: dict[str, bool] = {c: False for c in COMPONENTS}


def _load() -> None:
    global _CORE, _ACTIVE
    core = None
    active = {c: False for c in COMPONENTS}
    if _switch_on():
        try:
            core = _Core(_SO_PATH)
        except OSError:
            core = None   # absent/unloadable .so: documented degraded mode
        if core is not None:
            active = _self_test(core)
            if not all(active.values()):
                logger.warning(
                    "libhotcore parity self-test failed for %s; those "
                    "components stay on the pure-Python path",
                    [c for c, v in active.items() if not v])
            if not any(active.values()):
                core = None
    _CORE = core
    _ACTIVE = active


_load()


def reload() -> dict:
    """Re-evaluate XLLM_NATIVE + the .so (tests flip the switch
    mid-process; check.sh asserts the loader's verdict)."""
    _load()
    return status()


def load_core(force: bool = False) -> Optional[_Core]:
    """The raw bound core, for the differential tests: ``force=True``
    loads the .so even when ``XLLM_NATIVE=0`` so one process can compare
    both paths. Returns None when the .so is absent/unloadable."""
    if _CORE is not None and not force:
        return _CORE
    try:
        return _Core(_SO_PATH)
    except OSError:
        return None


def available(component: Optional[str] = None) -> bool:
    if component is None:
        return _CORE is not None
    return _ACTIVE.get(component, False)


def status() -> dict:
    """Loader verdict (flight-recorder context + check.sh assertion)."""
    return {"enabled": _switch_on(),
            "loaded": _CORE is not None,
            "so": str(_SO_PATH),
            "components": dict(_ACTIVE)}


def export_gauges() -> None:
    """Refresh ``native_path_active{component}`` (scrape-time, like
    CPU_ATTR.export_counters)."""
    from .metrics import NATIVE_PATH_ACTIVE

    for c in COMPONENTS:
        NATIVE_PATH_ACTIVE.labels(component=c).set(
            1.0 if _ACTIVE.get(c) else 0.0)


# ----------------------------------------------------------------- wrappers
# Shape note: every wrapper is `if not active: MISS; try native except
# Exception: MISS` — the call site owns the pure path, so fallback code
# lives exactly once, next to the logic it mirrors.

def json_bytes(obj: Any) -> Any:
    """Compact-JSON bytes (ensure_ascii=False) or MISS."""
    if not _ACTIVE["sse"]:
        return MISS
    try:
        return _CORE.json_bytes(obj)
    except Exception:  # noqa: BLE001  # xlint: allow-broad-except(any native refusal degrades to the pure path; the fallback re-raises canonically for truly bad input)
        return MISS


def sse_data_frame(obj: Any) -> Any:
    """b"data: <json>\\n\\n" or MISS."""
    if not _ACTIVE["sse"]:
        return MISS
    try:
        return _CORE.sse_data_frame(obj)
    except Exception:  # noqa: BLE001  # xlint: allow-broad-except(see json_bytes)
        return MISS


def sse_event_frame(name: str, obj: Any) -> Any:
    """b"event: <name>\\ndata: <json>\\n\\n" or MISS."""
    if not _ACTIVE["sse"]:
        return MISS
    try:
        return _CORE.sse_event_frame(name, obj)
    except Exception:  # noqa: BLE001  # xlint: allow-broad-except(see json_bytes)
        return MISS


def packb(obj: Any) -> Any:
    """msgpack bytes (parity: msgpack.packb(use_bin_type=True)) or MISS."""
    if not _ACTIVE["wire"]:
        return MISS
    try:
        return _CORE.packb(obj)
    except Exception:  # noqa: BLE001  # xlint: allow-broad-except(see json_bytes)
        return MISS


def unpackb(data: bytes) -> Any:
    """Decoded object (parity: msgpack.unpackb(raw=False)) or MISS."""
    if not _ACTIVE["wire"]:
        return MISS
    try:
        return _CORE.unpackb(data)
    except Exception:  # noqa: BLE001  # xlint: allow-broad-except(see json_bytes)
        return MISS


def pack_b64(obj: Any) -> Any:
    """ascii str base64(msgpack(obj)) — the LOADFRAME wire — or MISS."""
    if not _ACTIVE["wire"]:
        return MISS
    try:
        return _CORE.pack_b64(obj)
    except Exception:  # noqa: BLE001  # xlint: allow-broad-except(see json_bytes)
        return MISS


def unpack_b64(value: Any) -> Any:
    """Decoded object from base64(msgpack) str/bytes, or MISS."""
    if not _ACTIVE["wire"]:
        return MISS
    try:
        return _CORE.unpack_b64(value)
    except Exception:  # noqa: BLE001  # xlint: allow-broad-except(see json_bytes)
        return MISS


def rendezvous(members: Any, key: str) -> Any:
    """Highest-random-weight member ("" when empty) or MISS. ``members``
    must be a tuple/list of str (the RCU-published member tuple is)."""
    if not _ACTIVE["rendezvous"]:
        return MISS
    try:
        return _CORE.rendezvous(members, key)
    except Exception:  # noqa: BLE001  # xlint: allow-broad-except(see json_bytes)
        return MISS


def tok_encode(text: str) -> Any:
    """[b + 256 for b in text.encode("utf-8")] or MISS."""
    if not _ACTIVE["tokenizer"]:
        return MISS
    try:
        return _CORE.tok_encode(text)
    except Exception:  # noqa: BLE001  # xlint: allow-broad-except(see json_bytes)
        return MISS
