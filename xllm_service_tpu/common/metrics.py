"""Service metrics: counters, gauges, histograms with Prometheus text export.

Parity: reference bvar macros (`common/metrics.h:50-104`) and the three
defined instruments (`metrics.h:108-111`): `server_request_in_total`,
`time_to_first_token_latency_milliseconds`,
`inter_token_latency_milliseconds`. The reference leaves `/metrics` empty
(`http_service/service.cpp:526-532`); we implement it properly
(SURVEY.md §5.5 "New framework: same shape, Prometheus-format /metrics done
properly").

Labels: instruments may declare `labelnames`; call sites then obtain a
child series via `.labels(instance=..., policy=...)` (all declared labels,
keyword-only) and the family renders every child with escaped, declared-
order label pairs (`_bucket` lines put `le` first). Reads (`value()`,
`render()`) take the same lock the writers take — a torn read of a float
is impossible in CPython, but consistent multi-field reads (histogram
bucket/sum/count, family child sets) are not, so everything reads locked.
"""

from __future__ import annotations

import re as _re
import threading
from bisect import bisect_right
from typing import Any, Iterable, Optional

from ..devtools import lifecycle as _lifecycle


def _escape_label_value(v: str) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _render_labels(pairs: Iterable[tuple[str, str]]) -> str:
    inner = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in pairs)
    return "{" + inner + "}" if inner else ""


class _Metric:
    def __init__(self, name: str, help_: str, labelnames: Iterable[str] = ()):
        self.name = name
        self.help = help_
        self.labelnames = tuple(labelnames)
        # Set on children created by labels(); () on families/plain series.
        self._labelvalues: tuple[str, ...] = ()

    def _label_suffix(self,
                      extra: Optional[tuple[str, str]] = None) -> str:
        pairs: list[tuple[str, str]] = [extra] if extra else []
        pairs += list(zip(self.labelnames, self._labelvalues))
        return _render_labels(pairs)

    def _child_key(self, kw: dict[str, Any]) -> tuple[str, ...]:
        if not self.labelnames:
            raise ValueError(
                f"metric {self.name} declares no labels; call inc()/set()/"
                f"observe() directly")
        if set(kw) != set(self.labelnames):
            raise ValueError(
                f"metric {self.name} requires exactly labels "
                f"{self.labelnames}, got {tuple(sorted(kw))}")
        return tuple(str(kw[k]) for k in self.labelnames)


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name: str, help_: str = "",
                 labelnames: Iterable[str] = ()):
        super().__init__(name, help_, labelnames)
        self._v = 0.0
        self._lock = threading.Lock()   # lock-order: 810
        self._children: dict[tuple[str, ...], Counter] = {}

    def labels(self, **kw: Any) -> "Counter":
        key = self._child_key(kw)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = Counter(self.name, self.help)
                child.labelnames = self.labelnames
                child._labelvalues = key
                self._children[key] = child
                _lifecycle.note_series_created(self.name, key)
            return child

    def remove(self, **kw: Any) -> None:
        """Drop one child series (e.g. an evicted instance) so /metrics
        stops exporting a stale label set."""
        key = self._child_key(kw)
        with self._lock:
            self._children.pop(key, None)

    def inc(self, v: float = 1.0) -> None:
        if self.labelnames and not self._labelvalues:
            raise ValueError(f"metric {self.name} is labeled; use "
                             f".labels(...).inc()")
        with self._lock:
            self._v += v

    def value(self) -> float:
        """Plain series: its value. Labeled family: the sum over children
        (the series-agnostic total callers assert on)."""
        with self._lock:
            v = self._v
            children = list(self._children.values())
        return v + sum(c.value() for c in children)

    def render(self) -> str:
        with self._lock:
            v = self._v
            children = sorted(self._children.items())
        if self.labelnames and not self._labelvalues:
            return "".join(c.render() for _, c in children)
        return f"{self.name}{self._label_suffix()} {v}\n"


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name: str, help_: str = "",
                 labelnames: Iterable[str] = ()):
        super().__init__(name, help_, labelnames)
        self._v = 0.0
        self._lock = threading.Lock()   # lock-order: 811
        self._children: dict[tuple[str, ...], Gauge] = {}

    def labels(self, **kw: Any) -> "Gauge":
        key = self._child_key(kw)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = Gauge(self.name, self.help)
                child.labelnames = self.labelnames
                child._labelvalues = key
                self._children[key] = child
                _lifecycle.note_series_created(self.name, key)
            return child

    def remove(self, **kw: Any) -> None:
        key = self._child_key(kw)
        with self._lock:
            self._children.pop(key, None)

    def set(self, v: float) -> None:
        if self.labelnames and not self._labelvalues:
            raise ValueError(f"metric {self.name} is labeled; use "
                             f".labels(...).set()")
        with self._lock:
            self._v = float(v)

    def inc(self, v: float = 1.0) -> None:
        if self.labelnames and not self._labelvalues:
            raise ValueError(f"metric {self.name} is labeled; use "
                             f".labels(...).inc()")
        with self._lock:
            self._v += v

    def value(self) -> float:
        with self._lock:
            v = self._v
            children = list(self._children.values())
        return v + sum(c.value() for c in children)

    def render(self) -> str:
        with self._lock:
            v = self._v
            children = sorted(self._children.items())
        if self.labelnames and not self._labelvalues:
            return "".join(c.render() for _, c in children)
        return f"{self.name}{self._label_suffix()} {v}\n"


_DEFAULT_BUCKETS = (1, 2, 5, 10, 20, 50, 100, 200, 500, 1000, 2000, 5000, 10000, 30000)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, help_: str = "",
                 buckets: Iterable[float] = _DEFAULT_BUCKETS,
                 labelnames: Iterable[str] = ()):
        super().__init__(name, help_, labelnames)
        self.buckets = sorted(buckets)
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._n = 0
        self._lock = threading.Lock()   # lock-order: 812
        self._children: dict[tuple[str, ...], Histogram] = {}

    def labels(self, **kw: Any) -> "Histogram":
        key = self._child_key(kw)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = Histogram(self.name, self.help, self.buckets)
                child.labelnames = self.labelnames
                child._labelvalues = key
                self._children[key] = child
                _lifecycle.note_series_created(self.name, key)
            return child

    def remove(self, **kw: Any) -> None:
        key = self._child_key(kw)
        with self._lock:
            self._children.pop(key, None)

    def observe(self, v: float) -> None:
        if self.labelnames and not self._labelvalues:
            raise ValueError(f"metric {self.name} is labeled; use "
                             f".labels(...).observe()")
        with self._lock:
            self._counts[bisect_right(self.buckets, v)] += 1
            self._sum += v
            self._n += 1

    def count(self) -> int:
        with self._lock:
            n = self._n
            children = list(self._children.values())
        return n + sum(c.count() for c in children)

    def mean(self) -> float:
        with self._lock:
            s, n = self._sum, self._n
            children = list(self._children.values())
        for c in children:
            with c._lock:
                s += c._sum
                n += c._n
        return s / n if n else 0.0

    def render(self) -> str:
        with self._lock:
            counts = list(self._counts)
            total_sum, total_n = self._sum, self._n
            children = sorted(self._children.items())
        if self.labelnames and not self._labelvalues:
            return "".join(c.render() for _, c in children)
        out = []
        cum = 0
        for b, c in zip(self.buckets, counts):
            cum += c
            out.append(f"{self.name}_bucket"
                       f"{self._label_suffix(('le', str(b)))} {cum}\n")
        cum += counts[-1]
        out.append(f"{self.name}_bucket"
                   f"{self._label_suffix(('le', '+Inf'))} {cum}\n")
        out.append(f"{self.name}_sum{self._label_suffix()} {total_sum}\n")
        out.append(f"{self.name}_count{self._label_suffix()} {total_n}\n")
        return "".join(out)


def evict_series(metric: _Metric, **labels: Any) -> None:
    """Drop one labeled child series when its owning entity goes away
    (instance evicted, PD peer unlinked, master changed).

    This is the single blessed release site for the ``metric-series``
    effect pair (devtools/lifecycle.py): xlint's ``pair-evict`` rule
    flags direct ``.remove()`` calls outside this module, and under
    ``XLLM_LEAK_DEBUG=1`` the evicted key is tombstoned so a later
    stale write re-creating the series (the PR-12 gauge-resurrection
    bug) is reported."""
    key = metric._child_key(labels)
    metric.remove(**labels)
    _lifecycle.note_series_evicted(metric.name, key)


def relabel_prometheus_text(text: str, instance: str, role: str,
                            strip_comments: bool = False) -> str:
    """Stamp fleet-target labels onto a scraped Prometheus exposition
    (the /metrics/fleet merge): every sample line gains
    ``instance="<addr>",role="frontend|engine"``. A series that already
    carries an ``instance`` label (the master's per-engine series) keeps
    it as ``exported_instance`` — the same collision rule Prometheus
    federation applies with honor_labels=false. Comment/blank lines pass
    through (or are dropped with ``strip_comments`` — the fleet merge
    strips foreign sources' ``# TYPE`` lines, which would duplicate);
    unparseable lines are dropped rather than corrupting the merged
    exposition."""
    extra = (f'instance="{_escape_label_value(instance)}",'
             f'role="{_escape_label_value(role)}"')

    def _is_value(v: str) -> bool:
        try:
            float(v.split()[0])
            return True
        except (ValueError, IndexError):
            return False

    out: list[str] = []
    for line in text.splitlines():
        if not line or line.startswith("#"):
            if line and not strip_comments:
                out.append(line)
            continue
        brace = line.find("{")
        if brace < 0:
            # name value [ts]
            parts = line.split(None, 1)
            if len(parts) != 2 or not _is_value(parts[1]):
                continue
            out.append(f"{parts[0]}{{{extra}}} {parts[1]}")
            continue
        close = line.rfind("}")
        if close < brace:
            continue
        name, labels, rest = line[:brace], line[brace + 1:close], \
            line[close + 1:].lstrip()
        if not rest or not _is_value(rest):
            continue
        labels = _re.sub(r"(^|,)instance=", r"\1exported_instance=",
                         labels)
        inner = f"{labels},{extra}" if labels else extra
        out.append(f"{name}{{{inner}}} {rest}")
    return "\n".join(out) + ("\n" if out else "")


class MetricsRegistry:
    def __init__(self) -> None:
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()   # lock-order: 814

    def counter(self, name: str, help_: str = "",
                labelnames: Iterable[str] = ()) -> Counter:
        return self._get_or_create(
            name, lambda: Counter(name, help_, labelnames), Counter,
            labelnames)

    def gauge(self, name: str, help_: str = "",
              labelnames: Iterable[str] = ()) -> Gauge:
        return self._get_or_create(
            name, lambda: Gauge(name, help_, labelnames), Gauge, labelnames)

    def histogram(self, name: str, help_: str = "", buckets=_DEFAULT_BUCKETS,
                  labelnames: Iterable[str] = ()) -> Histogram:
        return self._get_or_create(
            name, lambda: Histogram(name, help_, buckets, labelnames),
            Histogram, labelnames)

    def _get_or_create(self, name, factory, cls, labelnames=()):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = factory()
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(f"metric {name} already registered as {type(m).__name__}")
            elif m.labelnames != tuple(labelnames):
                raise TypeError(
                    f"metric {name} already registered with labels "
                    f"{m.labelnames}, not {tuple(labelnames)}")
            return m

    def render_prometheus(self) -> str:
        parts = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            if m.help:
                parts.append(f"# HELP {m.name} {m.help}\n")
            parts.append(f"# TYPE {m.name} {m.kind}\n")
            parts.append(m.render())
        return "".join(parts)


# Global registry + the reference's instruments (`metrics.h:108-111`).
# TTFT/ITL carry {instance, policy} so tail latency can be attributed to a
# routing decision; the frontend counter carries the API kind.
REGISTRY = MetricsRegistry()
SERVER_REQUEST_IN_TOTAL = REGISTRY.counter(
    "server_request_in_total", "Total requests accepted by the HTTP frontend",
    labelnames=("kind",))
TTFT_MS = REGISTRY.histogram(
    "time_to_first_token_latency_milliseconds", "TTFT per request (ms)",
    labelnames=("instance", "policy"))
ITL_MS = REGISTRY.histogram(
    "inter_token_latency_milliseconds", "Inter-token latency (ms)",
    labelnames=("instance", "policy"))

# Per-instance live-load gauges (service-side view of the fleet; phase is
# prefill|decode) + engine-reported queue depth from heartbeats.
INSTANCE_INFLIGHT_REQUESTS = REGISTRY.gauge(
    "instance_inflight_requests",
    "In-flight requests the scheduler has accounted to an instance",
    labelnames=("instance", "phase"))
INSTANCE_QUEUE_DEPTH = REGISTRY.gauge(
    "instance_queue_depth",
    "Engine-reported waiting queue depth (from heartbeats)",
    labelnames=("instance",))

# Failure-handling observability (beyond the reference, which exposes no
# failure-path instruments at all): transparent-failover outcomes, channel
# retry pressure, and fleet eviction churn.
FAILOVER_ATTEMPTS_TOTAL = REGISTRY.counter(
    "failover_attempts_total",
    "Re-dispatch attempts for requests on failed instances "
    "(instance = the failed one)",
    labelnames=("instance",))
FAILOVER_SUCCESS_TOTAL = REGISTRY.counter(
    "failover_success_total",
    "Requests successfully re-dispatched after an instance failure "
    "(instance = the surviving target)",
    labelnames=("instance",))
RPC_RETRIES_TOTAL = REGISTRY.counter(
    "rpc_retries_total", "Engine-channel RPC attempts beyond the first",
    labelnames=("instance",))
INSTANCE_EVICTIONS_TOTAL = REGISTRY.counter(
    "instance_evictions_total", "Instances removed from the fleet",
    labelnames=("instance",))
# Successor of requests_cancelled_on_failure_total (which only counted
# the failover-surfaced subset): every service-side cancellation, by
# cause. Bounded label set (the four causes below) — no eviction needed,
# unlike the per-instance series above.
REQUESTS_CANCELLED_TOTAL = REGISTRY.counter(
    "requests_cancelled_total",
    "Requests cancelled by the service, by cause "
    "(deadline = per-request deadline / GC timeout expiry, disconnect = "
    "client went away, shed = admission control refused it, failover = "
    "instance failure with no replay path or budget exhausted)",
    labelnames=("reason",))

# Fleet observability plane (docs/observability.md): locally-exported
# control-plane freshness gauges (previously visible only as
# /admin/hotpath JSON), the SLO burn-rate surface (common/slo.py), and
# the anomaly flight recorder's capture counter. The freshness gauges
# are refreshed at scrape time by the /metrics handler — no background
# thread.
ROUTING_SNAPSHOT_AGE_SECONDS = REGISTRY.gauge(
    "routing_snapshot_age_seconds",
    "Age of the published RCU routing snapshot (how stale this "
    "frontend's lock-free fleet view is)")
LOADINFO_MAX_AGE_SECONDS = REGISTRY.gauge(
    "loadinfo_max_age_seconds",
    "Age of the stalest per-instance load-info entry (-1 = never "
    "updated)")
LOADINFO_STALE_INSTANCES = REGISTRY.gauge(
    "loadinfo_stale_instances",
    "Instances whose load telemetry is older than loadinfo_stale_after_s "
    "(relative staleness: 0 when all entries are equally stale)")
KVCACHE_FRAME_LOG_SEQ = REGISTRY.gauge(
    "kvcache_frame_log_seq",
    "Next coordination KV-index frame-log sequence number (replicas "
    "lagging this have not applied the newest frames)")
PLANNER_SCALE_HINT = REGISTRY.gauge(
    "planner_scale_hint",
    "Latest planner scale decision (positive = add instances, negative "
    "= remove; hint for an external autoscaler)")
# Closed-loop autoscaler (autoscaler/, docs/autoscaling.md): enacted
# action counts by kind, the controller's live fleet view by role, and
# the age of the newest decision (a stuck control loop shows up here
# before it shows up as an unserved burst). fleet_size and the decision
# age are refreshed at tick time and at scrape time.
HOTPATH_CPU_SECONDS = REGISTRY.counter(
    "hotpath_cpu_seconds_total",
    "Master hot-loop CPU seconds by coarse loop (ingest = heartbeat/"
    "telemetry-frame ingest, route = schedule, stream = generation-delta "
    "ingest) — the per-master scaling-evidence series; frame-level "
    "breakdown lives at /admin/profile",
    labelnames=("loop",))
NATIVE_PATH_ACTIVE = REGISTRY.gauge(
    "native_path_active",
    "1 when the libhotcore.so native fast path serves this component "
    "(wire = LOADFRAME/telemetry msgpack, sse = delta-frame assembly, "
    "rendezvous = ownership hashing, tokenizer = byte-id encode); 0 = "
    "pure-Python fallback — a degraded process in a fleet scrape",
    labelnames=("component",))
AUTOSCALER_ACTIONS_TOTAL = REGISTRY.counter(
    "autoscaler_actions_total",
    "Actions enacted by the autoscaler controller, by kind "
    "(scale_out|scale_in|drain|flip|hold)",
    labelnames=("action",))
FLEET_SIZE = REGISTRY.gauge(
    "fleet_size",
    "Schedulable instances per role as seen by this frontend's routing "
    "snapshot (draining/suspect excluded; role=draining counts retiring "
    "instances)",
    labelnames=("role",))
AUTOSCALER_LAST_DECISION_AGE_SECONDS = REGISTRY.gauge(
    "autoscaler_last_decision_age_seconds",
    "Seconds since the autoscaler controller last completed a decision "
    "tick (-1 = never ticked / disabled)")
# Overload-hardening plane (overload/, docs/robustness.md): admission
# gate depth, brownout state, retry-budget level (scrape-time refreshed
# by the /metrics handler) and per-instance breaker state (written on
# reconcile transitions; series evicted with the instance).
ADMISSION_PENDING_REQUESTS = REGISTRY.gauge(
    "admission_pending_requests",
    "In-flight requests admitted through the overload-admission gate")
BROWNOUT_ACTIVE = REGISTRY.gauge(
    "brownout_active",
    "1 while the frontend is in brownout (SLO burn breaching on both "
    "windows: batch max_tokens clamped, optional work shed)")
RETRY_BUDGET_TOKENS = REGISTRY.gauge(
    "retry_budget_tokens",
    "Remaining global retry-budget tokens (failover + relay recovery "
    "spend from this bucket; empty = retries fail fast)")
CIRCUIT_BREAKER_OPEN = REGISTRY.gauge(
    "circuit_breaker_open",
    "1 while an instance's engine channel is OPEN/HALF_OPEN (excluded "
    "from routing like SUSPECT until a half-open probe closes it)",
    labelnames=("instance",))
SLO_BURN_RATE = REGISTRY.gauge(
    "slo_burn_rate",
    "Error-budget burn rate per objective and rolling window "
    "(1.0 = budget-neutral pace; see /admin/slo)",
    labelnames=("objective", "window"))
FLIGHT_RECORDS_TOTAL = REGISTRY.counter(
    "flight_records_total",
    "Anomaly bundles captured by the flight recorder",
    labelnames=("kind",))

# Engine-agent-side labeled series (the agent's /metrics appends the
# registry render to its hand-rolled engine_* text). Both are evicted
# when their label subject goes away — ENGINE_PEER_LINKED on PD unlink,
# ENGINE_HEARTBEATS_TOTAL when the master changes — mirroring the
# master's evicted-instance series eviction (instance_mgr), so a
# long-lived engine doesn't grow /metrics without bound.
ENGINE_PEER_LINKED = REGISTRY.gauge(
    "engine_peer_linked",
    "PD peers currently linked to this engine agent (1 per live link)",
    labelnames=("peer",))
ENGINE_HEARTBEATS_TOTAL = REGISTRY.counter(
    "engine_heartbeats_total",
    "Heartbeats this agent pushed, by destination master",
    labelnames=("master",))

# Multi-master service plane (multimaster/): ownership handoffs between
# active frontends and owner-death recoveries. `owner` is the TARGET
# master of the forward (small cardinality: one series per replica).
HANDOFF_FORWARDED_TOTAL = REGISTRY.counter(
    "handoff_forwarded_total",
    "Requests relayed to their owning master by an accepting frontend",
    labelnames=("owner",))
HANDOFF_SERVED_TOTAL = REGISTRY.counter(
    "handoff_served_total",
    "Foreign-accepted requests served by this master as owner")
HANDOFF_RECOVERIES_TOTAL = REGISTRY.counter(
    "handoff_recoveries_total",
    "Mid-flight re-ownerships after an owning master died "
    "(owner = the rendezvous successor)",
    labelnames=("owner",))
HANDOFF_JOURNAL_REPLAYS_TOTAL = REGISTRY.counter(
    "handoff_journal_replays_total",
    "Relayed-stream reconnects served from the owner's seq-numbered "
    "delta journal (exact replay — no pipeline re-run, no splice risk)")

# Sharded telemetry-ingest plane (ISSUE 15): heartbeat ingest by shard
# verdict, coalesced load-frame publication, and the master->master
# generation-delta relay behind the multiplexed engine session.
HEARTBEATS_INGESTED_TOTAL = REGISTRY.counter(
    "heartbeats_ingested_total",
    "Heartbeats ingested by this frontend, by telemetry-shard verdict "
    "(owned = this master owns the instance's ingest, foreign = "
    "membership race / legacy engine still funneling to the elected "
    "master)",
    labelnames=("shard",))
LOADFRAMES_PUBLISHED_TOTAL = REGISTRY.counter(
    "loadframes_published_total",
    "Coalesced load/lease frames this master published for its "
    "telemetry shard")
LOADFRAMES_APPLIED_TOTAL = REGISTRY.counter(
    "loadframes_applied_total",
    "Peer owners' load/lease frames mirrored into this frontend's "
    "lock-free load-info view")
TELEMETRY_GENS_RELAYED_TOTAL = REGISTRY.counter(
    "telemetry_gens_relayed_total",
    "Generation-delta batches relayed master->master for engines whose "
    "multiplexed telemetry session lands here but whose request owner "
    "is another frontend",
    labelnames=("dest",))
LOADINFO_AGE_SECONDS = REGISTRY.gauge(
    "loadinfo_age_seconds",
    "Per-instance load-info snapshot age (scrape-time refreshed; -1 = "
    "never updated) — the staleness signal SLO/CAR scoring discounts by, "
    "now observable instead of inferred",
    labelnames=("instance",))

# Coordination-plane static stability (ISSUE 16): degraded-mode serving
# when the coordination plane is unreachable — plane health, outage
# accounting, reconnect churn, and the held-actions backlog.
COORDINATION_CONNECTED = REGISTRY.gauge(
    "coordination_connected",
    "1 while the coordination plane answers liveness probes, 0 while "
    "the health monitor classifies it DEGRADED/RECOVERING")
COORDINATION_DEGRADED_SECONDS_TOTAL = REGISTRY.counter(
    "coordination_degraded_seconds_total",
    "Cumulative seconds this frontend spent serving in degraded mode "
    "(coordination plane unreachable; census frozen, mastership sticky)")
COORDINATION_RECONNECTS_TOTAL = REGISTRY.counter(
    "coordination_reconnects_total",
    "Successful coordination-client reconnects (each one re-auths, "
    "re-subscribes watches, and re-establishes leased keys)")
COORDINATION_HELD_ACTIONS = REGISTRY.gauge(
    "coordination_held_actions",
    "Depth of the held-actions log: ownership-changing actions "
    "(evictions, drains, flips, frame publishes, autoscaler enactment) "
    "suspended while the coordination plane is degraded")
COORDINATION_FROZEN_EVENTS_TOTAL = REGISTRY.counter(
    "coordination_frozen_events_total",
    "Census events ignored under the degraded-mode freeze (lease-lapse "
    "verdicts and missed-lease sweeps suppressed while the plane is "
    "down)",
    labelnames=("kind",))
