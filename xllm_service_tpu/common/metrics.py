"""Service metrics: counters, gauges, histograms with Prometheus text export.

Parity: reference bvar macros (`common/metrics.h:50-104`) and the three
defined instruments (`metrics.h:108-111`): `server_request_in_total`,
`time_to_first_token_latency_milliseconds`,
`inter_token_latency_milliseconds`. The reference leaves `/metrics` empty
(`http_service/service.cpp:526-532`); we implement it properly
(SURVEY.md §5.5 "New framework: same shape, Prometheus-format /metrics done
properly").
"""

from __future__ import annotations

import threading
from bisect import bisect_right
from typing import Iterable


class _Metric:
    def __init__(self, name: str, help_: str):
        self.name = name
        self.help = help_


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name: str, help_: str = ""):
        super().__init__(name, help_)
        self._v = 0.0
        self._lock = threading.Lock()   # lock-order: 810

    def inc(self, v: float = 1.0) -> None:
        with self._lock:
            self._v += v

    def value(self) -> float:
        return self._v

    def render(self) -> str:
        return f"{self.name} {self._v}\n"


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name: str, help_: str = ""):
        super().__init__(name, help_)
        self._v = 0.0

    def set(self, v: float) -> None:
        self._v = float(v)

    def value(self) -> float:
        return self._v

    def render(self) -> str:
        return f"{self.name} {self._v}\n"


_DEFAULT_BUCKETS = (1, 2, 5, 10, 20, 50, 100, 200, 500, 1000, 2000, 5000, 10000, 30000)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, help_: str = "", buckets: Iterable[float] = _DEFAULT_BUCKETS):
        super().__init__(name, help_)
        self.buckets = sorted(buckets)
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._n = 0
        self._lock = threading.Lock()   # lock-order: 812

    def observe(self, v: float) -> None:
        with self._lock:
            self._counts[bisect_right(self.buckets, v)] += 1
            self._sum += v
            self._n += 1

    def count(self) -> int:
        return self._n

    def mean(self) -> float:
        return self._sum / self._n if self._n else 0.0

    def render(self) -> str:
        out = []
        cum = 0
        with self._lock:
            for b, c in zip(self.buckets, self._counts):
                cum += c
                out.append(f'{self.name}_bucket{{le="{b}"}} {cum}\n')
            cum += self._counts[-1]
            out.append(f'{self.name}_bucket{{le="+Inf"}} {cum}\n')
            out.append(f"{self.name}_sum {self._sum}\n")
            out.append(f"{self.name}_count {self._n}\n")
        return "".join(out)


class MetricsRegistry:
    def __init__(self) -> None:
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()   # lock-order: 814

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get_or_create(name, lambda: Counter(name, help_), Counter)

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get_or_create(name, lambda: Gauge(name, help_), Gauge)

    def histogram(self, name: str, help_: str = "", buckets=_DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(name, lambda: Histogram(name, help_, buckets), Histogram)

    def _get_or_create(self, name, factory, cls):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = factory()
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(f"metric {name} already registered as {type(m).__name__}")
            return m

    def render_prometheus(self) -> str:
        parts = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            if m.help:
                parts.append(f"# HELP {m.name} {m.help}\n")
            parts.append(f"# TYPE {m.name} {m.kind}\n")
            parts.append(m.render())
        return "".join(parts)


# Global registry + the reference's instruments (`metrics.h:108-111`).
REGISTRY = MetricsRegistry()
SERVER_REQUEST_IN_TOTAL = REGISTRY.counter(
    "server_request_in_total", "Total requests accepted by the HTTP frontend")
TTFT_MS = REGISTRY.histogram(
    "time_to_first_token_latency_milliseconds", "TTFT per request (ms)")
ITL_MS = REGISTRY.histogram(
    "inter_token_latency_milliseconds", "Inter-token latency (ms)")

# Failure-handling observability (beyond the reference, which exposes no
# failure-path instruments at all): transparent-failover outcomes, channel
# retry pressure, and fleet eviction churn.
FAILOVER_ATTEMPTS_TOTAL = REGISTRY.counter(
    "failover_attempts_total",
    "Re-dispatch attempts for requests on failed instances")
FAILOVER_SUCCESS_TOTAL = REGISTRY.counter(
    "failover_success_total",
    "Requests successfully re-dispatched after an instance failure")
RPC_RETRIES_TOTAL = REGISTRY.counter(
    "rpc_retries_total", "Engine-channel RPC attempts beyond the first")
INSTANCE_EVICTIONS_TOTAL = REGISTRY.counter(
    "instance_evictions_total", "Instances removed from the fleet")
REQUESTS_CANCELLED_ON_FAILURE_TOTAL = REGISTRY.counter(
    "requests_cancelled_on_failure_total",
    "Requests surfaced as errors after instance failure "
    "(failover disabled, budget exhausted, or no payload to replay)")
