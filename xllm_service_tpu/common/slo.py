"""SLO burn-rate monitor: multi-window error-budget tracking over live
telemetry.

The ROADMAP's closed-loop autoscaling item ("SLO policy on live
telemetry") needs a machine-readable answer to "are we meeting our
objectives, and how fast are we spending the budget?" — not a raw
latency histogram. This module implements the Google-SRE multi-window
multi-burn-rate construction:

- An **objective** turns each observation into good/bad: a TTFT or TPOT
  sample is *bad* when it exceeds its target (``slo_ttft_ms`` /
  ``slo_tpot_ms``); a request is *bad* when it surfaces an error. The
  **error budget** (``slo_error_budget``) is the allowed bad fraction.
- The **burn rate** over a window is ``bad_fraction / budget`` — 1.0
  means spending exactly the sustainable pace, 14.4 means the monthly
  budget would be gone in ~2 days.
- Two rolling windows (**fast** ~5 min, **slow** ~1 h) are tracked per
  objective; an objective is *breaching* when BOTH are at or above
  ``slo_burn_alert`` — the fast window gives detection latency, the slow
  window keeps a transient spike from paging anyone.

Observations aggregate into per-second buckets (bounded memory at any
QPS); reads walk only the buckets inside the window. The scored report
is served at ``GET /admin/slo`` and exported as the
``slo_burn_rate{objective,window}`` gauges, which is exactly the input
surface ``scheduler/planner.py`` and ``policies/slo_aware.py`` grow into
next.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Optional

from ..devtools import ownership as _ownership
from ..devtools.locks import make_lock

#: Objective keys (stable API: gauge label values and report keys).
OBJECTIVES = ("ttft", "tpot", "error_rate")


class WindowCounts:
    """Rolling good/bad counts bucketed per second (one deque of
    ``[sec, good, bad]`` triples; writers append/merge at the tail,
    readers prune the head lazily — bounded at any rate with or without
    a reader). Shared helper: the SLO objectives' windows here and the
    admission gate's shed-rate window (overload/admission.py) both ride
    it. NOT internally locked — the owner serializes access."""

    def __init__(self, window_s: float):
        self.window_s = max(1.0, float(window_s))
        self._buckets: deque[list] = deque()

    def record(self, bad: bool, now: Optional[float] = None) -> None:
        sec = int(now if now is not None else time.time())
        if self._buckets and self._buckets[-1][0] == sec:
            b = self._buckets[-1]
        else:
            b = [sec, 0, 0]
            self._buckets.append(b)
            # Prune on the write path too: a process that records but is
            # never scraped must still hold only one window of buckets
            # (reads prune as well — this keeps the 'bounded memory at
            # any QPS' claim true without any reader).
            self._prune(sec)
        b[2 if bad else 1] += 1

    def _prune(self, now: float) -> None:
        horizon = now - self.window_s
        while self._buckets and self._buckets[0][0] < horizon:
            self._buckets.popleft()

    def counts(self, now: Optional[float] = None) -> tuple[int, int]:
        """(good, bad) over the window."""
        now = now if now is not None else time.time()
        self._prune(now)
        good = bad = 0
        for _, g, b in self._buckets:
            good += g
            bad += b
        return good, bad


class _Objective:
    def __init__(self, name: str, fast_s: float, slow_s: float,
                 budget: float, target: Optional[float] = None):
        self.name = name
        self.target = target          # ms threshold; None = outcome-based
        self.budget = max(1e-6, float(budget))
        self.fast = WindowCounts(fast_s)
        self.slow = WindowCounts(slow_s)
        # Trace exemplars: the worst observation per second bucket
        # ([sec, value, trace_id], pruned to the slow horizon) — a burn
        # spike in /admin/slo links straight to a federated trace and
        # its critical-path breakdown instead of a bare number.
        self.exemplars: deque[list] = deque()

    def record(self, bad: bool, now: Optional[float] = None,
               value: Optional[float] = None, trace_id: str = "") -> None:
        self.fast.record(bad, now)
        self.slow.record(bad, now)
        if not trace_id or value is None:
            return
        sec = int(now if now is not None else time.time())
        if self.exemplars and self.exemplars[-1][0] == sec:
            b = self.exemplars[-1]
            if value > b[1]:
                b[1], b[2] = value, trace_id
        else:
            self.exemplars.append([sec, value, trace_id])
            horizon = sec - self.slow.window_s
            while self.exemplars and self.exemplars[0][0] < horizon:
                self.exemplars.popleft()

    def worst_exemplar(self, w: WindowCounts,
                       now: Optional[float] = None) -> Optional[dict]:
        now = now if now is not None else time.time()
        horizon = now - w.window_s
        best = None
        for sec, value, tid in self.exemplars:
            if sec >= horizon and (best is None or value > best[1]):
                best = (sec, value, tid)
        if best is None:
            return None
        return {"trace_id": best[2], "value": round(best[1], 3),
                "age_s": round(now - best[0], 1)}

    def window_report(self, w: WindowCounts,
                      now: Optional[float] = None) -> dict[str, Any]:
        good, bad = w.counts(now)
        n = good + bad
        frac = (bad / n) if n else 0.0
        return {"window_s": w.window_s, "n": n, "bad": bad,
                "bad_fraction": round(frac, 6),
                "burn_rate": round(frac / self.budget, 3),
                "exemplar": self.worst_exemplar(w, now)}

    def report(self, alert: float,
               now: Optional[float] = None) -> dict[str, Any]:
        fast = self.window_report(self.fast, now)
        slow = self.window_report(self.slow, now)
        return {
            "objective": self.name,
            "target_ms": self.target,
            "error_budget": self.budget,
            "fast": fast,
            "slow": slow,
            # Multi-window rule: both windows must burn hot — the fast
            # one for detection latency, the slow one so a blip that
            # already ended doesn't keep alerting.
            "breaching": (fast["burn_rate"] >= alert
                          and slow["burn_rate"] >= alert),
        }


@_ownership.verify_state
class SloMonitor:
    """Process-global burn-rate tracker over the three serving
    objectives. Writers (the scheduler's token/exit paths) hold one leaf
    lock for a deque append; the report walks bounded bucket lists."""

    def __init__(self) -> None:
        self._lock = make_lock("slo.monitor", order=816)  # lock-order: 816
        self.alert = 14.4
        self._configure_locked(1000.0, 50.0, 0.01, 300.0, 3600.0)

    def _configure_locked(self, ttft_ms: float, tpot_ms: float,
                          budget: float, fast_s: float,
                          slow_s: float) -> None:
        self.ttft_target_ms = float(ttft_ms)
        self.tpot_target_ms = float(tpot_ms)
        self._objectives = {
            "ttft": _Objective("ttft", fast_s, slow_s, budget, ttft_ms),
            "tpot": _Objective("tpot", fast_s, slow_s, budget, tpot_ms),
            "error_rate": _Objective("error_rate", fast_s, slow_s, budget),
        }

    def configure(self, ttft_ms: float, tpot_ms: float, budget: float,
                  fast_s: float, slow_s: float,
                  alert: Optional[float] = None) -> None:
        """(Re)configure objectives — resets the windows."""
        with self._lock:
            if alert is not None:
                self.alert = float(alert)
            self._configure_locked(ttft_ms, tpot_ms, budget, fast_s, slow_s)

    # ----------------------------------------------------------- recording
    def record_ttft(self, ms: float, now: Optional[float] = None,
                    trace_id: str = "") -> None:
        with self._lock:
            self._objectives["ttft"].record(
                ms > self.ttft_target_ms, now, value=ms, trace_id=trace_id)

    def record_tpot(self, ms: float, now: Optional[float] = None,
                    trace_id: str = "") -> None:
        with self._lock:
            self._objectives["tpot"].record(
                ms > self.tpot_target_ms, now, value=ms, trace_id=trace_id)

    def record_request(self, ok: bool, now: Optional[float] = None,
                       trace_id: str = "") -> None:
        with self._lock:
            self._objectives["error_rate"].record(
                not ok, now, value=None if ok else 1.0,
                trace_id="" if ok else trace_id)

    def ttft_breached(self, ms: float) -> bool:
        """Per-request breach check (flight recorder / tail-sampling keep
        decision) — no budget math, just the target."""
        return ms > self.ttft_target_ms

    # ------------------------------------------------------------- reading
    def report(self, now: Optional[float] = None) -> dict[str, Any]:
        with self._lock:
            objectives = {name: obj.report(self.alert, now)
                          for name, obj in self._objectives.items()}
        worst = max((o["fast"]["burn_rate"] for o in objectives.values()),
                    default=0.0)
        return {
            "alert_burn_rate": self.alert,
            "objectives": objectives,
            "worst_fast_burn_rate": worst,
            "breaching": sorted(name for name, o in objectives.items()
                                if o["breaching"]),
        }

    def export_gauges(self, now: Optional[float] = None) -> dict[str, Any]:
        """Refresh the ``slo_burn_rate{objective,window}`` gauges from the
        current windows and return the report (callers: the /metrics and
        /admin/slo handlers — scrape-time refresh, no background
        thread)."""
        from .metrics import SLO_BURN_RATE

        report = self.report(now)
        for name, obj in report["objectives"].items():
            for window in ("fast", "slow"):
                SLO_BURN_RATE.labels(objective=name, window=window).set(
                    obj[window]["burn_rate"])
        return report


#: Process-global monitor; the HTTP service configures it from options.
SLO_MONITOR = SloMonitor()
