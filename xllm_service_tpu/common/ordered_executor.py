"""Per-key ordered execution lanes.

Parity: the reference pins each request's output callbacks to one of 128
single-thread pools so token deltas for a request are delivered in order
while different requests proceed concurrently (`scheduler.h:127-133`,
`scheduler.cpp:349-356,542-556`). Same design: N single-worker lanes; a
request is pinned to lane ``hash(service_request_id) % N`` at registration
and unpinned at finish.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Optional


class _Lane(threading.Thread):
    def __init__(self, idx: int):
        super().__init__(name=f"output-lane-{idx}", daemon=True)
        self.q: "queue.Queue[Optional[Callable[[], None]]]" = queue.Queue()
        self.start()

    def run(self) -> None:
        while True:
            task = self.q.get()
            if task is None:
                return
            try:
                task()
            except Exception:  # noqa: BLE001 — a bad callback must not kill the lane
                import logging

                logging.getLogger(__name__).exception("output lane task failed")


class OrderedExecutor:
    """N single-worker lanes; tasks submitted with the same key run in FIFO
    order on the same thread."""

    def __init__(self, num_lanes: int = 16):
        if num_lanes <= 0:
            raise ValueError("num_lanes must be positive")
        self._lanes = [_Lane(i) for i in range(num_lanes)]

    @property
    def num_lanes(self) -> int:
        return len(self._lanes)

    def lane_for(self, key: str) -> int:
        return hash(key) % len(self._lanes)

    def submit(self, key: str, task: Callable[[], None]) -> None:
        self.submit_to_lane(self.lane_for(key), task)

    def submit_to_lane(self, lane_idx: int, task: Callable[[], None]) -> None:
        self._lanes[lane_idx].q.put(task)

    def shutdown(self) -> None:
        for lane in self._lanes:
            lane.q.put(None)
        for lane in self._lanes:
            lane.join(timeout=5)

    def drain(self, timeout: float = 5.0) -> None:
        """Block until all currently queued tasks have run (test helper)."""
        import time

        done = threading.Barrier(len(self._lanes) + 1)

        def _mark():
            try:
                done.wait(timeout)
            except threading.BrokenBarrierError:
                pass

        for lane in self._lanes:
            lane.q.put(_mark)
        try:
            done.wait(timeout)
        except threading.BrokenBarrierError:
            pass
