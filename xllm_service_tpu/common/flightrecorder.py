"""Anomaly flight recorder: bounded post-mortem bundles for requests
that went wrong.

When a request breaches its SLO, fails over, errors out, or a KV stream
falls back to the inline path, the operator's first questions are always
the same: what did this request's trace look like, what were the hot-path
stages doing, and what state was the fleet in *at that moment*? By the
time a human queries `/admin/trace`, the evidence has often aged out of
the rings. The flight recorder snapshots it at anomaly time:

- the request's assembled span list/tree (from the per-process
  ``SpanStore``, including tail-sampled pending spans — anomalies always
  record, see ``Tracer.keep_trace``),
- the always-on hot-path stage percentiles (``common/hotpath.py``),
- whatever context the hosting process registered (the master registers
  load-info ages + ownership stats; the engine agent registers its
  tier/transfer stats),

into a bounded ring served at ``GET /admin/flightrecorder/recent`` and
optionally appended to ``flightrecorder.jsonl`` (``flightrecorder_dir``
option) — chaos drills become self-documenting.

Recording runs on the caller's thread but never under a scheduler lock
(call sites sit on exit paths after locks release); the ring append is a
leaf-lock deque push, and the JSONL write is line-buffered append.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Optional

from ..devtools import lifecycle as _lifecycle
from ..devtools import ownership as _ownership
from ..devtools.locks import make_lock
from ..utils import get_logger

logger = get_logger(__name__)

#: Anomaly kinds (stable API: ring records, JSONL, and the
#: flight_records_total{kind} counter use these values).
KINDS = ("slo_breach", "failover", "error", "kv_stream_fallback",
         "handoff_recovery")


@_ownership.verify_state
class FlightRecorder:
    def __init__(self, capacity: int = 64):
        self._lock = make_lock("flightrecorder.ring", order=818)  # lock-order: 818
        self._ring: deque[dict[str, Any]] = deque(maxlen=max(1, capacity))
        self._path: Optional[str] = None
        self._file = None
        self._file_lock = threading.Lock()   # lock-order: 819
        # Context providers: name -> zero-arg callable returning a JSON-
        # able snapshot, captured into every bundle. Provider errors are
        # recorded in place of their value, never raised.
        self._context: dict[str, Callable[[], Any]] = {}

    def configure(self, capacity: Optional[int] = None,
                  directory: Any = "__unset__") -> None:
        with self._lock:
            if capacity is not None and capacity != self._ring.maxlen:
                self._ring = deque(self._ring, maxlen=max(1, capacity))
        if directory != "__unset__":
            with self._file_lock:
                if self._file is not None:
                    self._file.close()
                    self._file = None
                self._path = (os.path.join(directory, "flightrecorder.jsonl")
                              if directory else None)

    def add_context_provider(self, name: str,
                             fn: Callable[[], Any]) -> None:
        # Under the ring lock: providers register from owner startup
        # threads while record() snapshots the table on request-exit
        # threads — the unguarded dict write here was the first real
        # finding of the state-write ownership rule.
        with self._lock:
            if name in self._context:
                # Replacement: the old registration's obligation passes
                # to the new owner — release before re-acquiring so the
                # keyed balance stays exactly one.
                _lifecycle.note_release("flight-context", key=name)
            self._context[name] = fn
            _lifecycle.note_acquire("flight-context", key=name)

    def remove_context_provider(self, name: str,
                                fn: Optional[Callable[[], Any]] = None
                                ) -> None:
        """Deregister a provider at owner shutdown. With `fn`, removes
        only if the slot still holds that callable — a newer owner of the
        same name (tests build several masters per process) keeps its
        registration when an older one stops."""
        # == not `is`: bound methods are fresh objects per attribute
        # access but compare equal on (func, self).
        with self._lock:
            if fn is None or self._context.get(name) == fn:
                if name in self._context:
                    _lifecycle.note_release("flight-context", key=name)
                self._context.pop(name, None)

    # ------------------------------------------------------------ recording
    def record(self, kind: str, request_id: str = "", trace_id: str = "",
               detail: Optional[dict[str, Any]] = None) -> dict[str, Any]:
        """Capture one anomaly bundle. Never raises: a failed capture
        logs and records whatever it got."""
        from .hotpath import HOTPATH
        from .metrics import FLIGHT_RECORDS_TOTAL
        from .tracing import TRACER, span_tree

        bundle: dict[str, Any] = {
            "ts_ms": time.time() * 1000.0,
            "kind": kind,
            "request_id": request_id,
            "trace_id": trace_id,
            "detail": dict(detail or {}),
        }
        try:
            if not trace_id and request_id:
                trace_id = TRACER.store.trace_id_for_request(
                    request_id) or ""
                bundle["trace_id"] = trace_id
            if trace_id:
                spans = TRACER.store.trace(trace_id)
                bundle["num_spans"] = len(spans)
                bundle["trace"] = span_tree(spans)
            bundle["hotpath"] = HOTPATH.summary()
            with self._lock:
                providers = list(self._context.items())
            for name, fn in providers:
                try:
                    bundle[name] = fn()
                except Exception as e:  # noqa: BLE001 — a broken provider must not lose the bundle
                    bundle[name] = {"error": str(e)}
        except Exception:  # noqa: BLE001 — capture is best-effort by contract
            logger.exception("flight-recorder capture failed (%s)", kind)
        FLIGHT_RECORDS_TOTAL.labels(kind=kind).inc()
        with self._lock:
            self._ring.append(bundle)
        self._dump(bundle)
        return bundle

    def _dump(self, bundle: dict[str, Any]) -> None:
        if self._path is None:
            return
        try:
            # default=str: bundles embed arbitrary span attrs / provider
            # output (bytes, enums, numpy scalars) — a non-serializable
            # leaf must degrade to its repr, never break record()'s
            # never-raises contract on the request-exit path.
            line = json.dumps(bundle, default=str)
            with self._file_lock:
                if self._file is None:
                    os.makedirs(os.path.dirname(self._path), exist_ok=True)
                    self._file = open(self._path, "a", buffering=1)
                self._file.write(line + "\n")
        except (OSError, TypeError, ValueError):
            logger.exception("flight-recorder JSONL append failed")

    # -------------------------------------------------------------- reading
    def recent(self, limit: int = 20,
               kind: str = "") -> list[dict[str, Any]]:
        with self._lock:
            records = list(self._ring)
        if kind:
            records = [r for r in records if r.get("kind") == kind]
        return records[-max(0, int(limit)):][::-1]

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def close(self) -> None:
        with self._file_lock:
            if self._file is not None:
                self._file.close()
                self._file = None


#: Process-global recorder (master and engine agent each configure their
#: own process's instance and register their context providers).
RECORDER = FlightRecorder()


async def handle_flightrecorder_recent(request):
    """Shared aiohttp handler: ``GET /admin/flightrecorder/recent
    [?limit=N&kind=...]`` — newest first."""
    from aiohttp import web

    try:
        limit = int(request.query.get("limit", 20))
    except ValueError:
        return web.json_response({"error": "limit must be an integer"},
                                 status=400)
    records = RECORDER.recent(limit=limit,
                              kind=request.query.get("kind", ""))
    return web.json_response({"num_records": len(records),
                              "records": records})
