"""L1 common infrastructure: domain types, config, hashing, metrics.

Reference parity: `xllm_service/common/` (SURVEY.md §2.9).
"""
