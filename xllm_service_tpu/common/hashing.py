"""Chained block hashing for global prefix-KV-cache identity.

Parity: reference `common/hash_util.{h,cpp}` — 16-byte keys produced by a
chained 128-bit hash over ``[prev_hash ‖ block_token_ids]`` per fixed-size
token block (`hash_util.cpp:18-50`, block_size=128 per
`global_gflags.cpp:114-116`). The reference uses XXH3-128; the exact function
is an implementation detail — what matters is that every party (engines,
schedulers, replicas) computes identical keys for identical token prefixes.

We use BLAKE2b-128 keyed with the previous block hash via Python's hashlib
(C-speed, battle-tested, dependency-free). An optional C extension
(`csrc/blockhash.c`, built as ``libblockhash.so`` and loaded via ctypes)
implements the same construction for the native orchestration components;
both produce identical digests (tests/test_common.py asserts equivalence).

The hot entry point is :func:`prefix_block_hashes`: the token list is
converted ONCE (one ``np.asarray`` + one ``tobytes``), and per-block work
is either a single zero-copy ``memoryview`` slice into a one-shot keyed
``blake2b`` call, or — when the extension is present — one FFI call that
runs the whole chain in C. :func:`extend_prefix_block_hashes` continues a
chain incrementally, so callers that memoize hashes (``Request``) pay only
for blocks appended since the last call.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
from pathlib import Path
from typing import Optional, Sequence

import numpy as np

# 128-token blocks, matching the reference default (`global_gflags.cpp:114`).
DEFAULT_BLOCK_SIZE = 128
HASH_NBYTES = 16
_SEED = b"xllm-service-tpu"


def _load_native():
    """Optional csrc/libblockhash.so (``make -C csrc libblockhash.so``).
    Returns (buffer_fn, list_fn) — either may be None; ``list_fn`` ingests
    a Python token sequence directly (the list→int32 conversion dominates
    the hashlib path, so it runs in C too, GIL held via PyDLL).
    ``XLLM_NO_NATIVE_HASH=1`` forces the pure-Python path (the equivalence
    tests use it)."""
    if os.environ.get("XLLM_NO_NATIVE_HASH", "") not in ("", "0"):
        return None, None
    so = Path(__file__).resolve().parents[2] / "csrc" / "libblockhash.so"
    try:
        lib = ctypes.CDLL(str(so))
    except OSError:
        return None, None
    try:
        buf_fn = lib.chained_block_hashes
    except AttributeError:
        return None, None
    buf_fn.argtypes = [ctypes.c_char_p, ctypes.c_size_t, ctypes.c_size_t,
                       ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p]
    buf_fn.restype = None
    list_fn = None
    try:
        # PyDLL: keeps the GIL held across the call — the entry point uses
        # CPython APIs to read the token sequence.
        list_fn = ctypes.PyDLL(str(so)).chained_block_hashes_list
        list_fn.argtypes = [ctypes.py_object, ctypes.c_ssize_t,
                            ctypes.py_object]
        list_fn.restype = ctypes.py_object
    except (OSError, AttributeError):
        list_fn = None
    return buf_fn, list_fn


_NATIVE, _NATIVE_LIST = _load_native()


def native_available() -> bool:
    return _NATIVE is not None


def hash_block(prev: bytes, token_ids: Sequence[int]) -> bytes:
    """Hash one token block chained onto ``prev`` (b"" for the first block)."""
    key = prev if prev else _SEED
    data = np.asarray(token_ids, dtype=np.int32).tobytes()
    return hashlib.blake2b(data, digest_size=HASH_NBYTES, key=key).digest()


def _chain(buf: bytes, n_blocks: int, block_bytes: int,
           seed: bytes) -> list[bytes]:
    """Chained keyed BLAKE2b-128 over ``n_blocks`` slices of ``buf``."""
    if _NATIVE is not None:
        out = ctypes.create_string_buffer(n_blocks * HASH_NBYTES)
        _NATIVE(buf, n_blocks, block_bytes, seed, len(seed), out)
        raw = out.raw
        return [raw[i * HASH_NBYTES:(i + 1) * HASH_NBYTES]
                for i in range(n_blocks)]
    blake2b = hashlib.blake2b
    mv = memoryview(buf)
    prev = seed
    hashes: list[bytes] = []
    for i in range(n_blocks):
        prev = blake2b(mv[i * block_bytes:(i + 1) * block_bytes],
                       digest_size=HASH_NBYTES, key=prev).digest()
        hashes.append(prev)
    return hashes


def _hash_tokens(token_seq: Sequence[int], block_size: int,
                 seed: bytes) -> list[bytes]:
    if _NATIVE_LIST is not None and not isinstance(token_seq, np.ndarray):
        # List fast path: the element-by-element int32 conversion runs in
        # C (it costs ~25x the hash chain itself when done via np.asarray).
        raw = _NATIVE_LIST(token_seq, block_size, seed)
        return [raw[i:i + HASH_NBYTES]
                for i in range(0, len(raw), HASH_NBYTES)]
    arr = np.asarray(token_seq, dtype=np.int32)
    n_blocks = len(arr) // block_size
    if n_blocks == 0:
        return []
    buf = arr[:n_blocks * block_size].tobytes()
    return _chain(buf, n_blocks, block_size * 4, seed)


def prefix_block_hashes(
    token_ids: Sequence[int], block_size: int = DEFAULT_BLOCK_SIZE
) -> list[bytes]:
    """Chained hashes for every *complete* block of ``token_ids``.

    Matches the reference's matching loop (`global_kvcache_mgr.cpp:85-94`):
    only full blocks participate; the trailing partial block is ignored.
    """
    if block_size <= 0:
        raise ValueError(f"block_size must be positive, got {block_size}")
    return _hash_tokens(token_ids, block_size, _SEED)


def extend_prefix_block_hashes(
    prev_hashes: Sequence[bytes], token_ids: Sequence[int],
    block_size: int = DEFAULT_BLOCK_SIZE,
) -> list[bytes]:
    """Continue a memoized chain: ``prev_hashes`` are the hashes of the
    first ``len(prev_hashes)`` blocks of ``token_ids`` (the caller
    guarantees that prefix is unchanged — true for append-only growth like
    failover prompt extension); only the blocks beyond them are hashed.
    """
    if block_size <= 0:
        raise ValueError(f"block_size must be positive, got {block_size}")
    done = len(prev_hashes)
    n_blocks = len(token_ids) // block_size
    if done >= n_blocks:
        return list(prev_hashes[:n_blocks])
    seed = bytes(prev_hashes[-1]) if done else _SEED
    tail = token_ids[done * block_size:n_blocks * block_size]
    return list(prev_hashes) + _hash_tokens(tail, block_size, seed)


def prefix_block_hash_hexes(
    token_ids: Sequence[int], block_size: int = DEFAULT_BLOCK_SIZE
) -> list[str]:
    return [h.hex() for h in prefix_block_hashes(token_ids, block_size)]


def to_hex(h: bytes) -> str:
    return h.hex()


def from_hex(s: str) -> bytes:
    return bytes.fromhex(s)


def as_key(h: "bytes | str") -> Optional[bytes]:
    """Normalize a wire-carried block key — raw 16 bytes (msgpack path) or
    a hex string (legacy JSON path) — to the canonical bytes form. Returns
    None for garbage (callers skip the key rather than poison the index).
    """
    if isinstance(h, bytes):
        return h if len(h) == HASH_NBYTES else None
    try:
        b = bytes.fromhex(h)
    except (ValueError, TypeError):
        return None
    return b if len(b) == HASH_NBYTES else None
