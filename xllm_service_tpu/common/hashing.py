"""Chained block hashing for global prefix-KV-cache identity.

Parity: reference `common/hash_util.{h,cpp}` — 16-byte keys produced by a
chained 128-bit hash over ``[prev_hash ‖ block_token_ids]`` per fixed-size
token block (`hash_util.cpp:18-50`, block_size=128 per
`global_gflags.cpp:114-116`). The reference uses XXH3-128; the exact function
is an implementation detail — what matters is that every party (engines,
schedulers, replicas) computes identical keys for identical token prefixes.

We use BLAKE2b-128 keyed with the previous block hash via Python's hashlib
(C-speed, battle-tested, dependency-free). An optional C extension
(`csrc/blockhash.c`) implements the same construction for the native
orchestration components; both produce identical digests.
"""

from __future__ import annotations

import hashlib
from typing import Iterable, Sequence

import numpy as np

# 128-token blocks, matching the reference default (`global_gflags.cpp:114`).
DEFAULT_BLOCK_SIZE = 128
HASH_NBYTES = 16
_SEED = b"xllm-service-tpu"


def hash_block(prev: bytes, token_ids: Sequence[int]) -> bytes:
    """Hash one token block chained onto ``prev`` (b"" for the first block)."""
    key = prev if prev else _SEED
    h = hashlib.blake2b(digest_size=HASH_NBYTES, key=key)
    h.update(np.asarray(token_ids, dtype=np.int32).tobytes())
    return h.digest()


def prefix_block_hashes(
    token_ids: Sequence[int], block_size: int = DEFAULT_BLOCK_SIZE
) -> list[bytes]:
    """Chained hashes for every *complete* block of ``token_ids``.

    Matches the reference's matching loop (`global_kvcache_mgr.cpp:85-94`):
    only full blocks participate; the trailing partial block is ignored.
    """
    if block_size <= 0:
        raise ValueError(f"block_size must be positive, got {block_size}")
    arr = np.asarray(token_ids, dtype=np.int32)
    n_blocks = len(arr) // block_size
    out: list[bytes] = []
    prev = b""
    for i in range(n_blocks):
        prev = hash_block(prev, arr[i * block_size : (i + 1) * block_size])
        out.append(prev)
    return out


def prefix_block_hash_hexes(
    token_ids: Sequence[int], block_size: int = DEFAULT_BLOCK_SIZE
) -> list[str]:
    return [h.hex() for h in prefix_block_hashes(token_ids, block_size)]


def to_hex(h: bytes) -> str:
    return h.hex()


def from_hex(s: str) -> bytes:
    return bytes.fromhex(s)
