"""Always-on per-stage latency recorder for the master hot path.

The span-tracing plane (common/tracing.py) attributes individual requests
but costs a ring insert per span and can be disabled; capacity planning
needs a cheap aggregate that is ALWAYS there. This recorder is two
``perf_counter`` reads and a bounded-deque append per stage (appends on
``collections.deque`` are atomic under the GIL — no lock on the write
path), served by ``GET /admin/hotpath`` on the master and read by
serve_bench / master_hotpath_bench for their per-stage tables.

Stages (the four legs of the client-observed master+wire TTFT span):

========== ==========================================================
stage      measures
========== ==========================================================
schedule   executor hop + Scheduler.schedule (template/tokenize/route/
           bind) — sub-attributed by tracing spans when enabled
enrich     dispatch payload build + wire serialization
forward    engine dispatch POST (accept round trip)
first_delta engine accept -> first Generations delta ingested
========== ==========================================================
"""

from __future__ import annotations

from collections import deque
from typing import Any

#: Stage names in hot-path order (the admin endpoint and the bench tables
#: render in this order).
STAGES = ("schedule", "enrich", "forward", "first_delta")

_WINDOW = 2048   # per-stage sample window (bounded memory, recent view)


class HotpathRecorder:
    """Bounded per-stage sample windows with percentile summaries."""

    def __init__(self, window: int = _WINDOW):
        self._samples: dict[str, deque] = {
            s: deque(maxlen=window) for s in STAGES}

    def record(self, stage: str, ms: float) -> None:
        q = self._samples.get(stage)
        if q is not None:
            q.append(ms)

    @staticmethod
    def _pct(xs: list, p: float) -> float:
        if not xs:
            return 0.0
        xs = sorted(xs)
        k = min(len(xs) - 1, int(round((p / 100) * (len(xs) - 1))))
        return xs[k]

    def summary(self) -> dict[str, Any]:
        out: dict[str, Any] = {}
        for stage in STAGES:
            xs = list(self._samples[stage])
            out[stage] = {
                "n": len(xs),
                "p50": round(self._pct(xs, 50), 3),
                "p90": round(self._pct(xs, 90), 3),
                "p99": round(self._pct(xs, 99), 3),
            }
        return out

    def clear(self) -> None:
        for q in self._samples.values():
            q.clear()


#: Process-global recorder (the master is one process; the engine agent
#: has its own ttft_spans surface).
HOTPATH = HotpathRecorder()


class CpuAttribution:
    """Per-category CPU-second accounting for the master process
    (ingest = heartbeat/telemetry-frame ingest, route = schedule
    [template/tokenize/route/bind], stream = generation-delta ingest).

    The bench divides these by the process's total /proc CPU to get the
    ingest/route/stream shares the ISSUE-15 acceptance keys on. Each
    measurement is two ``thread_time`` reads (CPU time of the CURRENT
    thread — correct on executor threads, immune to wall-clock blocking)
    and one float add; totals are plain floats mutated under the GIL —
    a torn read of a monotonically-growing total is off by at most one
    sample, which is noise at bench scale."""

    CATEGORIES = ("ingest", "route", "stream")

    def __init__(self):
        self._totals = {c: 0.0 for c in self.CATEGORIES}
        self._counts = {c: 0 for c in self.CATEGORIES}
        self._exported = {c: 0.0 for c in self.CATEGORIES}

    def measure(self, category: str):
        return _CpuSpan(self, category)

    def add(self, category: str, seconds: float) -> None:
        if category in self._totals:
            self._totals[category] += seconds
            self._counts[category] += 1

    def summary(self) -> dict[str, Any]:
        return {c: {"cpu_s": round(self._totals[c], 4),
                    "n": self._counts[c]}
                for c in self.CATEGORIES}

    def export_counters(self) -> None:
        """Publish the delta since the last export to the Prometheus
        ``hotpath_cpu_seconds_total{loop}`` counter — called from the
        master's scrape-time gauge refresh, so /metrics and
        /metrics/fleet carry the loop-level CPU series without a
        background thread."""
        from .metrics import HOTPATH_CPU_SECONDS

        for c in self.CATEGORIES:
            delta = self._totals[c] - self._exported[c]
            if delta > 0:
                HOTPATH_CPU_SECONDS.labels(loop=c).inc(delta)
                self._exported[c] += delta

    def clear(self) -> None:
        for c in self.CATEGORIES:
            self._totals[c] = 0.0
            self._counts[c] = 0
            self._exported[c] = 0.0


class _CpuSpan:
    __slots__ = ("_attr", "_cat", "_t0")

    def __init__(self, attr: CpuAttribution, category: str):
        self._attr = attr
        self._cat = category

    def __enter__(self):
        import time

        self._t0 = time.thread_time()
        return self

    def __exit__(self, *exc):
        import time

        self._attr.add(self._cat, time.thread_time() - self._t0)


#: Process-global CPU attribution (served by /admin/hotpath; read by
#: master_hotpath_bench's ingest-share report).
CPU_ATTR = CpuAttribution()
