"""Per-instance latency model for SLO-aware scheduling.

Parity: reference `common/time_predictor.{h,cpp}` — fitted at instance
registration from engine-profiled tables:

- TTFT: degree-2 polynomial in prompt length (reference fits a Vandermonde
  system with QR, `time_predictor.cpp:28-49`; numpy polyfit is the same
  least-squares problem).
- TPOT: linear in (batch_size, total_tokens) (`time_predictor.cpp:51-75`).
"""

from __future__ import annotations

from typing import Sequence

import numpy as np


class TimePredictor:
    def __init__(self) -> None:
        self._ttft_coef: np.ndarray | None = None    # [c0, c1, c2] for 1,x,x^2
        self._tpot_coef: np.ndarray | None = None    # [c0, c_batch, c_tokens]

    # ---- fitting -----------------------------------------------------------
    def fit_ttft(self, samples: Sequence[Sequence[float]]) -> bool:
        """samples: rows of [prompt_len, ttft_ms]; needs >= 3 points."""
        arr = np.asarray(samples, dtype=np.float64)
        if arr.ndim != 2 or arr.shape[0] < 3 or arr.shape[1] != 2:
            return False
        x, y = arr[:, 0], arr[:, 1]
        A = np.stack([np.ones_like(x), x, x * x], axis=1)
        self._ttft_coef, *_ = np.linalg.lstsq(A, y, rcond=None)
        return True

    def fit_tpot(self, samples: Sequence[Sequence[float]]) -> bool:
        """samples: rows of [batch_size, total_tokens, tpot_ms]; >= 3 points."""
        arr = np.asarray(samples, dtype=np.float64)
        if arr.ndim != 2 or arr.shape[0] < 3 or arr.shape[1] != 3:
            return False
        A = np.stack([np.ones(arr.shape[0]), arr[:, 0], arr[:, 1]], axis=1)
        self._tpot_coef, *_ = np.linalg.lstsq(A, arr[:, 2], rcond=None)
        return True

    # ---- prediction (reference `time_predictor.cpp:77-93`) -----------------
    @property
    def has_ttft(self) -> bool:
        return self._ttft_coef is not None

    @property
    def has_tpot(self) -> bool:
        return self._tpot_coef is not None

    def predict_ttft(self, prompt_len: int) -> float:
        if self._ttft_coef is None:
            return 0.0
        c = self._ttft_coef
        return float(max(0.0, c[0] + c[1] * prompt_len + c[2] * prompt_len * prompt_len))

    def predict_tpot(self, batch_size: int, total_tokens: int) -> float:
        if self._tpot_coef is None:
            return 0.0
        c = self._tpot_coef
        return float(max(0.0, c[0] + c[1] * batch_size + c[2] * total_tokens))
