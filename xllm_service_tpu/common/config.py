"""Service configuration.

Parity: the reference's three-tier config (SURVEY.md §5.6): gflags
(`common/global_gflags.cpp:20-149`) copied into a fluent `Options` object
(`common/options.h:25-92`) plus env vars. Here: one dataclass with every
reference knob (same defaults), constructible from argparse CLI flags and
env vars; live-reloadable SLO targets (the reference exposes target_ttft /
target_tpot via brpc flag reload, `global_gflags.cpp:122-132` — we expose
them via the admin HTTP endpoint).
"""

from __future__ import annotations

import argparse
import dataclasses
import os
from dataclasses import dataclass, field


@dataclass
class ServiceOptions:
    """All orchestration-plane knobs (reference defaults preserved)."""

    # --- serving endpoints (reference `global_gflags.cpp:25,38`) ---
    host: str = "0.0.0.0"
    http_port: int = 8888
    rpc_port: int = 8889
    num_http_threads: int = 32
    num_rpc_threads: int = 32
    max_concurrency: int = 0          # 0 = unlimited
    # --- model / tokenization ---
    tokenizer_path: str = ""
    model_id: str = ""
    # --- coordination (reference `etcd_addr/namespace`) ---
    coordination_addr: str = ""       # "" => in-process memory backend
    coordination_namespace: str = ""
    coordination_username: str = field(
        default_factory=lambda: os.environ.get("ETCD_USERNAME", ""))
    coordination_password: str = field(
        default_factory=lambda: os.environ.get("ETCD_PASSWORD", ""))
    # --- scheduling ---
    # CAR shipped default since the multi-master round: the PR-5 data
    # plane made its schedule path as cheap as RR, and the
    # heterogeneous-mix soak (docs/performance.md) showed it no worse
    # than RR on zero-overlap traffic and far better on cache-hot mixes.
    load_balance_policy: str = "CAR"  # RR | CAR | SLO_AWARE
    block_size: int = 128             # prefix-hash block (`global_gflags.cpp:114-116`)
    max_waiting_requests: int = 1024  # CAR normalization denominator
    # CAR tier weights: what one matched block is worth per residence tier
    # (HBM hits reuse directly; DRAM/SSD hits pay an onload first). Fed to
    # GlobalKVCacheMgr, which bakes them into the per-block score tuples.
    tier_weight_hbm: float = 1.0
    tier_weight_dram: float = 0.6
    tier_weight_ssd: float = 0.3
    # Master→coordination KV-index sync: delta frames per full-state
    # compaction (scheduler/global_kvcache_mgr.py). Lower = replicas
    # bootstrap faster; higher = less periodic full-upload work.
    kvcache_frame_compact_every: int = 64
    # SLO targets, live-reloadable (`global_gflags.cpp:122-132`).
    target_ttft_ms: float = 1000.0
    target_tpot_ms: float = 50.0
    # --- ICI-topology-aware placement (common/topology.py, docs/topology.md) ---
    # How much load-skew advantage (in CAR score units / normalized link
    # penalty) a cross-slice DCN partner must show before it beats a
    # same-slice ICI partner. 0 disables the plane entirely (flat
    # placement); the plane is also dormant whenever the fleet's
    # effective coordinates collapse into a single slice, so flat fleets
    # see zero routing change at any knob value.
    topology_tradeoff: float = 0.25
    # Scheduler-side modeled link budgets (bytes/s) for transfer_cost —
    # mirror the engine BandwidthAccountant budgets so the master's
    # predicted handoff time matches what the engines actually pace.
    # 0 = use the class-default bandwidths (account-only fleets).
    topology_ici_bytes_per_s: float = 0.0
    topology_dcn_bytes_per_s: float = 0.0
    # Modeled KV bytes per prompt token for instances that don't
    # advertise a KV layout (fake engines); real engines' advertised
    # num_layers/num_kv_heads/head_dim/kv_dtype win when present.
    topology_kv_bytes_per_token: int = 0
    # --- engine RPC channel (reference fixes 3 retries with no backoff,
    #     `instance_mgr.cpp:480-498`; here both are knobs and retries back
    #     off exponentially with jitter) ---
    rpc_timeout_s: float = 5.0
    rpc_retries: int = 3
    rpc_backoff_base_s: float = 0.05
    rpc_backoff_max_s: float = 1.0
    # --- transparent failover (beyond the reference's cancel-and-surface:
    #     in-flight requests on a dead instance are re-dispatched, decode
    #     resumed by prompt extension; 0 disables = reference behavior) ---
    failover_max_retries: int = 3
    failover_backoff_base_s: float = 0.05
    failover_backoff_max_s: float = 2.0
    # --- failure detection (`global_gflags.cpp:95-113`) ---
    heartbeat_interval_s: float = 3.0
    lease_ttl_s: float = 3.0
    health_probe_attempts: int = 2
    health_probe_timeout_s: float = 1.0
    heartbeat_silence_to_suspect_s: float = 3.0
    detect_disconnected_instance_interval_s: float = 15.0
    reconcile_interval_s: float = 1.0
    sync_interval_s: float = 3.0      # master upload loop cadence
    readiness_check_interval_s: float = 3.0
    # --- output parsing preferences (`global_gflags.cpp:134-142`) ---
    tool_call_parser: str = "auto"
    reasoning_parser: str = "auto"
    # --- tracing / debug ---
    enable_request_trace: bool = False
    trace_dir: str = "trace"
    # Hop-propagated span tracing (common/tracing.py): in-memory ring of
    # spans behind /admin/trace. Off = every span call is a no-op attribute
    # check (<2%, benchmarks/bench_tracing_overhead.py). Spans are also
    # mirrored to the RequestTracer JSONL when enable_request_trace is on.
    enable_tracing: bool = True
    trace_span_capacity: int = 2048
    # Head sampling with tail-based keep (common/tracing.py): the fraction
    # of traces recorded into the queryable ring. Sampled-out traces park
    # in a bounded pending buffer and are PROMOTED whenever the request
    # ends anomalously (failover, error, SLO breach) — so always-on
    # tracing stays viable at high QPS without losing the traces worth
    # debugging. 1.0 = record everything (default).
    trace_sample_rate: float = 1.0
    # --- fleet observability plane (docs/observability.md) ---
    # Per-peer deadline for /admin/trace?scope=fleet and /metrics/fleet
    # fan-out: a dead agent degrades the view (partial-result marker),
    # never the endpoint.
    fleet_peer_timeout_s: float = 2.0
    # Bounded fan-out concurrency for fleet scrapes/queries.
    fleet_scrape_concurrency: int = 8
    # /metrics/fleet TTL cache: scrape storms against the fleet endpoint
    # hit the cache, not every engine.
    metrics_fleet_cache_ttl_s: float = 2.0
    # SLO objectives for the burn-rate monitor (common/slo.py): a TTFT/
    # TPOT observation over its target — or a failed request — burns
    # error budget; budget is the allowed bad fraction. Burn rates are
    # tracked over a fast and a slow rolling window (Google-SRE
    # multi-window multi-burn-rate) and served at /admin/slo + /metrics.
    slo_ttft_ms: float = 1000.0
    slo_tpot_ms: float = 50.0
    slo_error_budget: float = 0.01
    slo_fast_window_s: float = 300.0
    slo_slow_window_s: float = 3600.0
    # Burn-rate alert threshold: an objective is "breaching" when BOTH
    # windows burn at or above this multiple of budget-neutral pace.
    slo_burn_alert: float = 14.4
    # Anomaly flight recorder (common/flightrecorder.py): bounded ring of
    # post-mortem bundles (trace tree + hotpath stages + load snapshot)
    # captured on SLO breach / failover / error / KV-stream fallback.
    flightrecorder_capacity: int = 64
    # Continuous-profiling plane (profiling/sampler.py): always-on
    # wall-clock sampling at profile_hz (0 disables; ~19 Hz default — a
    # non-round rate so the sampler never phase-locks with periodic
    # loops; overhead gate <=1% via benchmarks/bench_profile_overhead).
    # Folded stacks rotate on profile_window_s; per-role distinct-stack
    # tables and stack depth are bounded (overflow is charged to a
    # visible "(overflow)" bucket, never unbounded memory).
    profile_hz: float = 19.0
    profile_window_s: float = 30.0
    profile_max_stacks: int = 256
    profile_max_depth: int = 24
    # --- closed-loop fleet autoscaler (autoscaler/, docs/autoscaling.md) ---
    # Master-gated control loop turning SLO burn rates + planner pressure
    # into SCALE_OUT / SCALE_IN(drain) / FLIP actions through a pluggable
    # actuator. Default OFF for one release: with the controller off the
    # planner keeps today's hint-only behavior (scale_hint published to
    # XLLM:PLANNER:decision, flips enacted directly by the planner/SLO
    # policy) — turning it on funnels every actuation through the
    # controller, the single actuation path.
    autoscaler_enabled: bool = False
    # Actuator backend: "hint" publishes typed action records to a
    # coordination key (today's external-autoscaler contract); "local"
    # launches/stops engine agent processes on this box (drills, benches,
    # single-host deployments).
    autoscaler_actuator: str = "hint"
    # Fleet bounds the controller never crosses (draining instances count
    # toward the max until they deregister).
    autoscaler_min_instances: int = 1
    autoscaler_max_instances: int = 8
    # Hysteresis: consecutive breaching ticks before a SCALE_OUT /
    # consecutive idle ticks before a SCALE_IN (one tick per sync pass).
    autoscaler_breach_ticks: int = 2
    autoscaler_idle_ticks: int = 5
    # Growth step per SCALE_OUT as a fraction of the desired fleet
    # (always at least one instance, clamped to the max).
    autoscaler_scale_out_step: float = 0.5
    # Per-action cooldowns: after an action of a kind, no further action
    # of that kind until the cooldown elapses. Replacement of lost
    # capacity (live < desired) bypasses the scale-out cooldown but rides
    # the spawn-retry backoff below.
    autoscaler_scale_out_cooldown_s: float = 20.0
    autoscaler_scale_in_cooldown_s: float = 45.0
    autoscaler_flip_cooldown_s: float = 10.0
    # Hold-state guard: when the stalest load-info entry is older than
    # this (or an instance never reported), the controller HOLDs — a
    # control loop acting on dead telemetry amplifies outages.
    autoscaler_stale_hold_s: float = 15.0
    # Graceful drain: a DRAINING instance whose in-flight work is done
    # deregisters after this grace; one that can't drain by the deadline
    # is deregistered anyway (its stragglers ride the normal failover
    # path).
    autoscaler_drain_grace_s: float = 1.0
    autoscaler_drain_deadline_s: float = 120.0
    # Actuator spawn-failure retry (exponential backoff with jitter): a
    # failed launch never wedges the loop — the controller re-tries the
    # replacement on a later tick.
    autoscaler_spawn_retry_base_s: float = 1.0
    autoscaler_spawn_retry_max_s: float = 30.0
    # Bounded decision log behind /admin/autoscaler.
    autoscaler_decision_log_capacity: int = 256
    # Local actuator launch command template (shell-split; {port} and
    # {coordination_addr} placeholders). "" = the built-in fake-engine
    # launcher (examples/run_fake_engine.py) — drills and benches
    # exercise the full loop against real OS processes.
    autoscaler_spawn_cmd: str = ""
    # JSONL dump directory ("" = in-memory ring only).
    flightrecorder_dir: str = ""
    # --- overload-hardening plane (overload/, docs/robustness.md) ---
    # Default per-request deadline budget in ms (0 = none). A request's
    # own `timeout` body field (seconds) or `x-request-deadline-ms`
    # header wins; the absolute deadline propagates through the enriched
    # payload and the handoff wire and is enforced at every hop —
    # admission, scheduler GC, mid-stream ingest, and the engines. The
    # blunt `request_timeout_s` GC remains the backstop bound.
    default_request_deadline_ms: float = 0.0
    # Admission gate: max in-flight admitted requests per live instance
    # (limit = this x live fleet size off the RCU routing snapshot;
    # 0 = admission control disabled). Requests over the watermark get a
    # fast 429 + Retry-After instead of queueing.
    admission_max_inflight_per_instance: int = 0
    # Batch-priority (x-request-priority: batch) watermark as a fraction
    # of the admission limit; batch is shed entirely while the SLO burn
    # is hot (brownout state).
    admission_batch_watermark: float = 0.5
    admission_retry_after_s: float = 1.0
    # Brownout: when any SLO objective breaches on BOTH burn windows,
    # degrade before refusing — clamp batch max_tokens and shed optional
    # work (trace head-sampling drops to brownout_trace_sample_rate);
    # lifts after recover_ticks consecutive clean sync passes.
    brownout_enabled: bool = True
    brownout_batch_max_tokens: int = 32
    brownout_recover_ticks: int = 2
    brownout_trace_sample_rate: float = 0.0
    # Per-instance circuit breaker on the engine channel (rpc/breaker.py):
    # a rolling error/timeout window flips the channel OPEN — the routing
    # snapshot excludes the instance like SUSPECT — and a half-open probe
    # (reconcile thread) restores it.
    circuit_breaker_enabled: bool = True
    circuit_breaker_window_s: float = 30.0
    circuit_breaker_min_samples: int = 5
    circuit_breaker_failure_ratio: float = 0.5
    circuit_breaker_open_cooldown_s: float = 5.0
    # Global retry budget across failover + relay recovery (token
    # bucket: each accepted request deposits `ratio` tokens, each retry
    # spends one; cap = burst allowance, 0 disables).
    retry_budget_ratio: float = 0.1
    retry_budget_cap: float = 50.0
    debug_log: bool = field(
        default_factory=lambda: os.environ.get("ENABLE_XLLM_DEBUG_LOG", "") not in ("", "0", "false"))
    # --- multi-master service plane (multimaster/) ---
    # Every replica is an ACTIVE frontend; requests are owned by exactly
    # one master via rendezvous hashing of the request id over the live
    # service records (docs/multi_master.md).
    multimaster_ownership: bool = True
    # Mine generated request ids until the accepting frontend owns them
    # (expected N draws on an N-replica plane) so the common case pays no
    # forward hop. Off = ids are assigned by pure rendezvous (~(N-1)/N of
    # accepts relay through /rpc/handoff — useful to drill the path).
    multimaster_mine_owned_ids: bool = True
    # Owner attempts per relayed request: the first POST plus
    # (attempts-1) deterministic re-ownership recoveries.
    handoff_max_attempts: int = 3
    # Max silence between reads of the owner's response before the relay
    # treats the owner as hung and re-owns (a killed-but-not-closed owner
    # — SIGKILL mid-accept, a stalled event loop — leaves the TCP
    # connection open; without a read deadline the relayed stream would
    # stall forever instead of failing over). Engine token gaps beyond
    # this are pathological.
    handoff_stall_timeout_s: float = 60.0
    # Load-info staleness (multi-master replicas score routing off
    # coordination-mirrored telemetry): entries older than this are
    # discounted by CAR/SLO scoring, up to `stale_load_penalty` score
    # units (CAR) / a proportional predicted-TPOT inflation (SLO).
    loadinfo_stale_after_s: float = 9.0
    stale_load_penalty: float = 0.5
    # Telemetry-ingest plane (ISSUE 15). "shard": each ACTIVE master
    # ingests heartbeats/load only for the instances it owns under the
    # rendezvous telemetry map, runs failure detection only for them,
    # and publishes a coalesced load/lease frame per sync tick
    # (XLLM:LOADFRAME:<self>) that every other frontend mirrors — the
    # elected master's ingest funnel is spread 1/N. "master": the
    # reference-shaped legacy funnel (elected master ingests everything,
    # publishes per-instance LOADMETRICS keys, replicas mirror) — the
    # bench baseline and the mixed-version escape hatch. A string knob,
    # not a bool: store_true CLI bools can't be turned off.
    telemetry_ingest_mode: str = "shard"
    # --- coordination-plane static stability (ISSUE 16) ---
    # Degraded-mode serving when the coordination plane is unreachable:
    # the health monitor classifies CONNECTED -> DEGRADED -> RECOVERING
    # from client-side evidence; while degraded the fleet census is
    # frozen (lease lapses stop producing SUSPECT/evict — liveness falls
    # back to direct heartbeat silence over the mux sessions), the
    # elected master stays sticky, and ownership-changing actions are
    # held in a bounded log for replay-or-discard at recovery. "on" /
    # "off" — a string knob, not a bool: store_true CLI bools can't be
    # turned off, and the outage bench needs the control leg.
    coordination_degraded_mode: str = "on"
    # Consecutive failed plane probes (one per sync tick) before the
    # monitor declares DEGRADED. 2 ticks x sync_interval_s rides out a
    # single blip without engaging the freeze.
    coordination_degraded_after_ticks: int = 2
    # While degraded: an owned instance whose direct heartbeats (mux
    # session) have been silent this long goes SUSPECT anyway — the
    # silent-AND-lease-lapsed instance still dies; a chatty one never
    # does. Deliberately longer than heartbeat_silence_to_suspect_s:
    # without lease-lapse corroboration, silence alone needs more
    # benefit of the doubt.
    degraded_heartbeat_silence_s: float = 10.0
    # Recovery storm damping: each entity (master, engine agent) delays
    # its post-outage re-assertion by a deterministic per-entity jitter
    # drawn from [0, this window), so re-registrations spread instead of
    # thundering the just-recovered plane. Also caps the coordination
    # client's randomized reconnect backoff.
    coordination_reconnect_jitter_s: float = 5.0
    # Bound on the held-actions log (oldest coalesced entries are
    # dropped-and-counted beyond it).
    coordination_held_log_capacity: int = 256
    # Handoff delta journal (exact replay dedup): how long the owner
    # keeps buffering a relayed stream's deltas after the relay
    # connection breaks, waiting for a reconnect — beyond it the request
    # is cancelled like a plain disconnect. 0 disables the journal.
    handoff_journal_grace_s: float = 10.0
    # --- request registry ---
    num_output_threads: int = 16      # per-request output-ordering lanes
    request_timeout_s: float = 600.0
    # Dedicated bounded pool for Scheduler.schedule (template/tokenize/
    # route/bind): isolates admission from the default executor, where it
    # would queue behind generations ingest and failover backoff sleeps.
    num_schedule_threads: int = 8

    def with_overrides(self, **kw) -> "ServiceOptions":
        return dataclasses.replace(self, **kw)

    @classmethod
    def add_cli_args(cls, p: argparse.ArgumentParser) -> None:
        for f in dataclasses.fields(cls):
            name = "--" + f.name.replace("_", "-")
            if f.type in ("bool", bool):
                p.add_argument(name, action="store_true", default=None)
            else:
                p.add_argument(name, default=None)

    @classmethod
    def from_cli_args(cls, args: argparse.Namespace) -> "ServiceOptions":
        opts = cls()
        for f in dataclasses.fields(cls):
            v = getattr(args, f.name, None)
            if v is None:
                continue
            cur = getattr(opts, f.name)
            if isinstance(cur, bool):
                setattr(opts, f.name, bool(v))
            elif isinstance(cur, int):
                setattr(opts, f.name, int(v))
            elif isinstance(cur, float):
                setattr(opts, f.name, float(v))
            else:
                setattr(opts, f.name, v)
        return opts
