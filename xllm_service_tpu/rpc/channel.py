"""Synchronous control-plane channel to one engine instance.

Parity: the reference caches one brpc channel per instance with 3 retries and
configurable timeouts (`instance_mgr.cpp:480-498`) and calls the engine's
`XllmAPIService` (Completions/ChatCompletions/Models) and `DisaggPDService`
(LinkInstance/UnlinkInstance) stubs. Here the engine speaks HTTP+JSON; the
channel wraps `requests` with retries. Used from manager threads; the
asyncio HTTP frontend uses its own aiohttp session for hot-path forwarding.
"""

from __future__ import annotations

import json
from typing import Any, Optional

import requests

from ..common.types import InstanceMetaInfo
from ..utils import get_logger

logger = get_logger(__name__)

DEFAULT_TIMEOUT_S = 5.0
DEFAULT_RETRIES = 3


class EngineChannel:
    def __init__(self, name: str, base_url: Optional[str] = None,
                 timeout_s: float = DEFAULT_TIMEOUT_S,
                 retries: int = DEFAULT_RETRIES):
        # `name` is the engine's HTTP address (reference: InstanceMetaInfo.name
        # doubles as the HTTP endpoint, `xllm_rpc_service.proto:31-46`).
        self.name = name
        self.base_url = base_url or (
            name if name.startswith("http") else f"http://{name}")
        self.timeout_s = timeout_s
        self.retries = retries
        self._session = requests.Session()

    def _post(self, path: str, payload: dict[str, Any],
              timeout_s: Optional[float] = None) -> tuple[bool, Any]:
        err: Any = None
        for _ in range(self.retries):
            try:
                r = self._session.post(self.base_url + path, json=payload,
                                       timeout=timeout_s or self.timeout_s)
                if r.status_code == 200:
                    try:
                        return True, r.json()
                    except ValueError:  # incl. requests' JSONDecodeError,
                        return True, r.text   # else it'd retry as failure
                err = f"HTTP {r.status_code}: {r.text[:200]}"
            except requests.RequestException as e:
                err = str(e)
        return False, err

    def _get(self, path: str, timeout_s: Optional[float] = None) -> tuple[bool, Any]:
        try:
            r = self._session.get(self.base_url + path,
                                  timeout=timeout_s or self.timeout_s)
            if r.status_code == 200:
                try:
                    return True, r.json()
                except json.JSONDecodeError:
                    return True, r.text
            return False, f"HTTP {r.status_code}"
        except requests.RequestException as e:
            return False, str(e)

    # ---- control plane -----------------------------------------------------
    def health(self, timeout_s: float = 1.0) -> bool:
        """Reference probes HTTP GET /health (`instance_mgr.cpp:500-539`)."""
        ok, _ = self._get("/health", timeout_s=timeout_s)
        return ok

    def link(self, peer: InstanceMetaInfo) -> bool:
        """Introduce a PD peer for KV-transfer setup (reference
        `DisaggPDService.LinkInstance`, `instance_mgr.cpp:1087-1113`)."""
        ok, err = self._post("/rpc/link", {"peer": json.loads(peer.to_json())})
        if not ok:
            logger.warning("link %s -> %s failed: %s", self.name, peer.name, err)
        return ok

    def unlink(self, peer_name: str) -> bool:
        ok, _ = self._post("/rpc/unlink", {"peer_name": peer_name})
        return ok

    def flip_role(self, new_type: str) -> bool:
        """Dynamic PD-role switch (reference flips types via engine contract,
        `instance_mgr.cpp:1023-1063`; TPU engine swaps compiled programs)."""
        ok, _ = self._post("/rpc/flip_role", {"type": new_type})
        return ok

    def cancel(self, service_request_id: str) -> bool:
        """Propagate client disconnect / service-side cancellation to the
        engine (reference cancels via the engine contract on disconnect,
        `scheduler.cpp:507-521`)."""
        ok, _ = self._post("/rpc/cancel",
                           {"service_request_id": service_request_id})
        return ok

    def models(self) -> list[dict[str, Any]]:
        ok, body = self._get("/v1/models")
        if ok and isinstance(body, dict):
            return body.get("data", [])
        return []

    # ---- data plane (sync fallback; the frontend normally forwards async) --
    def forward(self, path: str, payload: dict[str, Any]) -> tuple[bool, Any]:
        return self._post(path, payload)

    def forward_status(self, path: str,
                       payload: dict[str, Any]) -> tuple[int, Any]:
        """Single-shot POST preserving the engine's status code + body (for
        proxied endpoints where 4xx/5xx must pass through to the client
        instead of collapsing into a retry/False)."""
        try:
            r = self._session.post(self.base_url + path, json=payload,
                                   timeout=self.timeout_s)
        except requests.RequestException as e:
            return 502, {"error": str(e)}
        try:
            return r.status_code, r.json()
        except ValueError:   # covers requests' own JSONDecodeError too
            return r.status_code, {"error": r.text[:300]}

    def close(self) -> None:
        self._session.close()
