"""Synchronous control-plane channel to one engine instance.

Parity: the reference caches one brpc channel per instance with 3 retries and
configurable timeouts (`instance_mgr.cpp:480-498`) and calls the engine's
`XllmAPIService` (Completions/ChatCompletions/Models) and `DisaggPDService`
(LinkInstance/UnlinkInstance) stubs. Here the engine speaks HTTP+JSON; the
channel wraps `requests` with retries. Used from manager threads; the
asyncio HTTP frontend uses its own aiohttp session for hot-path forwarding.

Beyond the reference: retries back off exponentially with jitter (the
reference hammers immediately), both knobs come from `ServiceOptions`
(`rpc_retries`/`rpc_timeout_s`/`rpc_backoff_*`), and non-idempotent
generation forwards are NEVER retried here — an ambiguous failure (e.g.
connection reset after the body was sent) may have started generation, so
replay is owned exclusively by the scheduler's failover layer, which
rebinds incarnations so a duplicate stream is dropped. Every attempt first
consults the fault plane (`common/faults.py`, points `rpc.post`/`rpc.get`)
so chaos drills can script drops, delays and errors deterministically.
"""

from __future__ import annotations

import json
import socket
import time
from typing import Any, Optional

import requests
from requests.adapters import HTTPAdapter

from ..common import tracing
from ..common.faults import FAULTS, FaultInjected
from ..common.metrics import RPC_RETRIES_TOTAL
from ..devtools import ownership as _ownership
from ..common.types import InstanceMetaInfo
from ..utils import get_logger, jittered_backoff
from . import wire
from .breaker import CircuitBreaker

logger = get_logger(__name__)

DEFAULT_TIMEOUT_S = 5.0
DEFAULT_RETRIES = 3
DEFAULT_BACKOFF_BASE_S = 0.05
DEFAULT_BACKOFF_MAX_S = 1.0


def _breaker_ok(status_code: int) -> bool:
    """Is an HTTP answer health evidence for the circuit breaker?

    Any 2xx/3xx/4xx answer is (the instance's serving loop is alive and
    deciding) — and so are the DELIBERATE overload/lifecycle rejections
    the overload plane itself produces: 429 (shed), 503 (draining /
    accept-queue full), 504 (deadline refused). Counting those as
    sickness would eject a healthy-but-busy instance from routing during
    the exact burst the plane exists to absorb: deadline-expired
    dispatches land 504s, the breaker opens, capacity shrinks, queues
    deepen, MORE deadlines expire — a positive-feedback ejection
    cascade. Only unexplained server errors (500/502/...) join
    transport failures as breaker evidence."""
    return status_code < 500 or status_code in (503, 504)


class _KeepaliveAdapter(HTTPAdapter):
    """Transport adapter enabling TCP keepalive on pooled connections: a
    channel idles between control-plane calls (heartbeat gaps, quiet
    fleets), and a silently dropped NAT/conntrack mapping otherwise
    surfaces as a full connect+retry on the NEXT call — paid by a live
    request (failover replay, cancellation)."""

    _SOCKET_OPTIONS = [(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)]
    # Aggressive-but-sane probe timings where the platform exposes them.
    if hasattr(socket, "TCP_KEEPIDLE"):
        _SOCKET_OPTIONS += [
            (socket.IPPROTO_TCP, socket.TCP_KEEPIDLE, 30),
            (socket.IPPROTO_TCP, socket.TCP_KEEPINTVL, 10),
            (socket.IPPROTO_TCP, socket.TCP_KEEPCNT, 3),
        ]

    def init_poolmanager(self, *args, **kwargs):
        from urllib3.connection import HTTPConnection

        # EXTEND the urllib3 defaults — replacing them would silently
        # drop TCP_NODELAY and re-enable Nagle on every channel RPC.
        kwargs["socket_options"] = (
            list(HTTPConnection.default_socket_options)
            + list(self._SOCKET_OPTIONS))
        return super().init_poolmanager(*args, **kwargs)


def make_keepalive_session(pool_connections: int = 4,
                           pool_maxsize: int = 4) -> requests.Session:
    """A shared, BOUNDED keepalive session: one connection pool for every
    telemetry hop a process makes (heartbeats + generation-delta pushes),
    instead of one adapter pool per destination plus a fresh TCP connect
    per bare ``requests.post``. ``pool_connections`` bounds how many
    per-host pools are cached (LRU — a master that stopped being a
    destination ages out), ``pool_maxsize`` bounds sockets per host.
    The multiplexed engine telemetry session (ISSUE 15) is one of these
    with all traffic routed at the engine's owning master, so the
    per-engine connection count stays O(1) regardless of ``--masters``."""
    s = requests.Session()
    s.mount("http://", _KeepaliveAdapter(pool_connections=pool_connections,
                                         pool_maxsize=pool_maxsize))
    return s


def session_connection_stats(session: requests.Session) -> dict:
    """Live connection accounting for a session built by
    :func:`make_keepalive_session` — the bench's engine-side
    connection-count evidence. ``hosts`` = distinct destination pools
    currently cached; ``connections_created`` = TCP connects ever made
    across them (urllib3's per-pool counter)."""
    try:
        pools = session.get_adapter("http://").poolmanager.pools
        # urllib3's RecentlyUsedContainer: values() under its own lock.
        host_pools = list(pools._container.values())  # noqa: SLF001
        return {
            "hosts": len(host_pools),
            "connections_created": sum(
                getattr(p, "num_connections", 0) for p in host_pools),
        }
    except Exception:  # noqa: BLE001  # xlint: allow-broad-except(urllib3 pool internals are version-dependent; accounting degrades to -1 sentinels rather than breaking telemetry)
        return {"hosts": -1, "connections_created": -1}


@_ownership.verify_state
class EngineChannel:
    def __init__(self, name: str, base_url: Optional[str] = None,
                 timeout_s: float = DEFAULT_TIMEOUT_S,
                 retries: int = DEFAULT_RETRIES,
                 backoff_base_s: float = DEFAULT_BACKOFF_BASE_S,
                 backoff_max_s: float = DEFAULT_BACKOFF_MAX_S,
                 breaker: Optional[CircuitBreaker] = None):
        # `name` is the engine's HTTP address (reference: InstanceMetaInfo.name
        # doubles as the HTTP endpoint, `xllm_rpc_service.proto:31-46`).
        self.name = name
        self.base_url = base_url or (
            name if name.startswith("http") else f"http://{name}")
        self.timeout_s = timeout_s
        self.retries = max(1, retries)
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        # Negotiated dispatch-wire format for `forward` (InstanceMgr sets
        # this from the instance's advertised wire_formats at
        # registration; 415 responses demote it back to JSON).
        self.wire_format = wire.WIRE_JSON
        # Per-instance circuit breaker (rpc/breaker.py): OPEN fails every
        # call fast; the reconcile thread mirrors the state into routing
        # (BREAKER_OPEN) and drives the half-open recovery probe.
        self.breaker = breaker if breaker is not None \
            else CircuitBreaker(name)
        self._session = requests.Session()
        self._session.mount("http://", _KeepaliveAdapter())

    @classmethod
    def from_options(cls, name: str, options: Any) -> "EngineChannel":
        """Build with the `rpc_*` / `circuit_breaker_*` knobs from a
        ServiceOptions."""
        return cls(name,
                   timeout_s=options.rpc_timeout_s,
                   retries=options.rpc_retries,
                   backoff_base_s=options.rpc_backoff_base_s,
                   backoff_max_s=options.rpc_backoff_max_s,
                   breaker=CircuitBreaker(
                       name,
                       window_s=options.circuit_breaker_window_s,
                       min_samples=options.circuit_breaker_min_samples,
                       failure_ratio=options.circuit_breaker_failure_ratio,
                       open_cooldown_s=(
                           options.circuit_breaker_open_cooldown_s),
                       enabled=options.circuit_breaker_enabled))

    def _sleep_backoff(self, prior_attempts: int) -> None:
        time.sleep(jittered_backoff(self.backoff_base_s,
                                    self.backoff_max_s, prior_attempts))

    def _post(self, path: str, payload: dict[str, Any],
              timeout_s: Optional[float] = None,
              retries: Optional[int] = None,
              fmt: str = wire.WIRE_JSON) -> tuple[bool, Any]:
        attempts = self.retries if retries is None else max(1, retries)
        err: Any = None
        if not self.breaker.allow():
            return False, "circuit breaker open"
        data, ctype = wire.encode_dispatch(payload, fmt)
        # Trace propagation: the calling thread's active span rides the
        # wire as headers ({} almost always — one thread-local read).
        headers = dict(tracing.current_headers())
        headers["Content-Type"] = ctype
        for attempt in range(attempts):
            if attempt:
                RPC_RETRIES_TOTAL.labels(instance=self.name).inc()
                self._sleep_backoff(attempt - 1)
            try:
                FAULTS.check("rpc.post", instance=self.name, path=path)
                r = self._session.post(self.base_url + path, data=data,
                                       headers=headers,
                                       timeout=timeout_s or self.timeout_s)
                if r.status_code == 200:
                    try:
                        self.breaker.record(True)
                        return True, r.json()
                    except ValueError:  # incl. requests' JSONDecodeError,
                        return True, r.text   # else it'd retry as failure
                self.breaker.record(_breaker_ok(r.status_code))
                err = f"HTTP {r.status_code}: {r.text[:200]}"
            except FaultInjected as e:
                self.breaker.record(False)
                err = str(e)
            except requests.RequestException as e:
                self.breaker.record(False)
                err = str(e)
        return False, err

    def _get(self, path: str, timeout_s: Optional[float] = None,
             retries: Optional[int] = None) -> tuple[bool, Any]:
        attempts = self.retries if retries is None else max(1, retries)
        err: Any = None
        if not self.breaker.allow():
            return False, "circuit breaker open"
        headers = tracing.current_headers() or None
        for attempt in range(attempts):
            if attempt:
                RPC_RETRIES_TOTAL.labels(instance=self.name).inc()
                self._sleep_backoff(attempt - 1)
            try:
                FAULTS.check("rpc.get", instance=self.name, path=path)
                r = self._session.get(self.base_url + path, headers=headers,
                                      timeout=timeout_s or self.timeout_s)
                if r.status_code == 200:
                    try:
                        self.breaker.record(True)
                        return True, r.json()
                    except ValueError:  # same contract as _post: a non-JSON
                        return True, r.text   # 200 body is a success payload
                self.breaker.record(_breaker_ok(r.status_code))
                err = f"HTTP {r.status_code}"
            except FaultInjected as e:
                self.breaker.record(False)
                err = str(e)
            except requests.RequestException as e:
                self.breaker.record(False)
                err = str(e)
        return False, err

    def warm_up(self, timeout_s: float = 2.0) -> bool:
        """Prime the connection pool (TCP + keepalive handshake) so the
        FIRST real call on this channel doesn't pay connection setup.
        Best-effort: registration proceeds either way."""
        ok, _ = self._get("/health", timeout_s=timeout_s, retries=1)
        return ok

    # ---- control plane -----------------------------------------------------
    def health(self, timeout_s: float = 1.0) -> bool:
        """Reference probes HTTP GET /health (`instance_mgr.cpp:500-539`).
        Single attempt: InstanceMgr owns the probe-retry policy
        (`health_probe_attempts`)."""
        ok, _ = self._get("/health", timeout_s=timeout_s, retries=1)
        return ok

    def link(self, peer: InstanceMetaInfo) -> bool:
        """Introduce a PD peer for KV-transfer setup (reference
        `DisaggPDService.LinkInstance`, `instance_mgr.cpp:1087-1113`)."""
        ok, err = self._post("/rpc/link", {"peer": json.loads(peer.to_json())})
        if not ok:
            logger.warning("link %s -> %s failed: %s", self.name, peer.name, err)
        return ok

    def unlink(self, peer_name: str) -> bool:
        ok, _ = self._post("/rpc/unlink", {"peer_name": peer_name})
        return ok

    def flip_role(self, new_type: str) -> bool:
        """Dynamic PD-role switch (reference flips types via engine contract,
        `instance_mgr.cpp:1023-1063`; TPU engine swaps compiled programs)."""
        ok, _ = self._post("/rpc/flip_role", {"type": new_type})
        return ok

    def drain(self) -> bool:
        """Graceful retirement (autoscaler scale-in / operator drain —
        no reference counterpart, its instances die abruptly): the
        engine advertises `draining` in its registration, finishes
        in-flight work and self-stops. Best effort; the master marks the
        instance DRAINING either way."""
        ok, _ = self._post("/rpc/drain", {})
        return ok

    def cancel(self, service_request_id: str) -> bool:
        """Propagate client disconnect / service-side cancellation to the
        engine (reference cancels via the engine contract on disconnect,
        `scheduler.cpp:507-521`)."""
        ok, _ = self._post("/rpc/cancel",
                           {"service_request_id": service_request_id})
        return ok

    def models(self) -> list[dict[str, Any]]:
        ok, body = self._get("/v1/models")
        if ok and isinstance(body, dict):
            return body.get("data", [])
        return []

    # ---- data plane (sync fallback; the frontend normally forwards async) --
    def forward(self, path: str, payload: dict[str, Any]) -> tuple[bool, Any]:
        """Single-shot by design: a generation forward is NOT idempotent.
        An ambiguous failure (reset after send) may already be generating;
        blind retry would double-submit. The failover layer owns replay —
        it rebinds incarnations first so any duplicate stream is dropped.

        Rides the negotiated dispatch wire (msgpack for current engines).
        A 415 demotes the channel to JSON and re-sends once — a 415
        rejection cannot have started generation, so this is the one safe
        retry on this wire."""
        ok, resp = self._post(path, payload, retries=1,
                              fmt=self.wire_format)
        if not ok and self.wire_format != wire.WIRE_JSON \
                and isinstance(resp, str) and resp.startswith("HTTP 415"):
            logger.warning("engine %s rejected msgpack dispatch; demoting "
                           "channel to JSON wire", self.name)
            with _ownership.escape("415 wire demotion: one-way monotonic "
                                   "fallback to JSON; GIL-atomic string "
                                   "swap on the negotiation slot"):
                self.wire_format = wire.WIRE_JSON
            ok, resp = self._post(path, payload, retries=1)
        return ok, resp

    def forward_status(self, path: str,
                       payload: dict[str, Any]) -> tuple[int, Any]:
        """Single-shot POST preserving the engine's status code + body (for
        proxied endpoints where 4xx/5xx must pass through to the client
        instead of collapsing into a retry/False)."""
        if not self.breaker.allow():
            return 503, {"error": "circuit breaker open"}
        try:
            FAULTS.check("rpc.post", instance=self.name, path=path)
            r = self._session.post(self.base_url + path, json=payload,
                                   headers=tracing.current_headers() or None,
                                   timeout=self.timeout_s)
        except (requests.RequestException, FaultInjected) as e:
            self.breaker.record(False)
            return 502, {"error": str(e)}
        self.breaker.record(_breaker_ok(r.status_code))
        try:
            return r.status_code, r.json()
        except ValueError:   # covers requests' own JSONDecodeError too
            return r.status_code, {"error": r.text[:300]}

    def close(self) -> None:
        self._session.close()
