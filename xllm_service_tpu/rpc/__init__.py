"""L2 RPC plane: service↔engine wire contract and channels.

Parity: reference `rpc_service/` + `proto/` (SURVEY.md §2.3). Both sides of
the contract are ours to define (the reference's engine submodule is empty,
SURVEY.md §0); we use HTTP+JSON framing over aiohttp/requests rather than
brpc+protobuf — the *behavioral* contract (fire-and-forget enriched request
forwarding, batched Generations streaming back, heartbeats with KV-cache
events and load metrics, Link/Unlink peer introduction) is preserved.
"""

from .channel import EngineChannel

__all__ = ["EngineChannel"]

# Coordination key layout (reference key scheme `XLLM:<TYPE>:<name>`,
# `instance_mgr.cpp:45-50`; `XLLM:SERVICE:`, `XLLM:CACHE:`,
# `XLLM:LOADMETRICS:` per SURVEY.md §3.4-3.5).
INSTANCE_KEY_PREFIX = "XLLM:INSTANCE:"       # + "<TYPE>:<name>"
SERVICE_KEY_PREFIX = "XLLM:SERVICE:"         # + "<ip:rpc_port>"
MASTER_KEY = "XLLM:SERVICE:MASTER"
CACHE_KEY_PREFIX = "XLLM:CACHE:"             # + block-hash hex (legacy)
# Binary KV-index delta frames (rpc/wire.py encode_kv_frame): one key per
# master sync tick, zero-padded monotonic seq so lexicographic order ==
# apply order. Lives under CACHE_KEY_PREFIX so one watch covers frames
# AND legacy per-block keys ("FRAME:" cannot collide with hex).
CACHE_FRAME_KEY_PREFIX = CACHE_KEY_PREFIX + "FRAME:"  # + %020d seq
LOADMETRICS_KEY_PREFIX = "XLLM:LOADMETRICS:"  # + instance name
# Sharded telemetry-ingest plane (multimaster): ONE coalesced load/lease
# frame key per OWNING master (rpc/wire.py encode_load_frame), rewritten
# in place each sync tick — the key is the owner's address, so each key
# is single-writer by construction and "latest frame per owner" is the
# whole convergence story (no log growth, no compaction).
LOADFRAME_KEY_PREFIX = "XLLM:LOADFRAME:"      # + owner rpc addr


def instance_key(type_str: str, name: str) -> str:
    return f"{INSTANCE_KEY_PREFIX}{type_str}:{name}"


def parse_instance_key(key: str) -> tuple[str, str]:
    """-> (type, name)"""
    rest = key[len(INSTANCE_KEY_PREFIX):]
    type_str, _, name = rest.partition(":")
    return type_str, name
