"""Per-instance circuit breaker for the engine control-plane channel.

One sick-but-leased engine — accepting TCP, failing or timing out every
RPC — is worse than a dead one: a dead engine's lease lapses and the
three-state failure detector evicts it, but a sick one keeps renewing
its lease while every cancel/link/drain/forward against it burns a full
timeout × retries, and every request routed to it burns failover budget.
The breaker is the standard three-state answer (Nygard, *Release It!*;
the same shape as Envoy outlier detection), attached to each
:class:`..rpc.channel.EngineChannel`:

- **CLOSED** — normal. Outcomes are recorded into a rolling window;
  when at least ``min_samples`` outcomes in ``window_s`` are
  ``failure_ratio`` bad, the breaker OPENs.
- **OPEN** — every call fails fast (no network). The routing layer
  (InstanceMgr's reconcile thread) mirrors this as the
  ``BREAKER_OPEN`` runtime state, so the RCU routing snapshot excludes
  the instance exactly like SUSPECT.
- **HALF_OPEN** — after ``open_cooldown_s`` ONE probe is allowed
  through (the reconcile thread's health probe). Success closes the
  breaker and restores the instance to routing; failure re-opens it for
  another cooldown.

Failures are TRANSPORT failures (timeouts, resets, refusals) and
unexplained server errors (500/502) — any other HTTP answer, including
the overload plane's own deliberate 429/503/504 rejections, is evidence
of health, not sickness (see ``channel._breaker_ok``: counting
deliberate overload answers as failures would eject busy-but-healthy
instances mid-burst, a positive-feedback capacity collapse). Recording
happens per attempt (inside the channel's retry loop), so a flapping
instance accumulates evidence at attempt rate, not call rate.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Optional

from ..devtools import lifecycle as _lifecycle
from ..devtools import ownership as _ownership
from ..devtools.locks import make_lock

STATE_CLOSED = "closed"
STATE_OPEN = "open"
STATE_HALF_OPEN = "half_open"


@_ownership.verify_state
class CircuitBreaker:
    """Rolling-window breaker; all transitions under one leaf lock."""

    def __init__(self, name: str = "", window_s: float = 30.0,
                 min_samples: int = 5, failure_ratio: float = 0.5,
                 open_cooldown_s: float = 5.0, enabled: bool = True):
        self.name = name
        self.window_s = max(0.1, window_s)
        self.min_samples = max(1, min_samples)
        self.failure_ratio = min(1.0, max(0.0, failure_ratio))
        self.open_cooldown_s = max(0.0, open_cooldown_s)
        self.enabled = enabled
        self._lock = make_lock("rpc.breaker", order=836)  # lock-order: 836
        self._events: deque = deque()     # (monotonic_ts, ok)
        self._state = STATE_CLOSED
        self._opened_at = 0.0
        self._probe_inflight = False
        self._open_total = 0

    # ---------------------------------------------------------------- reads
    def state(self) -> str:
        with self._lock:
            return self._state

    def is_open(self, now: Optional[float] = None) -> bool:
        """True while calls would be refused (OPEN before cooldown).
        HALF_OPEN reports False: a probe may pass."""
        now = now if now is not None else time.monotonic()
        with self._lock:
            return self._state == STATE_OPEN and \
                now - self._opened_at < self.open_cooldown_s

    # ------------------------------------------------------------ decisions
    def allow(self, now: Optional[float] = None) -> bool:
        """May a call proceed now? OPEN fails fast until the cooldown
        elapses, then transitions to HALF_OPEN and admits exactly ONE
        probe at a time (further calls fail fast until the probe's
        outcome is recorded)."""
        if not self.enabled:
            return True
        now = now if now is not None else time.monotonic()
        with self._lock:
            if self._state == STATE_CLOSED:
                return True
            if self._state == STATE_OPEN:
                if now - self._opened_at < self.open_cooldown_s:
                    return False
                self._state = STATE_HALF_OPEN
                self._probe_inflight = True
                _lifecycle.note_acquire("breaker-probe", key=self.name)
                return True
            # HALF_OPEN: one probe in flight at a time.
            if self._probe_inflight:
                return False
            self._probe_inflight = True
            _lifecycle.note_acquire("breaker-probe", key=self.name)
            return True

    def record(self, ok: bool, now: Optional[float] = None) -> None:
        """One attempt outcome. In HALF_OPEN the outcome resolves the
        probe: success closes (window reset — the sick history must not
        immediately re-trip), failure re-opens for another cooldown."""
        if not self.enabled:
            return
        now = now if now is not None else time.monotonic()
        with self._lock:
            if self._state == STATE_HALF_OPEN:
                if self._probe_inflight:
                    _lifecycle.note_release("breaker-probe", key=self.name)
                self._probe_inflight = False
                if ok:
                    self._state = STATE_CLOSED
                    self._events.clear()
                else:
                    self._state = STATE_OPEN
                    self._opened_at = now
                    self._open_total += 1
                return
            self._events.append((now, ok))
            horizon = now - self.window_s
            while self._events and self._events[0][0] < horizon:
                self._events.popleft()
            if self._state != STATE_CLOSED:
                return
            n = len(self._events)
            if n < self.min_samples:
                return
            bad = sum(1 for _, o in self._events if not o)
            if bad / n >= self.failure_ratio:
                self._state = STATE_OPEN
                self._opened_at = now
                self._open_total += 1

    # ------------------------------------------------------------ reporting
    def snapshot(self, now: Optional[float] = None) -> dict[str, Any]:
        now = now if now is not None else time.monotonic()
        with self._lock:
            n = len(self._events)
            bad = sum(1 for _, o in self._events if not o)
            return {
                "state": self._state,
                "enabled": self.enabled,
                "window_samples": n,
                "window_failures": bad,
                "open_total": self._open_total,
                "open_age_s": round(now - self._opened_at, 3)
                if self._state != STATE_CLOSED else 0.0,
            }
