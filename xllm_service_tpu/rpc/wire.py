"""Symmetric binary dispatch wire (master → engine hot path).

The token-return wire (engine → master ``/rpc/generations``) has been
msgpack since the tracing round — binary beats JSON both to encode and to
parse, and the reference ships batched protobuf on the same hop for the
same reason. The dispatch wire (master → engine enriched
completions/chat payload) stayed JSON: ``token_ids`` is a
multi-thousand-int list JSON-encoded per request. This module makes the
hot wire symmetric:

- ``encode_dispatch(payload, fmt)`` — one blessed encoder for every
  dispatch site (msgpack when the target advertises it, compact JSON
  otherwise). msgpack encoding of a given dict is deterministic
  (insertion-ordered maps), so a retained failover payload re-encodes
  byte-identically — the chaos drill asserts this.
- ``decode_body(content_type, data)`` — the engine-side inverse,
  content-type negotiated.

Negotiation is per instance: engines advertise ``wire_formats`` in their
registration metadata (``InstanceMetaInfo.wire_formats``); the master
dispatches msgpack iff the target advertises it, and demotes an instance
to JSON on an HTTP 415 (legacy engine running an older build — a 415
rejection cannot have started generation, so the JSON re-send is safe
even on this non-idempotent wire).

``HOT_PATH_FUNCTIONS`` is the registry behind xlint's ``hot-json`` rule:
inside these functions, hand-rolled ``json.dumps``/``json=`` encoding is
a lint violation (hatch: ``# xlint: allow-hot-json(reason)``) — dispatch
bytes must come from this module so the wire stays symmetric and the
negotiation stays in one place.
"""

from __future__ import annotations

import base64
import json
from typing import Any

import msgpack

from ..common import native as _native

#: Wire format names (the values carried in InstanceMetaInfo.wire_formats).
WIRE_MSGPACK = "msgpack"
WIRE_JSON = "json"

MSGPACK_CONTENT_TYPE = "application/msgpack"
JSON_CONTENT_TYPE = "application/json"

#: Registered hot-path dispatch call sites ("Class.method" or a bare
#: module-level function name → why it is hot). xlint's hot-json rule is
#: bidirectional over this registry: each entry must resolve to a live
#: function in the tree, and inside each, ``json.dumps(...)`` calls and
#: ``json=`` kwargs are violations unless hatched with
#: ``# xlint: allow-hot-json(reason)``.
HOT_PATH_FUNCTIONS: dict[str, str] = {
    "XllmHttpService._forward_to_instance":
        "initial engine dispatch from the HTTP frontend",
    "XllmHttpService.handle_generations":
        "token-return ingest (hottest service endpoint)",
    "XllmHttpService.handle_telemetry":
        "multiplexed engine telemetry ingest (tagged hb/gens frames + "
        "master->master gens relay)",
    "XllmHttpService._respond":
        "SSE emit loop (client-facing frames are protocol JSON)",
    "Scheduler._failover_loop":
        "failover replay dispatch",
    "EngineChannel.forward":
        "sync dispatch fallback / failover wire",
    "GenerationStreamer._send":
        "engine agent batched Generations push",
    "FakeEngine._generate":
        "fake-engine Generations push (wire-contract reference impl)",
    "EngineAgent._heartbeat_loop":
        "heartbeat push (KV-cache event deltas, raw 16-byte keys)",
    "FakeEngine._heartbeat_loop":
        "fake-engine heartbeat push (wire-contract reference impl)",
    "GlobalKVCacheMgr.upload_kvcache":
        "master→coordination KV-index sync (binary delta frames)",
    "OwnershipRouter.owner_of":
        "per-request ownership resolution (every accept + every relay)",
    "OwnershipRouter.instance_owner":
        "per-beat telemetry-shard verdict (memoized on the published "
        "member tuple; native rendezvous walk on miss)",
    "SimpleTokenizer.encode":
        "per-request prompt tokenization inside the route loop (the "
        "profiler's hottest route frame; native byte-id fast path)",
    "HandoffRelay._relay_stream":
        "owner-forward SSE relay (frames must pass through as raw bytes)",
    "XllmHttpService.handle_handoff":
        "owner-side ingest of relayed requests (full dispatch pipeline)",
    "TieredKVStore.offload":
        "per-eviction tier-offload admission (engine thread, never blocks)",
    "TieredKVStore.fetch":
        "cold-tier onload read on the engine admission path",
    "InferenceEngine._pump_tier_offloads":
        "eviction drain after every page allocation (decode loop)",
    "InferenceEngine._onload_cold_prefix":
        "cold-prefix extension walk at admission (ahead of prefill)",
    "StreamOfferTable.read_chunk":
        "per-chunk streaming-transfer serve (one memoryview slice)",
    "pull_stream":
        "chunked KV pull loop (decode-side executor thread, paced)",
    "EngineAgent._h_kv_stream_pull":
        "streaming-transfer pull endpoint (msgpack frames)",
    "SamplingProfiler._sample_once":
        "always-on ~19 Hz wall-clock stack sampler tick (overhead gate "
        "<=1%: benchmarks/bench_profile_overhead.py)",
    # RCU snapshot readers (rcu-read single-load discipline applies: one
    # load of the publication attribute per call, or two loads may
    # observe different snapshots — the PR-6 COW-apply torn-read smell).
    "GlobalKVCacheMgr.match":
        "lock-free prefix-index walk on every CAR schedule",
    "InstanceMgr.get_next_instance_pair":
        "RR pair selection off the routing snapshot",
    "InstanceMgr.select_instance_pair_on_slo":
        "SLO pair selection off the routing snapshot",
    "select_pair_on_slo":
        "lock-free SLO selection kernel (snapshot + request-load view + "
        "staleness-aware predictive scoring)",
    "SloAwarePolicy.select_instances_pair":
        "whole SLO_AWARE selection on the schedule path",
    "InstanceMgr.get_request_loads":
        "published request-load accessor for SLO predictive scoring",
    "AutoscalerController.tick":
        "autoscaler decision loop (sync cadence; lock-free telemetry "
        "gather, pure kernel, rate-limited enactment)",
    "InstanceMgr.bind_request_instance_incarnations":
        "RCU bind re-validation against the current snapshot",
    "InstanceMgr.get_channel":
        "per-dispatch channel lookup off the routing snapshot",
    "InstanceMgr.get_load_infos":
        "published load-info accessor for CAR/planner scoring",
    "CacheAwareRoutingPolicy.select_instances_pair":
        "whole CAR selection (match + load-info scoring, lock-free)",
}


def pack_dispatch(payload: dict[str, Any]) -> bytes:
    """Deterministic msgpack encoding of a dispatch payload (same dict →
    same bytes; maps keep insertion order). Native fast path when
    libhotcore serves it — byte-identical by the differential tests, so
    the retained-failover re-encode determinism holds across a mixed
    native/pure fleet."""
    enc = _native.packb(payload)
    if enc is not _native.MISS:
        return enc
    return msgpack.packb(payload, use_bin_type=True)


def unpack_dispatch(data: bytes) -> Any:
    obj = _native.unpackb(data)
    if obj is not _native.MISS:
        return obj
    return msgpack.unpackb(data, raw=False)


def encode_dispatch(payload: dict[str, Any],
                    fmt: str = WIRE_JSON) -> tuple[bytes, str]:
    """Serialize an enriched dispatch payload for the wire. Returns
    (body bytes, content type)."""
    if fmt == WIRE_MSGPACK:
        return pack_dispatch(payload), MSGPACK_CONTENT_TYPE
    return (json.dumps(payload, separators=(",", ":")).encode(),
            JSON_CONTENT_TYPE)


def decode_body(content_type: str, data: bytes) -> Any:
    """Engine-side inverse of :func:`encode_dispatch`. Raises ValueError
    on a malformed body (callers surface it as a 400)."""
    if content_type and MSGPACK_CONTENT_TYPE in content_type:
        try:
            return unpack_dispatch(data)
        except Exception as e:  # msgpack raises library-specific errors
            raise ValueError(f"malformed msgpack body: {e}") from None
    return json.loads(data)


# --------------------------------------------------------------- KV frames
#
# Master→coordination KV-index sync rides ONE coordination key per sync
# tick (`XLLM:CACHE:FRAME:<seq>`) whose value is a msgpack-encoded delta
# batch with raw 16-byte block keys — instead of one JSON-valued key per
# block. Coordination values are strings, so the binary frame is base64-
# wrapped (pure ASCII: survives every backend, including the native C++
# coordination server's JSON framing). Replicas decode one blob per tick
# and batch-apply; the legacy per-block JSON keys remain readable for
# mixed-version clusters (global_kvcache_mgr.py).

def encode_kv_frame(upserts: dict[bytes, Any], removals: "list[bytes]",
                    full: bool = False) -> str:
    """One sync tick's delta: ``upserts`` maps raw block key → positional
    [hbm, dram, ssd] instance-name row (CacheLocations.to_row); ``full``
    marks a compaction frame carrying the entire index (replicas rebuild
    from it instead of merging)."""
    frame = {"u": upserts, "r": list(removals)}
    if full:
        frame["full"] = True
    enc = _native.pack_b64(frame)
    if enc is not _native.MISS:
        return enc
    return base64.b64encode(
        msgpack.packb(frame, use_bin_type=True)).decode("ascii")


def decode_kv_frame(value: str) -> "tuple[dict[bytes, Any], list[bytes], bool]":
    """Inverse of :func:`encode_kv_frame` → (upserts, removals, full).
    Raises ValueError on a malformed frame (callers skip it)."""
    try:
        frame = _native.unpack_b64(value)
        if frame is _native.MISS:
            frame = msgpack.unpackb(base64.b64decode(value), raw=False)
        if not isinstance(frame, dict):
            raise TypeError("frame is not a map")
        upserts = frame.get("u") or {}
        removals = list(frame.get("r") or ())
        if not isinstance(upserts, dict):
            raise TypeError("frame upserts is not a map")
    except Exception as e:  # base64/msgpack raise library-specific errors
        raise ValueError(f"malformed kv frame: {e}") from None
    return upserts, removals, bool(frame.get("full"))


# ------------------------------------------------------------- load frames
#
# Sharded telemetry ingest (multimaster): each active master coalesces
# the heartbeat-fed load/latency/lease state of the instances it OWNS
# (rendezvous shard map) into one frame per sync tick, written to its own
# `XLLM:LOADFRAME:<owner addr>` key. Every other frontend mirrors the
# frame — the elected master's per-instance LOADMETRICS upload funnel is
# replaced by N single-writer keys. base64(msgpack), like the KV frames:
# coordination values are strings on every backend.

def encode_load_frame(instances: dict, gone: "dict[str, str]", seq: int,
                      now_ms: int) -> str:
    """One owner's full telemetry shard: ``instances`` maps instance
    name → {"l": load dict, "y": latency dict, "hb": last-heartbeat ms,
    "up": telemetry-updated ms, "st": runtime-state value}; ``gone``
    maps recently-evicted owned instances to the eviction reason
    (tombstones — mirrors deregister with the same reason, so a
    mirrored graceful drain doesn't count as an eviction); ``now_ms``
    is the owner's clock at build time so mirrors can re-base
    heartbeat/telemetry ages without cross-host clock agreement."""
    frame = {"i": instances, "g": dict(gone), "s": seq, "ms": now_ms}
    enc = _native.pack_b64(frame)
    if enc is not _native.MISS:
        return enc
    return base64.b64encode(
        msgpack.packb(frame, use_bin_type=True)).decode("ascii")


def decode_load_frame(value: str) -> dict:
    """Inverse of :func:`encode_load_frame` → {"i": ..., "g": [...],
    "s": seq, "ms": build ms}. Raises ValueError on a malformed frame
    (callers skip it)."""
    try:
        frame = _native.unpack_b64(value)
        if frame is _native.MISS:
            frame = msgpack.unpackb(base64.b64decode(value), raw=False)
        if not isinstance(frame, dict) or not isinstance(
                frame.get("i", {}), dict):
            raise TypeError("load frame is not a map")
    except Exception as e:  # base64/msgpack raise library-specific errors
        raise ValueError(f"malformed load frame: {e}") from None
    frame.setdefault("i", {})
    frame.setdefault("g", {})
    return frame


# --------------------------------------------------------- telemetry frames
#
# Multiplexed engine telemetry session: ONE keepalive session per engine
# carries tagged frames to the engine's OWNING master (`/rpc/telemetry`)
# — heartbeats ("hb") ingested there, generation-delta batches ("gens")
# ingested when the tagged dest is the owner itself and relayed
# master→master otherwise — so engine-side fan-out is O(engines), not
# O(engines × masters).

TELEMETRY_HB = "hb"
TELEMETRY_GENS = "gens"


def encode_telemetry(frames: "list[dict]") -> tuple[bytes, str]:
    """Tagged telemetry frames for one POST: each frame is
    {"t": "hb", "d": heartbeat payload} or {"t": "gens",
    "dest": service addr, "d": {"gens": [...]}}. Always msgpack — the
    endpoint is new, so there is no legacy-JSON peer to negotiate with
    (an old master answers 404 and the engine falls back to the legacy
    wires)."""
    enc = _native.packb({"frames": frames})
    if enc is not _native.MISS:
        return enc, MSGPACK_CONTENT_TYPE
    return (msgpack.packb({"frames": frames}, use_bin_type=True),
            MSGPACK_CONTENT_TYPE)


def negotiate(wire_formats: Any) -> str:
    """The dispatch format for an instance advertising `wire_formats`
    (missing/empty/legacy metadata → JSON)."""
    try:
        return WIRE_MSGPACK if WIRE_MSGPACK in (wire_formats or ()) \
            else WIRE_JSON
    except TypeError:
        return WIRE_JSON
