"""Standalone coordination server.

The framework's self-contained replacement for the reference's external etcd
cluster (SURVEY.md §2.13: "etcd is hardware-neutral" — but this framework is
also deployable with zero external dependencies). One server process holds a
:class:`MemoryStore`; any number of scheduler replicas and engine agents
connect over TCP with a newline-delimited JSON protocol.

etcd-parity semantics:
- leases: a leased key expires unless refreshed; clients refresh at ttl/3.
  Because refreshes ride the client's connection, process death ⇒ refresh
  stop ⇒ expiry ⇒ DELETE watch events — the exact liveness signal the
  reference builds on etcd leases (`etcd_client.cpp:105-120`).
- watches: server pushes `{"event": "watch", ...}` frames to subscribed
  connections.
- auth: optional username/password (reference reads ETCD_USERNAME/PASSWORD,
  `scheduler.cpp:29-58`).

Run: ``python -m xllm_service_tpu.coordination.server --port 2379``.
"""

from __future__ import annotations

import argparse
import json
import socket
import socketserver
import threading
import time
from typing import Optional

from .base import KeyEvent
from .memory import MemoryStore
from ..devtools.locks import make_lock
from ..utils import get_logger

logger = get_logger(__name__)


class _Conn(socketserver.BaseRequestHandler):
    """One client connection: request/response + watch pushes."""

    def setup(self) -> None:
        self.wlock = make_lock("coord_server.conn_write", order=36)  # lock-order: 36
        self.watch_ids: dict[int, int] = {}   # client watch id -> store watch id
        self.authed = not self.server.auth    # type: ignore[attr-defined]
        self.rfile = self.request.makefile("rb")
        self.server.note_accept(self)         # type: ignore[attr-defined]

    def _send(self, obj: dict) -> None:
        data = (json.dumps(obj) + "\n").encode()
        with self.wlock:
            try:
                # xlint: allow-blocking-under-lock(single-writer frame serialization; the socket is the resource this lock guards)
                self.request.sendall(data)
            except OSError:
                pass

    def handle(self) -> None:
        store: MemoryStore = self.server.store  # type: ignore[attr-defined]
        for line in self.rfile:
            line = line.strip()
            if not line:
                continue
            try:
                req = json.loads(line)
            except json.JSONDecodeError:
                self._send({"ok": False, "error": "bad json"})
                continue
            rid = req.get("id")
            op = req.get("op")
            try:
                if op == "auth":
                    auth = self.server.auth  # type: ignore[attr-defined]
                    self.authed = (not auth) or (
                        (req.get("username"), req.get("password")) == auth)
                    self._send({"id": rid, "ok": self.authed})
                    continue
                if not self.authed:
                    self._send({"id": rid, "ok": False, "error": "unauthenticated"})
                    continue
                self._send({"id": rid, **self._dispatch(store, op, req)})
            except Exception as e:  # noqa: BLE001  # xlint: allow-broad-except(error is surfaced to the client as a protocol-level error frame)
                self._send({"id": rid, "ok": False, "error": str(e)})

    def _dispatch(self, store: MemoryStore, op: str, req: dict) -> dict:
        if op == "put":
            ok = store.put(req["key"], req["value"], req.get("ttl"),
                           create_only=req.get("create_only", False))
            return {"ok": ok}
        if op == "refresh":
            return {"ok": store.refresh(req["key"], req["ttl"])}
        if op == "get":
            v = store.get(req["key"])
            return {"ok": True, "value": v}
        if op == "get_prefix":
            return {"ok": True, "kvs": store.get_prefix(req["prefix"])}
        if op == "rm":
            return {"ok": store.rm(req["key"])}
        if op == "rm_prefix":
            n = store.rm_prefix(req["prefix"], req.get("guard_key"))
            return {"ok": True, "count": n}
        if op == "bulk_set":
            return {"ok": store.bulk_set(req["kvs"])}
        if op == "bulk_rm":
            return {"ok": True, "count": store.bulk_rm(req["keys"])}
        if op == "bulk_apply":
            return {"ok": store.bulk_apply(req.get("kvs", {}),
                                           req.get("rm_keys", []))}
        if op == "watch":
            cwid = req["watch_id"]
            prefix = req["prefix"]

            def push(events: list[KeyEvent], _prefix: str,
                     _cwid: int = cwid, _p: str = prefix) -> None:
                self._send({"event": "watch", "watch_id": _cwid, "prefix": _p,
                            "events": [{"type": e.type.value, "key": e.key,
                                        "value": e.value} for e in events]})

            self.watch_ids[cwid] = store.add_watch(prefix, push)
            return {"ok": True}
        if op == "unwatch":
            swid = self.watch_ids.pop(req["watch_id"], None)
            if swid is not None:
                store.remove_watch(swid)
            return {"ok": True}
        if op == "ping":
            return {"ok": True}
        return {"ok": False, "error": f"unknown op {op}"}

    def finish(self) -> None:
        store: MemoryStore = self.server.store  # type: ignore[attr-defined]
        for swid in self.watch_ids.values():
            store.remove_watch(swid)
        self.server.note_close(self)          # type: ignore[attr-defined]


class CoordinationServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    #: Accept-log bound: enough to audit a full fleet's post-outage
    #: reconnect storm without unbounded growth in long-lived servers.
    ACCEPT_LOG_CAPACITY = 4096

    def __init__(self, host: str = "0.0.0.0", port: int = 2379,
                 auth: Optional[tuple[str, str]] = None,
                 store: Optional[MemoryStore] = None,
                 accept_log_path: str = ""):
        self.store = store or MemoryStore()
        self.auth = auth
        # Connection bookkeeping + bounded accept log (timestamps of
        # every accepted connection): the outage bench reads the accept
        # spread after a restart to verify recovery is storm-free.
        self._conn_lock = make_lock("coord_server.conns", order=38)  # lock-order: 38
        self._conns: set = set()
        self.accept_log: list[float] = []
        self._accept_log_path = accept_log_path
        super().__init__((host, port), _Conn)

    @property
    def port(self) -> int:
        return self.server_address[1]

    def note_accept(self, conn) -> None:
        ts = time.time()
        with self._conn_lock:
            self._conns.add(conn)
            self.accept_log.append(ts)
            if len(self.accept_log) > self.ACCEPT_LOG_CAPACITY:
                del self.accept_log[:len(self.accept_log)
                                    - self.ACCEPT_LOG_CAPACITY]
        if self._accept_log_path:
            try:
                with open(self._accept_log_path, "a") as f:
                    f.write(f"{ts:.6f}\n")
            except OSError:
                pass
        logger.debug("accepted coordination connection (%d live)",
                     len(self._conns))

    def note_close(self, conn) -> None:
        with self._conn_lock:
            self._conns.discard(conn)

    def start_background(self) -> threading.Thread:
        def _serve() -> None:
            try:
                self.serve_forever()
            except OSError:
                # kill() closes the listener out from under the poll
                # loop — that IS the simulated process death, not an
                # error worth a thread traceback.
                pass

        t = threading.Thread(target=_serve, name="coord-server",
                             daemon=True)
        t.start()
        return t

    def stop(self) -> None:
        self.shutdown()
        self.server_close()
        self.store.close()

    def kill(self) -> None:
        """Simulate abrupt process death (the chaos drills' killable
        hook): sever every live client connection mid-stream and close
        the listener WITHOUT the graceful teardown — clients see ECONNRESET
        exactly as if the process got SIGKILLed. The store is dropped
        with it (a restarted server starts empty, like a fresh
        process)."""
        # The LISTENER dies first: shutdown()'s serve_forever poll
        # window (≤0.5s) would otherwise keep accepting the just-severed
        # clients' instant reconnects into zombie handler threads — a
        # half-dead server no real SIGKILL produces.
        try:
            self.socket.close()
        except OSError:
            pass
        self.shutdown()
        # Sever every connection accepted up to the listener close
        # (snapshot AFTER shutdown so a straggler accepted during the
        # race is included).
        with self._conn_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.request.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.request.close()
            except OSError:
                pass
        try:
            self.server_close()
        except OSError:
            pass
        self.store.close()


def main() -> None:
    p = argparse.ArgumentParser(description="xllm-service-tpu coordination server")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=2379)
    p.add_argument("--username", default="")
    p.add_argument("--password", default="")
    p.add_argument("--accept-log", default="",
                   help="append an epoch timestamp per accepted "
                        "connection to this file (outage-bench reconnect "
                        "spread audit)")
    args = p.parse_args()
    auth = (args.username, args.password) if args.username else None
    srv = CoordinationServer(args.host, args.port, auth=auth,
                             accept_log_path=args.accept_log)
    logger.info("coordination server listening on %s:%d", args.host, srv.port)
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        srv.stop()


if __name__ == "__main__":
    main()
