"""Backend-neutral coordination interface.

Capability parity with the reference's `EtcdClient`
(`scheduler/etcd_client/etcd_client.h:38`, SURVEY.md §2.7):

- `set(key, value)` plain put; `set(key, value, ttl)` = transaction
  {create-if-absent + put-with-lease} with a background keepalive retained
  until `release` (`etcd_client.cpp:105-120`).
- bulk upsert/delete (`etcd_client.cpp:122-137`).
- `get`, `get_prefix` (`etcd_client.cpp:174-219`).
- `rm`, `rm_prefix` — the reference guards bulk rm on still-being-master
  (`etcd_client.cpp:149-160`); we expose `rm_prefix(guard_key=...)`.
- `add_watch(prefix, cb)` recursive prefix watch with cancel
  (`etcd_client.cpp:221-259`).
- `create_if_absent` — master-election primitive (`scheduler.cpp:72-76`).

Values are opaque strings on every backend (memory, native C++ server,
etcd) and must survive JSON framing, so binary payloads are ASCII-wrapped
by the producer — the KV-index sync frames are base64(msgpack)
(`rpc/wire.py encode_kv_frame`); one frame key per master sync tick
replaces the per-block JSON values the index used to write.
"""

from __future__ import annotations

import abc
import enum
from dataclasses import dataclass
from typing import Callable, Iterable, Mapping, Optional


class WatchEventType(str, enum.Enum):
    PUT = "PUT"
    DELETE = "DELETE"


@dataclass
class KeyEvent:
    type: WatchEventType
    key: str          # full key (namespace stripped)
    value: str        # "" for DELETE


# Watch callback receives the batch of events for one revision plus the
# watched prefix (reference passes (response, prefix_len); we pre-strip).
WatchCallback = Callable[[list[KeyEvent], str], None]


class CoordinationClient(abc.ABC):
    """All keys are namespaced transparently (reference
    `common/utils.cpp:105-133` etcd namespace support)."""

    @abc.abstractmethod
    def set(self, key: str, value: str, ttl_s: Optional[float] = None,
            keepalive: bool = True) -> bool:
        """Put. With ttl_s, attach a lease; with keepalive, auto-refresh the
        lease until `release(key)` or client close."""

    @abc.abstractmethod
    def create_if_absent(self, key: str, value: str,
                         ttl_s: Optional[float] = None,
                         keepalive: bool = True) -> bool:
        """Atomic create; returns False if the key exists. Election primitive."""

    @abc.abstractmethod
    def get(self, key: str) -> Optional[str]: ...

    @abc.abstractmethod
    def get_prefix(self, prefix: str) -> dict[str, str]: ...

    @abc.abstractmethod
    def rm(self, key: str) -> bool: ...

    @abc.abstractmethod
    def rm_prefix(self, prefix: str, guard_key: Optional[str] = None) -> int:
        """Delete all keys under prefix. If guard_key is given, only proceed
        while guard_key exists (reference master-guarded bulk rm,
        `etcd_client.cpp:149-160`). Returns number deleted."""

    @abc.abstractmethod
    def bulk_set(self, kvs: Mapping[str, str]) -> bool: ...

    @abc.abstractmethod
    def bulk_rm(self, keys: Iterable[str]) -> int: ...

    def bulk_apply(self, kvs: Mapping[str, str],
                   rm_keys: Iterable[str]) -> bool:
        """Deletes + puts as ONE revision: watchers receive a single
        event batch (DELETEs first, then PUTs), so a multi-key state
        transition — e.g. the KV-index compaction's prune-legacy +
        install-full-frame — is applied atomically by replicas instead
        of exposing the half-pruned intermediate state. Backends that
        can't batch fall back to this default (rm then set, two
        revisions — correct but with a transient window; the
        memory/native backends override with a true single batch)."""
        self.bulk_rm(rm_keys)
        return self.bulk_set(kvs)

    def ping(self) -> bool:
        """Liveness probe of the coordination PLANE itself (not any key):
        the client-side evidence the degraded-mode health monitor
        classifies CONNECTED -> DEGRADED from. Backends that can lose
        connectivity override; the default is always-reachable."""
        return True

    @abc.abstractmethod
    def release(self, key: str) -> None:
        """Stop keepalive for a leased key (lease then expires naturally)."""

    @abc.abstractmethod
    def add_watch(self, prefix: str, cb: WatchCallback) -> int:
        """Watch a prefix recursively; returns a watch id."""

    @abc.abstractmethod
    def remove_watch(self, watch_id: int) -> None: ...

    @abc.abstractmethod
    def close(self) -> None: ...

    # Context-manager sugar.
    def __enter__(self) -> "CoordinationClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
