"""L3 coordination: etcd-equivalent metadata/liveness store.

Parity: reference `scheduler/etcd_client/` wrapping etcd-cpp-apiv3
(SURVEY.md §2.7). The framework defines a backend-neutral
:class:`CoordinationClient` interface with the same capability surface —
TTL leases + keepalive, create-if-absent transactions, bulk ops, typed
prefix gets, recursive prefix watches — and ships two backends:

- :mod:`.memory` — in-process store (hermetic tests, single-host deploys).
- :mod:`.client`/:mod:`.server` — a standalone coordination service over TCP
  (this repo's self-contained replacement for an external etcd cluster; an
  etcd backend can be slotted in behind the same interface where etcd is
  available).
"""

from .base import CoordinationClient, KeyEvent, WatchEventType
from .memory import InMemoryCoordination

__all__ = [
    "CoordinationClient",
    "KeyEvent",
    "WatchEventType",
    "InMemoryCoordination",
    "connect",
]


def connect(addr: str = "", namespace: str = "", username: str = "",
            password: str = "", reconnect_max_backoff_s: float = 2.0):
    """Create a coordination client: empty addr -> shared in-memory backend;
    'host:port' -> TCP client to a coordination server."""
    if not addr:
        return InMemoryCoordination.shared(namespace=namespace)
    from .client import TcpCoordinationClient

    return TcpCoordinationClient(
        addr, namespace=namespace, username=username, password=password,
        reconnect_max_backoff_s=reconnect_max_backoff_s)
