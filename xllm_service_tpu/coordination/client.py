"""TCP client for the standalone coordination server.

Implements :class:`CoordinationClient` over the newline-JSON protocol of
:mod:`.server`. A reader thread demultiplexes responses (by request id) from
watch pushes; a keepalive thread refreshes leased keys at ttl/3 — so if this
process dies, its leases lapse on the server and watchers see DELETEs
(etcd-lease parity; reference `etcd_client.cpp:105-120`).
"""

from __future__ import annotations

import itertools
import json
import queue
import socket
import threading
from typing import Optional

from .base import CoordinationClient, KeyEvent, WatchCallback, WatchEventType
from ..common.faults import FAULTS, FaultInjected
from ..common.metrics import COORDINATION_RECONNECTS_TOTAL
from ..devtools import lifecycle as _lifecycle
from ..devtools.locks import make_lock
from ..utils import get_logger, jittered_backoff

logger = get_logger(__name__)


class CoordinationError(RuntimeError):
    pass


class TcpCoordinationClient(CoordinationClient):
    """Resilience (the reference inherits this from the etcd client):
    on connection loss the client reconnects with backoff, re-authenticates,
    re-subscribes every watch, and the keepalive loop RE-CREATES leased keys
    whose refresh fails — so a coordination-server restart (in-memory state
    lost) converges back to the live fleet's registrations."""

    def __init__(self, addr: str, namespace: str = "",
                 username: str = "", password: str = "",
                 timeout_s: float = 10.0,
                 reconnect_max_backoff_s: float = 2.0):
        host, _, port = addr.rpartition(":")
        self._addr = (host or "127.0.0.1", int(port))
        self._auth = (username, password) if username else None
        # Reconnect backoff cap; each attempt's delay is exponential AND
        # randomized (jittered_backoff) so a fleet of clients that lost
        # the same server does not retry in lockstep — the reconnect
        # storm the degraded-mode recovery path must avoid.
        self._reconnect_max_backoff_s = max(0.1, reconnect_max_backoff_s)
        # Client-side plane evidence for the degraded-mode health
        # monitor: False from the moment the reader detects connection
        # death until the full session (auth + watches + leases) is
        # re-established. Single-assignment bool (GIL-atomic), written
        # only by the reader thread after __init__.
        self.connected = True
        self.reconnects_total = 0
        self._wlock = make_lock("coord_client.write", order=30)  # lock-order: 30
        self._ns = namespace.strip("/")
        self._ids = itertools.count(1)
        # rid -> (event, response, connection generation it was sent on).
        self._pending: dict[int, tuple[threading.Event, dict, int]] = {}
        self._plock = make_lock("coord_client.pending", order=32)  # lock-order: 32
        self._watches: dict[int, tuple[str, WatchCallback]] = {}
        # wid -> keys (namespace-stripped) last known to exist under the
        # watch prefix; the reconnect resync diffs the server's current
        # state against this to synthesize the PUT/DELETE events that were
        # lost while the connection was down (list-then-watch).
        self._watch_known: dict[int, set[str]] = {}
        # key -> (ttl, last_value, create_only) so a failed refresh can
        # re-create with the ORIGINAL semantics (an election key must never
        # be re-asserted with a plain put — that would overwrite a new
        # winner and split-brain).
        self._keepalives: dict[str, tuple[float, str, bool]] = {}
        self._ka_lock = make_lock("coord_client.keepalives", order=34)  # lock-order: 34
        self._closed = threading.Event()
        self._timeout_s = timeout_s
        # Connection generation, bumped under _wlock with each (re)connect;
        # lets reconnect fail exactly the calls sent on dead connections.
        self._gen = 0
        # Watch callbacks run on a DEDICATED dispatcher thread, fed FIFO
        # from the reader (one queue + one consumer = delivery order
        # preserved, which the replica frame-log apply depends on). They
        # must NOT run on the reader thread itself: a callback that makes
        # a coordination call — the master-election takeover does exactly
        # this (`scheduler._on_master_event` -> `create_if_absent`) —
        # would wait on a response only the reader can deliver, while the
        # reader waits inside the callback. The server had already applied
        # the write, so the deadlock's timeout left the caller believing
        # the election failed while its key sat in the store unrefreshed.
        self._watch_q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._connect()
        self._reader = threading.Thread(target=self._read_loop,
                                        name="coord-reader", daemon=True)
        self._reader.start()
        self._watch_thread = threading.Thread(target=self._watch_loop,
                                              name="coord-watch", daemon=True)
        self._watch_thread.start()
        self._ka_thread = threading.Thread(target=self._keepalive_loop,
                                           name="coord-ka", daemon=True)
        self._ka_thread.start()
        if self._auth:
            resp = self._call({"op": "auth", "username": username,
                               "password": password})
            if not resp.get("ok"):
                raise CoordinationError("coordination auth failed")
        # Connectivity check (reference pings with a PUT of XLLM_PING,
        # `etcd_client.cpp:58-86`).
        if not self._call({"op": "ping"}).get("ok"):
            raise CoordinationError("coordination ping failed")

    def _connect(self) -> None:
        FAULTS.check("coord.connect", addr=f"{self._addr[0]}:{self._addr[1]}")
        sock = socket.create_connection(self._addr, timeout=self._timeout_s)
        sock.settimeout(None)
        with self._wlock:
            self._sock = sock
            self._gen += 1
        self._rfile = sock.makefile("rb")

    def _reconnect_loop(self) -> bool:
        """Re-establish the connection + session state. Returns False if
        the client was closed while retrying."""
        attempt = 0
        while not self._closed.is_set():
            try:
                self._connect()
            except (OSError, FaultInjected):
                if self._closed.wait(jittered_backoff(
                        0.1, self._reconnect_max_backoff_s, attempt)):
                    return False
                attempt += 1
                continue
            logger.info("coordination reconnected to %s:%d", *self._addr)
            # Bounded session establishment: the auth/resync/ping reads
            # below must not block forever on a half-open socket (the
            # timeout is lifted again before normal — idle-tolerant —
            # watch reads resume).
            try:
                self._sock.settimeout(min(5.0, self._timeout_s))
            except OSError:
                continue
            if self._auth:
                # Synchronous auth exchange (we ARE the reader thread here,
                # so reading the response line directly is safe). A silent
                # auth failure would leave the session half-broken.
                self._send_raw({"op": "auth", "id": next(self._ids),
                                "username": self._auth[0],
                                "password": self._auth[1]})
                try:
                    line = self._rfile.readline()
                    if not json.loads(line).get("ok"):
                        logger.error("coordination re-auth REJECTED after "
                                     "reconnect; retrying connection")
                        self._sock.close()
                        if self._closed.wait(jittered_backoff(
                                0.1, self._reconnect_max_backoff_s, attempt)):
                            return False
                        attempt += 1
                        continue
                except (OSError, ValueError):
                    continue
            # Re-subscribe watches (server lost them with the connection).
            for wid, (prefix, _cb) in list(self._watches.items()):
                self._send_raw({"op": "watch", "id": next(self._ids),
                                "watch_id": wid,
                                "prefix": self._k(prefix)})
            # Re-create leased keys immediately, honoring create_only (an
            # election key lost to a new winner must NOT be clobbered).
            with self._ka_lock:
                items = list(self._keepalives.items())
            for key, (ttl, value, create_only) in items:
                self._send_raw({"op": "put", "id": next(self._ids),
                                "key": key, "value": value, "ttl": ttl,
                                "create_only": create_only})
            # List-then-watch resync: deliver the events lost during the
            # outage, so a coordination blip can't silently freeze instance
            # discovery (a registration or eviction that happened while we
            # were down would otherwise never reach the watchers).
            self._resync_watches()
            # Liveness check before declaring the session good: a connect
            # that raced a dying server can complete the TCP handshake in
            # the kernel's accept backlog with no process behind it — the
            # resync above then no-ops per watch and we would flag
            # `connected` on a socket the next write discovers is dead.
            resp = self._request_on_reader({"op": "ping"})
            if not resp or not resp.get("ok"):
                try:
                    self._sock.close()
                except OSError:
                    pass
                # Any call that raced onto this rejected connection
                # must not ride out its full timeout.
                self._fail_pending()
                if self._closed.wait(jittered_backoff(
                        0.1, self._reconnect_max_backoff_s, attempt)):
                    return False
                attempt += 1
                continue
            try:
                self._sock.settimeout(None)
            except OSError:
                continue
            self.reconnects_total += 1
            COORDINATION_RECONNECTS_TOTAL.inc()
            self.connected = True
            return True
        return False

    def _request_on_reader(self, req: dict) -> Optional[dict]:
        """Synchronous exchange issued FROM the reader thread (reconnect
        path — `_call` would deadlock waiting on ourselves). Watch pushes
        interleaved on the wire are enqueued to the dispatcher."""
        rid = next(self._ids)
        req["id"] = rid
        if not self._send_raw(req):
            return None
        try:
            for line in self._rfile:
                msg = json.loads(line)
                if msg.get("event") == "watch":
                    self._enqueue_watch(msg)
                    continue
                if msg.get("id") == rid:
                    return msg
                # A concurrent _call (e.g. the keepalive refreshing a
                # lease on the fresh connection) interleaved its response:
                # complete its waiter instead of dropping it, or the call
                # would stall for its full timeout.
                with self._plock:
                    waiter = self._pending.pop(msg.get("id"), None)
                if waiter is not None:
                    waiter[1].update(msg)
                    waiter[0].set()
        except (OSError, ValueError):
            return None
        return None

    def _resync_watches(self) -> None:
        for wid, (prefix, cb) in list(self._watches.items()):
            resp = self._request_on_reader(
                {"op": "get_prefix", "prefix": self._k(prefix)})
            if not resp or not resp.get("ok"):
                continue
            current = {self._strip(k): v
                       for k, v in resp.get("kvs", {}).items()}
            known = self._watch_known.get(wid, set())
            events = [KeyEvent(WatchEventType.DELETE, k, "")
                      for k in sorted(known - set(current))]
            events += [KeyEvent(WatchEventType.PUT, k, current[k])
                       for k in sorted(current)]
            self._watch_known[wid] = set(current)
            if not events:
                continue
            # Through the dispatcher queue like live pushes: resync events
            # must not run callbacks on the reader thread either (same
            # election-takeover deadlock), and FIFO keeps them ordered
            # before any pushes the fresh connection delivers next.
            self._watch_q.put((cb, events, prefix))

    def _send_raw(self, req: dict) -> bool:
        data = (json.dumps(req) + "\n").encode()
        try:
            with self._wlock:
                # xlint: allow-blocking-under-lock(single-writer frame serialization; the socket is the resource this lock guards)
                self._sock.sendall(data)
            return True
        except OSError:
            return False

    # ---- plumbing ----------------------------------------------------------
    def _k(self, key: str) -> str:
        return f"{self._ns}/{key}" if self._ns else key

    def _strip(self, key: str) -> str:
        return key[len(self._ns) + 1:] if self._ns else key

    def _read_loop(self) -> None:
        while not self._closed.is_set():
            self._read_one_connection()
            self.connected = False
            if self._closed.is_set():
                break
            # Close the dead socket so concurrent writers fail fast instead
            # of buffering into a black hole for their full call timeout.
            try:
                self._sock.close()
            except OSError:
                pass
            self._fail_pending()
            if not self._reconnect_loop():
                self._fail_pending()
                return
            # Calls issued while we were reconnecting went to the dead
            # socket; fail exactly those (generation check protects calls
            # already sent on the fresh connection).
            with self._wlock:
                cur_gen = self._gen
            self._fail_pending(older_than=cur_gen)
        self._fail_pending()

    def _fail_pending(self, older_than: Optional[int] = None) -> None:
        with self._plock:
            doomed = [rid for rid, (_, _, gen) in self._pending.items()
                      if older_than is None or gen < older_than]
            for rid in doomed:
                ev, resp, _ = self._pending.pop(rid)
                resp["ok"] = False
                resp["error"] = "connection closed"
                ev.set()

    def _enqueue_watch(self, msg: dict) -> None:
        """Reader-thread half of watch delivery: decode, update the
        known-key bookkeeping (kept on the reader thread so the resync
        diff never races the dispatcher), and queue for the dispatcher."""
        wid = msg["watch_id"]
        entry = self._watches.get(wid)
        if entry is None:
            return
        prefix, cb = entry
        events = [KeyEvent(WatchEventType(e["type"]),
                           self._strip(e["key"]), e.get("value", ""))
                  for e in msg.get("events", ())]
        known = self._watch_known.setdefault(wid, set())
        for e in events:
            if e.type == WatchEventType.PUT:
                known.add(e.key)
            else:
                known.discard(e.key)
        self._watch_q.put((cb, events, prefix))

    def _watch_loop(self) -> None:
        """Dispatcher half: the ONLY thread that runs watch callbacks, so
        callbacks may freely issue coordination calls (election takeover)
        without deadlocking the reader, and per-client delivery stays
        strictly ordered."""
        while True:
            item = self._watch_q.get()
            if item is None:
                return
            cb, events, prefix = item
            try:
                cb(events, prefix)
            except Exception:  # noqa: BLE001
                logger.exception("watch callback failed")

    def _read_one_connection(self) -> None:
        try:
            for line in self._rfile:
                msg = json.loads(line)
                if msg.get("event") == "watch":
                    self._enqueue_watch(msg)
                    continue
                rid = msg.get("id")
                with self._plock:
                    waiter = self._pending.pop(rid, None)
                if waiter is not None:
                    waiter[1].update(msg)
                    waiter[0].set()  # (ev, resp, gen)
        except (OSError, ValueError):
            pass

    def _call(self, req: dict, timeout_s: Optional[float] = None) -> dict:
        if self._closed.is_set():
            return {"ok": False, "error": "client closed"}
        if not self.connected:
            # Fail fast while the reader is mid-reconnect: a call sent
            # on a half-established socket would ride in _pending until
            # its full timeout (nothing fails it if the reconnect
            # attempt is later rejected), stalling the caller — which
            # during an outage is the scheduler's sync tick itself.
            return {"ok": False, "error": "disconnected"}
        rule = FAULTS.fire("coord.call", op=req.get("op"))
        if rule is not None:
            if rule.action == "disconnect":
                # Sever the connection (blip simulation): this call fails
                # and the reader thread drives reconnect + watch resync.
                try:
                    self._sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
            elif rule.action == "delay":
                import time as _t
                _t.sleep(rule.delay_s)
            else:
                return {"ok": False, "error": "fault injected"}
        rid = next(self._ids)
        req["id"] = rid
        ev, resp = threading.Event(), {}
        data = (json.dumps(req) + "\n").encode()
        try:
            with self._wlock:
                with self._plock:
                    self._pending[rid] = (ev, resp, self._gen)
                # xlint: allow-blocking-under-lock(single-writer frame serialization; registration + send must be atomic vs reconnect)
                self._sock.sendall(data)
        except OSError as e:
            with self._plock:
                self._pending.pop(rid, None)
            return {"ok": False, "error": str(e)}
        if not ev.wait(timeout_s if timeout_s is not None
                       else self._timeout_s):
            with self._plock:
                self._pending.pop(rid, None)
            return {"ok": False, "error": "timeout"}
        return resp

    def _keepalive_loop(self) -> None:
        # Fine-grained tick; each key refreshed at ttl/3 cadence (etcd
        # KeepAlive behavior).
        last_refresh: dict[str, float] = {}
        import time as _time

        while not self._closed.wait(0.02):
            now = _time.monotonic()
            with self._ka_lock:
                items = list(self._keepalives.items())
            for key, (ttl, value, create_only) in items:
                if now - last_refresh.get(key, 0.0) >= ttl / 3.0:
                    last_refresh[key] = now
                    ok = self._call({"op": "refresh", "key": key,
                                     "ttl": ttl}).get("ok", False)
                    if not ok and not self._closed.is_set():
                        # Key vanished (server restart / lease raced out):
                        # re-create with the ORIGINAL create_only semantics.
                        resp = self._call({"op": "put", "key": key,
                                           "value": value, "ttl": ttl,
                                           "create_only": create_only})
                        if create_only and resp and not resp.get("ok"):
                            # Someone else now holds the election key: we
                            # are no longer the owner — stop claiming it.
                            # (Owners detect demotion via verify_ownership.)
                            with self._ka_lock:
                                if self._keepalives.pop(key, None) \
                                        is not None:
                                    _lifecycle.note_release(
                                        "coord-lease", key=(id(self), key))

    # ---- CoordinationClient ------------------------------------------------
    def ping(self) -> bool:
        """Plane liveness probe (degraded-mode monitor evidence): a real
        round-trip, so a half-open connection reads as down — unlike
        `get`, whose None conflates missing-key with unreachable."""
        if not self.connected:
            return False
        # Short dedicated timeout: the probe runs on the scheduler-sync
        # cadence, and a probe that stalls for the full call timeout
        # would stall the sync tick itself — the probe's whole job is to
        # answer "up or not" faster than that.
        return bool(self._call({"op": "ping"},
                               timeout_s=min(1.0, self._timeout_s))
                    .get("ok"))

    def set(self, key, value, ttl_s=None, keepalive=True) -> bool:
        ok = self._call({"op": "put", "key": self._k(key), "value": value,
                         "ttl": ttl_s}).get("ok", False)
        if ok and ttl_s and keepalive:
            with self._ka_lock:
                if self._k(key) not in self._keepalives:
                    _lifecycle.note_acquire("coord-lease",
                                            key=(id(self), self._k(key)))
                self._keepalives[self._k(key)] = (ttl_s, value, False)
        return ok

    def create_if_absent(self, key, value, ttl_s=None, keepalive=True) -> bool:
        ok = self._call({"op": "put", "key": self._k(key), "value": value,
                         "ttl": ttl_s, "create_only": True}).get("ok", False)
        if ok and ttl_s and keepalive:
            with self._ka_lock:
                if self._k(key) not in self._keepalives:
                    _lifecycle.note_acquire("coord-lease",
                                            key=(id(self), self._k(key)))
                self._keepalives[self._k(key)] = (ttl_s, value, True)
        return ok

    def get(self, key) -> Optional[str]:
        resp = self._call({"op": "get", "key": self._k(key)})
        return resp.get("value") if resp.get("ok") else None

    def get_prefix(self, prefix) -> dict[str, str]:
        resp = self._call({"op": "get_prefix", "prefix": self._k(prefix)})
        if not resp.get("ok"):
            return {}
        return {self._strip(k): v for k, v in resp.get("kvs", {}).items()}

    def rm(self, key) -> bool:
        self.release(key)
        return self._call({"op": "rm", "key": self._k(key)}).get("ok", False)

    def rm_prefix(self, prefix, guard_key=None) -> int:
        resp = self._call({"op": "rm_prefix", "prefix": self._k(prefix),
                           "guard_key": self._k(guard_key) if guard_key else None})
        return resp.get("count", 0)

    def bulk_set(self, kvs) -> bool:
        return self._call({"op": "bulk_set",
                           "kvs": {self._k(k): v for k, v in kvs.items()}}).get("ok", False)

    def bulk_rm(self, keys) -> int:
        return self._call({"op": "bulk_rm",
                           "keys": [self._k(k) for k in keys]}).get("count", 0)

    def bulk_apply(self, kvs, rm_keys) -> bool:
        resp = self._call({"op": "bulk_apply",
                           "kvs": {self._k(k): v for k, v in kvs.items()},
                           "rm_keys": [self._k(k) for k in rm_keys]})
        if resp.get("ok"):
            return True
        if "unknown op" in str(resp.get("error", "")):
            # Legacy coordination server: fall back to two revisions
            # (correct, with the pre-batch transient window).
            return super().bulk_apply(kvs, rm_keys)
        return False

    def release(self, key) -> None:
        with self._ka_lock:
            if self._keepalives.pop(self._k(key), None) is not None:
                _lifecycle.note_release("coord-lease",
                                        key=(id(self), self._k(key)))

    def add_watch(self, prefix, cb) -> int:
        wid = next(self._ids)
        self._watches[wid] = (prefix, cb)
        self._call({"op": "watch", "watch_id": wid, "prefix": self._k(prefix)})
        return wid

    def remove_watch(self, watch_id) -> None:
        self._watches.pop(watch_id, None)
        self._watch_known.pop(watch_id, None)
        self._call({"op": "unwatch", "watch_id": watch_id})

    def close(self) -> None:
        if self._closed.is_set():
            return
        self._closed.set()
        with self._ka_lock:
            for k in self._keepalives:
                _lifecycle.note_release("coord-lease", key=(id(self), k))
            self._keepalives.clear()
        self._watch_q.put(None)   # dispatcher sentinel
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()
