"""In-process coordination backend.

The reference has no test double for etcd (SURVEY.md §4 calls this out as a
gap to fix); this backend is the hermetic-test lever *and* a real single-host
deployment mode. Semantics mirror etcd v3 as used by the reference:
TTL-leased keys expire unless kept alive; expiry fires DELETE watch events
(which is exactly how the reference detects dead instances and dead masters,
SURVEY.md §3.4-3.5).

Multiple clients attached to one :class:`MemoryStore` model multiple
processes sharing one etcd cluster; closing a client stops its keepalives so
its leased keys lapse — simulating process death in failure-injection tests.
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Iterable, Mapping, Optional

from .base import CoordinationClient, KeyEvent, WatchCallback, WatchEventType
from ..common.faults import FAULTS
from ..devtools import lifecycle as _lifecycle
from ..devtools.locks import make_lock


@dataclass
class _Entry:
    value: str
    expire_at: Optional[float] = None   # None = no lease


@dataclass
class _Watch:
    id: int
    prefix: str
    cb: WatchCallback


class MemoryStore:
    """The shared 'cluster'. Thread-safe; watch callbacks run on a dedicated
    dispatch thread (never under the store lock)."""

    _shared: dict[str, "MemoryStore"] = {}
    _shared_lock = make_lock("memory_store.shared", order=40)  # lock-order: 40

    @classmethod
    def shared(cls, name: str = "default") -> "MemoryStore":
        with cls._shared_lock:
            st = cls._shared.get(name)
            if st is None:
                st = cls()
                cls._shared[name] = st
            return st

    @classmethod
    def reset_shared(cls, name: str = "default") -> None:
        with cls._shared_lock:
            st = cls._shared.pop(name, None)
        if st is not None:
            st.close()

    def __init__(self, expiry_tick_s: float = 0.05):
        self._data: dict[str, _Entry] = {}
        self._watches: list[_Watch] = []
        self._next_watch_id = 1
        self._lock = make_lock("memory_store.data", order=44)  # lock-order: 44
        self._events: "queue.Queue[Optional[tuple[list[KeyEvent], str, WatchCallback]]]" = queue.Queue()
        self._closed = False
        self._dispatcher = threading.Thread(target=self._dispatch_loop,
                                            name="coord-dispatch", daemon=True)
        self._dispatcher.start()
        self._expiry_tick_s = expiry_tick_s
        self._expirer = threading.Thread(target=self._expiry_loop,
                                         name="coord-expiry", daemon=True)
        self._expirer.start()

    # ---- internals ---------------------------------------------------------
    def _dispatch_loop(self) -> None:
        while True:
            item = self._events.get()
            if item is None:
                return
            events, prefix, cb = item
            try:
                cb(events, prefix)
            except Exception:  # noqa: BLE001
                import logging

                logging.getLogger(__name__).exception("watch callback failed")

    def _expiry_loop(self) -> None:
        while not self._closed:
            time.sleep(self._expiry_tick_s)
            now = time.monotonic()
            expired: list[str] = []
            with self._lock:
                for k, e in self._data.items():
                    if e.expire_at is not None and e.expire_at <= now:
                        expired.append(k)
                for k in expired:
                    del self._data[k]
                if expired:
                    self._emit_locked([KeyEvent(WatchEventType.DELETE, k, "") for k in expired])

    def _emit_locked(self, events: list[KeyEvent]) -> None:
        for w in self._watches:
            hits = [e for e in events if e.key.startswith(w.prefix)]
            if hits:
                self._events.put((hits, w.prefix, w.cb))

    # ---- ops (called by clients, keys already namespaced) ------------------
    def put(self, key: str, value: str, ttl_s: Optional[float],
            create_only: bool = False) -> bool:
        with self._lock:
            exists = key in self._data
            if create_only and exists:
                e = self._data[key]
                # A leased key that has logically expired but not yet been
                # swept still blocks creation in etcd only until expiry; treat
                # sweep-lag as expired for correctness.
                if e.expire_at is None or e.expire_at > time.monotonic():
                    return False
            expire_at = time.monotonic() + ttl_s if ttl_s else None
            self._data[key] = _Entry(value, expire_at)
            self._emit_locked([KeyEvent(WatchEventType.PUT, key, value)])
            return True

    def refresh(self, key: str, ttl_s: float) -> bool:
        with self._lock:
            e = self._data.get(key)
            if e is None or e.expire_at is None:
                return False
            e.expire_at = time.monotonic() + ttl_s
            return True

    def get(self, key: str) -> Optional[str]:
        with self._lock:
            e = self._data.get(key)
            return e.value if e is not None else None

    def get_prefix(self, prefix: str) -> dict[str, str]:
        with self._lock:
            return {k: e.value for k, e in self._data.items() if k.startswith(prefix)}

    def rm(self, key: str) -> bool:
        with self._lock:
            if key not in self._data:
                return False
            del self._data[key]
            self._emit_locked([KeyEvent(WatchEventType.DELETE, key, "")])
            return True

    def rm_prefix(self, prefix: str, guard_key: Optional[str]) -> int:
        with self._lock:
            if guard_key is not None and guard_key not in self._data:
                return 0
            keys = [k for k in self._data if k.startswith(prefix)]
            for k in keys:
                del self._data[k]
            if keys:
                self._emit_locked([KeyEvent(WatchEventType.DELETE, k, "") for k in keys])
            return len(keys)

    def bulk_set(self, kvs: Mapping[str, str]) -> bool:
        with self._lock:
            events = []
            for k, v in kvs.items():
                self._data[k] = _Entry(v, None)
                events.append(KeyEvent(WatchEventType.PUT, k, v))
            if events:
                self._emit_locked(events)
            return True

    def bulk_rm(self, keys: Iterable[str]) -> int:
        with self._lock:
            removed = [k for k in keys if k in self._data]
            for k in removed:
                del self._data[k]
            if removed:
                self._emit_locked([KeyEvent(WatchEventType.DELETE, k, "") for k in removed])
            return len(removed)

    def bulk_apply(self, kvs: Mapping[str, str], rm_keys: Iterable[str]) -> bool:
        """Deletes + puts in ONE emission: watchers see one event batch
        (DELETEs first), so multi-key transitions apply atomically."""
        with self._lock:
            events = []
            for k in rm_keys:
                if k in self._data and k not in kvs:
                    del self._data[k]
                    events.append(KeyEvent(WatchEventType.DELETE, k, ""))
            for k, v in kvs.items():
                self._data[k] = _Entry(v, None)
                events.append(KeyEvent(WatchEventType.PUT, k, v))
            if events:
                self._emit_locked(events)
            return True

    def add_watch(self, prefix: str, cb: WatchCallback) -> int:
        with self._lock:
            wid = self._next_watch_id
            self._next_watch_id += 1
            self._watches.append(_Watch(wid, prefix, cb))
            return wid

    def remove_watch(self, watch_id: int) -> None:
        with self._lock:
            self._watches = [w for w in self._watches if w.id != watch_id]

    def close(self) -> None:
        self._closed = True
        self._events.put(None)


class InMemoryCoordination(CoordinationClient):
    """A 'process handle' on a MemoryStore: owns keepalives + watches."""

    def __init__(self, store: Optional[MemoryStore] = None, namespace: str = ""):
        self._store = store or MemoryStore()
        self._ns = namespace.strip("/")
        # key -> ttl for keys this client keeps alive.
        self._keepalives: dict[str, float] = {}
        self._ka_lock = make_lock("memory_coord.keepalives", order=42)  # lock-order: 42
        self._watch_ids: list[int] = []
        self._closed = threading.Event()
        self._ka_thread = threading.Thread(target=self._keepalive_loop,
                                           name="coord-keepalive", daemon=True)
        self._ka_thread.start()

    @classmethod
    def shared(cls, name: str = "default", namespace: str = "") -> "InMemoryCoordination":
        return cls(MemoryStore.shared(name), namespace=namespace)

    @property
    def store(self) -> MemoryStore:
        return self._store

    def _k(self, key: str) -> str:
        return f"{self._ns}/{key}" if self._ns else key

    def _strip(self, key: str) -> str:
        return key[len(self._ns) + 1:] if self._ns else key

    def _keepalive_loop(self) -> None:
        # Refresh each leased key at ~ttl/3 cadence (etcd KeepAlive behavior,
        # reference retains `etcd::KeepAlive` handles in `keep_alives_`,
        # `etcd_client.h:160`).
        while not self._closed.wait(0.1):
            with self._ka_lock:
                items = list(self._keepalives.items())
            for key, ttl in items:
                self._store.refresh(key, ttl)

    # ---- CoordinationClient ------------------------------------------------
    def ping(self) -> bool:
        # Hermetic plane-outage simulation: a scripted `coord.outage`
        # fault fails the liveness probe, so the degraded-mode health
        # monitor can be drilled to DEGRADED/RECOVERING without a real
        # TCP coordination server to kill.
        if FAULTS.fire("coord.outage") is not None:
            return False
        return True

    def set(self, key, value, ttl_s=None, keepalive=True) -> bool:
        ok = self._store.put(self._k(key), value, ttl_s)
        if ok and ttl_s and keepalive:
            with self._ka_lock:
                if self._k(key) not in self._keepalives:
                    _lifecycle.note_acquire("coord-lease",
                                            key=(id(self), self._k(key)))
                self._keepalives[self._k(key)] = ttl_s
        return ok

    def create_if_absent(self, key, value, ttl_s=None, keepalive=True) -> bool:
        ok = self._store.put(self._k(key), value, ttl_s, create_only=True)
        if ok and ttl_s and keepalive:
            with self._ka_lock:
                if self._k(key) not in self._keepalives:
                    _lifecycle.note_acquire("coord-lease",
                                            key=(id(self), self._k(key)))
                self._keepalives[self._k(key)] = ttl_s
        return ok

    def get(self, key):
        return self._store.get(self._k(key))

    def get_prefix(self, prefix):
        raw = self._store.get_prefix(self._k(prefix))
        return {self._strip(k): v for k, v in raw.items()}

    def rm(self, key) -> bool:
        self.release(key)
        return self._store.rm(self._k(key))

    def rm_prefix(self, prefix, guard_key=None) -> int:
        return self._store.rm_prefix(
            self._k(prefix), self._k(guard_key) if guard_key else None)

    def bulk_set(self, kvs) -> bool:
        return self._store.bulk_set({self._k(k): v for k, v in kvs.items()})

    def bulk_rm(self, keys) -> int:
        return self._store.bulk_rm([self._k(k) for k in keys])

    def bulk_apply(self, kvs, rm_keys) -> bool:
        return self._store.bulk_apply({self._k(k): v for k, v in kvs.items()},
                                      [self._k(k) for k in rm_keys])

    def release(self, key) -> None:
        with self._ka_lock:
            if self._keepalives.pop(self._k(key), None) is not None:
                _lifecycle.note_release("coord-lease",
                                        key=(id(self), self._k(key)))

    def add_watch(self, prefix, cb) -> int:
        ns_prefix = self._k(prefix)

        def wrapped(events: list[KeyEvent], _raw_prefix: str) -> None:
            cb([KeyEvent(e.type, self._strip(e.key), e.value) for e in events], prefix)

        wid = self._store.add_watch(ns_prefix, wrapped)
        self._watch_ids.append(wid)
        return wid

    def remove_watch(self, watch_id) -> None:
        self._store.remove_watch(watch_id)
        if watch_id in self._watch_ids:
            self._watch_ids.remove(watch_id)

    def close(self) -> None:
        self._closed.set()
        with self._ka_lock:
            for k in self._keepalives:
                _lifecycle.note_release("coord-lease", key=(id(self), k))
            self._keepalives.clear()
        for wid in list(self._watch_ids):
            self._store.remove_watch(wid)
        self._watch_ids.clear()
