"""Coordination-plane health monitor: degraded-mode (static-stability)
serving through a total coordination outage.

The reference hangs all fleet liveness off etcd leases, so an etcd
outage lapses every instance lease and demotes the elected master — the
data plane collapses even though every engine is healthy. This monitor
is the decoupling: it classifies the plane CONNECTED -> DEGRADED ->
RECOVERING from **client-side evidence only** (consecutive failed
liveness pings across scheduler sync ticks — never lease loss, which
would conflate an outage with a lost election), and while the plane is
down the fleet keeps doing what it was doing:

- **census freeze** — lease-lapse verdicts and missed-lease sweeps stop
  producing SUSPECT/evict (`InstanceMgr` consults :meth:`degraded`);
  instance liveness falls back to direct heartbeat silence over the
  multiplexed telemetry sessions (`degraded_heartbeat_silence_s`): a
  silent-AND-lease-lapsed instance still dies, a chatty one never does;
- **sticky mastership under a fencing rule** — the elected master keeps
  serving and routing from last-known-good RCU snapshots but suspends
  ownership-*changing* actions (evictions, drains, flips, autoscaler
  enactment, LOADFRAME/KV-frame publishing) into the bounded
  :class:`HeldActionLog`. The stickiness applies ONLY while the plane is
  unreachable: a master that *observes* someone else holding the write
  lease still demotes immediately, and held actions are discarded — they
  never execute after demotion;
- **storm-free recovery** — on reconnect the monitor holds RECOVERING
  for a deterministic per-entity jitter (:func:`entity_jitter`, so a
  fleet's re-assertions spread over `coordination_reconnect_jitter_s`
  instead of thundering the just-recovered plane), then fires
  ``on_recovered``: the scheduler re-asserts its registration,
  reconciles incarnations against what coordination now says, resyncs
  the frame log, and replays-or-discards each held action with the
  reason flight-recorded.

Thread contract: :meth:`tick` runs on the scheduler-sync thread only;
:meth:`degraded`/:meth:`hold`/:meth:`note_frozen` are called from the
reconcile and watch-dispatch threads — all state lives behind one leaf
lock (order 26), and transition callbacks fire OUTSIDE it (they call
back into subsystems with lower-ordered locks).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from hashlib import blake2b
from typing import Any, Callable, Optional

from ..common.flightrecorder import RECORDER
from ..common.metrics import (COORDINATION_CONNECTED,
                              COORDINATION_DEGRADED_SECONDS_TOTAL,
                              COORDINATION_FROZEN_EVENTS_TOTAL,
                              COORDINATION_HELD_ACTIONS)
from ..devtools import ownership as _ownership
from ..devtools.locks import make_lock
from ..utils import get_logger

logger = get_logger(__name__)

#: Plane states. DEGRADED and RECOVERING both keep the census frozen and
#: the holds engaged — RECOVERING only adds "the plane answers again,
#: wait out the per-entity jitter before re-asserting".
CONNECTED = "CONNECTED"
DEGRADED = "DEGRADED"
RECOVERING = "RECOVERING"


def entity_jitter(entity: str, window_s: float) -> float:
    """Deterministic per-entity delay in ``[0, window_s)``: every entity
    (master addr, agent instance name) computes its own slot from a hash
    of its identity, so post-outage re-assertions spread over the window
    without any coordination — which is the point: there is none."""
    if window_s <= 0.0:
        return 0.0
    h = int.from_bytes(blake2b(entity.encode(), digest_size=4).digest(),
                       "big")
    return (h / float(0xFFFFFFFF)) * window_s


@dataclass
class HeldAction:
    """One suspended ownership-changing action. Coalesced by
    ``(kind, key)`` — a 30 s outage must not grow the log by one entry
    per sync tick for the same suppressed publish."""

    kind: str
    key: str
    reason: str
    detail: dict[str, Any] = field(default_factory=dict)
    first_held_ms: int = 0
    count: int = 1

    def to_dict(self) -> dict[str, Any]:
        return {"kind": self.kind, "key": self.key, "reason": self.reason,
                "detail": dict(self.detail), "count": self.count,
                "first_held_ms": self.first_held_ms}


@_ownership.verify_state
class HeldActionLog:
    """Bounded, coalescing log of suspended actions, behind its own
    leaf lock (fed from the sync, reconcile, and watch-dispatch
    threads; drained by recovery on the sync thread)."""

    def __init__(self, capacity: int) -> None:
        self._lock = make_lock("coordination.heldlog", order=27)  # lock-order: 27
        self._capacity = max(1, int(capacity))
        self._items: dict[tuple[str, str], HeldAction] = {}
        self._order: list[tuple[str, str]] = []
        self._dropped = 0

    def hold(self, kind: str, key: str, reason: str = "",
             **detail: Any) -> HeldAction:
        with self._lock:
            slot = (kind, key)
            cur = self._items.get(slot)
            if cur is not None:
                cur.count += 1
                if detail:
                    cur.detail.update(detail)
                return cur
            action = HeldAction(kind=kind, key=key, reason=reason,
                                detail=dict(detail),
                                first_held_ms=int(time.time() * 1000))
            self._items[slot] = action
            self._order.append(slot)
            while len(self._order) > self._capacity:
                oldest = self._order.pop(0)
                self._items.pop(oldest, None)
                self._dropped += 1
            COORDINATION_HELD_ACTIONS.set(len(self._order))
            return action

    def drain(self) -> list[HeldAction]:
        with self._lock:
            out = [self._items[slot] for slot in self._order]
            self._items = {}
            self._order = []
            COORDINATION_HELD_ACTIONS.set(0)
            return out

    def depth(self) -> int:
        with self._lock:
            return len(self._order)

    def report(self) -> dict[str, Any]:
        with self._lock:
            return {"depth": len(self._order), "dropped": self._dropped,
                    "actions": [self._items[s].to_dict()
                                for s in self._order]}


@_ownership.verify_state
class CoordinationHealthMonitor:
    """CONNECTED -> DEGRADED -> RECOVERING classifier + held-action log.

    One instance per frontend, owned by the scheduler; ``entity`` is the
    frontend's rpc address (the per-entity jitter identity)."""

    def __init__(self, coord, options, entity: str = "",
                 on_degraded: Optional[Callable[[], None]] = None,
                 on_recovered: Optional[Callable[[], None]] = None) -> None:
        self._coord = coord
        self._entity = entity
        self._enabled = getattr(options, "coordination_degraded_mode",
                                "on") != "off"
        self._after_ticks = max(1, int(getattr(
            options, "coordination_degraded_after_ticks", 2)))
        self._jitter_window_s = float(getattr(
            options, "coordination_reconnect_jitter_s", 5.0))
        self._lock = make_lock("coordination.health", order=26)  # lock-order: 26
        self.held = HeldActionLog(
            int(getattr(options, "coordination_held_log_capacity", 256)))
        self._state = CONNECTED
        self._consec_failures = 0
        self._outage_started_mono = 0.0
        self._outage_started_unix = 0.0
        self._recover_at_mono = 0.0
        self._last_tick_mono = 0.0
        self._outages_total = 0
        self._frozen_events: dict[str, int] = {}
        # Transition hooks (construction-time; fired on the sync thread,
        # outside _lock — they call back into subsystems whose locks
        # order below this one).
        self.on_degraded = on_degraded
        self.on_recovered = on_recovered
        COORDINATION_CONNECTED.set(1)

    # ------------------------------------------------------------- queries
    def state(self) -> str:
        with self._lock:
            return self._state

    def degraded(self) -> bool:
        """True while the census freeze and action holds apply
        (DEGRADED *or* RECOVERING — holds release only once recovery
        has actually re-asserted)."""
        with self._lock:
            return self._state != CONNECTED

    def update_entity(self, entity: str) -> None:
        """Follow the scheduler's post-bind re-registration."""
        with self._lock:
            with _ownership.escape("post-bind re-registration: rebinds "
                                   "the init-only entity id once, before "
                                   "traffic"):
                self._entity = entity

    # ---------------------------------------------------------- transitions
    def tick(self) -> str:
        """One probe + state-machine step, on the scheduler-sync cadence.
        Returns the (possibly new) state; fires transition callbacks
        outside the lock."""
        ok = self._probe()
        now = time.monotonic()
        fire_degraded = fire_recovered = False
        outage_s = 0.0
        frozen_snapshot: dict[str, int] = {}
        with self._lock:
            prev = self._state
            if self._state != CONNECTED and self._last_tick_mono:
                COORDINATION_DEGRADED_SECONDS_TOTAL.inc(
                    max(0.0, now - self._last_tick_mono))
            self._last_tick_mono = now
            if ok:
                self._consec_failures = 0
                if self._state == DEGRADED:
                    # The plane answers again: hold RECOVERING for this
                    # entity's deterministic jitter slot so the fleet's
                    # re-assertions spread over the window.
                    self._state = RECOVERING
                    self._recover_at_mono = now + entity_jitter(
                        self._entity, self._jitter_window_s)
                elif self._state == RECOVERING \
                        and now >= self._recover_at_mono:
                    self._state = CONNECTED
                    fire_recovered = True
                    outage_s = now - self._outage_started_mono
                    frozen_snapshot = dict(self._frozen_events)
            else:
                self._consec_failures += 1
                if self._state == CONNECTED and self._enabled \
                        and self._consec_failures >= self._after_ticks:
                    self._state = DEGRADED
                    self._outage_started_mono = now
                    self._outage_started_unix = time.time()
                    self._outages_total += 1
                    fire_degraded = True
                elif self._state == RECOVERING:
                    # Reconnect didn't stick — back to DEGRADED, same
                    # outage (keep the original start for accounting).
                    self._state = DEGRADED
            state = self._state
        COORDINATION_CONNECTED.set(1 if ok else 0)
        if fire_degraded:
            logger.warning(
                "coordination plane DEGRADED after %d failed probes: "
                "census frozen, mastership sticky, ownership-changing "
                "actions held", self._after_ticks)
            RECORDER.record("coordination_degraded",
                            detail={"entity": self._entity,
                                    "failed_probes": self._after_ticks})
            if self.on_degraded is not None:
                self.on_degraded()
        if fire_recovered:
            logger.info(
                "coordination plane RECOVERED after %.1fs: replaying or "
                "discarding %d held actions", outage_s, self.held.depth())
            RECORDER.record("coordination_recovered",
                            detail={"entity": self._entity,
                                    "outage_seconds": round(outage_s, 3),
                                    "held_depth": self.held.depth(),
                                    "frozen_events": frozen_snapshot})
            if self.on_recovered is not None:
                self.on_recovered()
        if prev != state and not (fire_degraded or fire_recovered):
            logger.info("coordination plane %s -> %s", prev, state)
        return state

    def _probe(self) -> bool:
        # A client mid-reconnect short-circuits (connected=False) before
        # the ping round-trip; backends without connectivity loss report
        # connected implicitly.
        if not getattr(self._coord, "connected", True):
            return False
        try:
            return bool(self._coord.ping())
        except Exception:  # noqa: BLE001  # xlint: allow-broad-except(a probe that THROWS is exactly the evidence this monitor exists to classify)
            return False

    # ------------------------------------------------------ freeze / holds
    def hold(self, kind: str, key: str, reason: str = "",
             **detail: Any) -> None:
        """Suspend one ownership-changing action into the bounded log."""
        self.held.hold(kind, key, reason, **detail)

    def note_frozen(self, kind: str, key: str = "") -> None:
        """Count a census event ignored under the freeze (observability:
        the recovery bundle and /admin/coordination surface these)."""
        COORDINATION_FROZEN_EVENTS_TOTAL.labels(kind=kind).inc()
        with self._lock:
            self._frozen_events[kind] = self._frozen_events.get(kind, 0) + 1

    def drain_held(self) -> list[HeldAction]:
        return self.held.drain()

    def discard_held(self, reason: str) -> int:
        """Drop every held action WITHOUT replaying (demotion fencing:
        a master that lost the lease must never enact what it queued
        while it thought it was still the owner). Reasons are
        flight-recorded per action."""
        dropped = self.held.drain()
        for action in dropped:
            RECORDER.record("held_action_discarded",
                            detail={"kind": action.kind, "key": action.key,
                                    "held_reason": action.reason,
                                    "discard_reason": reason,
                                    "count": action.count})
        if dropped:
            logger.warning("discarded %d held actions: %s",
                           len(dropped), reason)
        return len(dropped)

    # ------------------------------------------------------------ reporting
    def report(self) -> dict[str, Any]:
        with self._lock:
            state = self._state
            out: dict[str, Any] = {
                "state": state,
                "enabled": self._enabled,
                "entity": self._entity,
                "consecutive_failures": self._consec_failures,
                "degraded_after_ticks": self._after_ticks,
                "reconnect_jitter_s": self._jitter_window_s,
                "outages_total": self._outages_total,
                "frozen_events": dict(self._frozen_events),
            }
            if state != CONNECTED:
                out["outage_started_unix"] = self._outage_started_unix
                out["outage_seconds"] = round(
                    time.monotonic() - self._outage_started_mono, 3)
            if state == RECOVERING:
                out["recover_in_s"] = round(
                    max(0.0, self._recover_at_mono - time.monotonic()), 3)
        out["held"] = self.held.report()
        out["reconnects_total"] = getattr(self._coord,
                                          "reconnects_total", 0)
        return out
