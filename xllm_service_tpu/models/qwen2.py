"""Qwen2/2.5 family (BASELINE config 3: Qwen2-72B dynamic PD-ratio).

Architecturally the llama family with per-projection qkv biases
(`qkv_bias=True` in ModelConfig) and its own default dimensions; all forward
paths are shared with models/llama.py (the bias is applied inside
`_project_qkv` when present).
"""

from __future__ import annotations

from .base import ModelConfig, ModelFamily, register_model_family
from .llama import (
    LLAMA_STACKED_RULES,
    decode_forward,
    init_params,
    embed_forward,
    mixed_decode_chunk_forward,
    prefill_forward,
    verify_forward,
)


def qwen2_7b_config() -> ModelConfig:
    return ModelConfig(name="qwen2", vocab_size=152064, hidden_size=3584,
                       num_layers=28, num_heads=28, num_kv_heads=4,
                       head_dim=128, ffn_size=18944, rope_theta=1000000.0,
                       qkv_bias=True, max_context_len=32768)


def qwen2_72b_config() -> ModelConfig:
    return ModelConfig(name="qwen2", vocab_size=152064, hidden_size=8192,
                       num_layers=80, num_heads=64, num_kv_heads=8,
                       head_dim=128, ffn_size=29568, rope_theta=1000000.0,
                       qkv_bias=True, max_context_len=32768)


register_model_family(ModelFamily(
    name="qwen2",
    init_params=init_params,
    prefill_forward=prefill_forward,
    decode_forward=decode_forward,
    sharding_rules=LLAMA_STACKED_RULES,
    verify_forward=verify_forward,
    embed_forward=embed_forward,
    mixed_decode_chunk_forward=mixed_decode_chunk_forward,
    supports_int8=True,
))
