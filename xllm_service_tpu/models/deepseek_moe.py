"""DeepSeek-V2-style MoE family (BASELINE config 4: expert-parallel decode).

Mixture-of-experts transformer with shared + routed experts and top-k
softmax gating, designed for **expert parallelism over the mesh `expert`
axis**: expert-stacked weights `[L, E, D, F]` are sharded on E, every token
is scored against all experts with a dense dispatch einsum, and the gated
combine contracts the expert dimension — GSPMD turns that contraction into
a psum over the expert axis (the TPU-idiomatic EP decode; no all-to-all
token shuffling needed at serving batch sizes).

Attention is **MLA (multi-head latent attention)** when
`kv_lora_rank > 0` (the DeepSeek-V2 design): the paged cache stores one
compressed latent `[kv_lora_rank ‖ rope_dim]` per token, the per-head K
up-projection is absorbed into the query, and the V up-projection is
applied after attention — so the framework's paged-attention ops run
unchanged over latents and the KV cache shrinks by the heads factor.
GQA+RoPE remains available for non-MLA configs. The first
`first_dense_layers` layers run a plain dense MLP (DeepSeek-V2 layer 0 in
real checkpoints, `modeling_deepseek.py` first_k_dense_replace); their
weights live in a separate `dense_mlp` subtree stacked over those layers
only, and the `moe` subtree stacks over the remaining layers — so real HF
checkpoints map position-for-position (models/loader.py
load_hf_deepseek_safetensors).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.attention import (
    decode_attention_step,
    prefill_attention,
    rms_norm,
    write_decode_kv,
    write_prefill_kv,
)
from ..parallel.mesh import AXIS_EXPERT, AXIS_MODEL
from ..parallel.sharding import ShardingRules
from .base import ModelConfig, ModelFamily, register_model_family
from .quant import quantized_einsum
from .llama import _project_qkv, _unembed

Params = dict

MOE_STACKED_RULES = ShardingRules(rules=[
    # int8-quant `/scale` leaves FIRST (first match wins; see
    # LLAMA_STACKED_RULES): a scale has the kernel's dims minus the
    # contraction (-2), sharded with the kernel's OUTPUT dim.
    (r"(k_up|v_up)/kernel/scale", P(None, AXIS_MODEL, None)),
    (r"(kv_down|k_rope)/kernel/scale", P()),
    (r"experts/(gate_proj|up_proj)/kernel/scale",
     P(None, AXIS_EXPERT, AXIS_MODEL)),                # [L, E, F]
    (r"experts/down_proj/kernel/scale", P(None, AXIS_EXPERT, None)),
    (r"(shared|dense_mlp)/(gate_proj|up_proj)/kernel/scale",
     P(None, AXIS_MODEL)),
    (r"(shared|dense_mlp)/down_proj/kernel/scale", P()),
    (r"(q_proj|k_proj|v_proj)/kernel/scale", P(None, AXIS_MODEL)),
    (r"o_proj/kernel/scale", P()),
    (r"lm_head/kernel/scale", P(AXIS_MODEL)),
    # MLA tensors: heads on the model axis; shared latent projections
    # replicated.
    (r"(k_up|v_up)/kernel", P(None, AXIS_MODEL, None, None)),  # [L, H, ., .]
    (r"(kv_down|k_rope)/kernel", P()),
    (r"kv_norm/scale", P()),
    (r"experts/(gate_proj|up_proj)/kernel",
     P(None, AXIS_EXPERT, None, AXIS_MODEL)),          # [L, E, D, F]
    (r"experts/down_proj/kernel",
     P(None, AXIS_EXPERT, AXIS_MODEL, None)),          # [L, E, F, D]
    (r"shared/(gate_proj|up_proj)/kernel", P(None, None, AXIS_MODEL)),
    (r"shared/down_proj/kernel", P(None, AXIS_MODEL, None)),
    (r"dense_mlp/(gate_proj|up_proj)/kernel", P(None, None, AXIS_MODEL)),
    (r"dense_mlp/down_proj/kernel", P(None, AXIS_MODEL, None)),
    (r"router/kernel", P()),
    (r"embed/embedding", P(AXIS_MODEL, None)),
    (r"(q_proj|k_proj|v_proj)/kernel", P(None, None, AXIS_MODEL)),
    (r"o_proj/kernel", P(None, AXIS_MODEL, None)),
    (r"lm_head/kernel", P(None, AXIS_MODEL)),
])


def deepseek_v2_lite_config() -> ModelConfig:
    """DeepSeek-V2-Lite with MLA: the paged cache stores one compressed
    latent (kv_lora_rank=512 + rope 64 = 576 dims) per token — advertised to
    the engine as num_kv_heads=1, head_dim=576."""
    return ModelConfig(name="deepseek_moe", vocab_size=102400,
                       hidden_size=2048, num_layers=27, num_heads=16,
                       num_kv_heads=1, head_dim=576, ffn_size=10944,
                       rope_theta=10000.0, max_context_len=32768,
                       kv_lora_rank=512, qk_nope_head_dim=128,
                       qk_rope_head_dim=64, v_head_dim=128,
                       num_experts=64, num_experts_per_token=6,
                       num_shared_experts=2, moe_ffn_size=1408,
                       first_dense_layers=1)


def bench_moe_config() -> ModelConfig:
    """~3.5B-total / ~0.9B-active MLA+MoE bench shape — V2-Lite's exact
    layer geometry (dataclasses.replace keeps them locked together) cut
    to 12 layers / 32 experts / 32k vocab so it fits one v5e chip
    weight-only int8 with a latent KV pool: the single-chip datum for
    BASELINE config 4 (expert-parallel decode measures relative to it)."""
    import dataclasses
    return dataclasses.replace(deepseek_v2_lite_config(),
                               vocab_size=32768, num_layers=12,
                               max_context_len=4096, num_experts=32)


def tiny_moe_config(**kw) -> ModelConfig:
    defaults = dict(name="deepseek_moe", vocab_size=512, hidden_size=128,
                    num_layers=2, num_heads=4, num_kv_heads=2, head_dim=32,
                    ffn_size=256, max_context_len=512, num_experts=4,
                    num_experts_per_token=2, num_shared_experts=1,
                    moe_ffn_size=64, first_dense_layers=0)
    defaults.update(kw)
    return ModelConfig(**defaults)


def tiny_mla_config(**kw) -> ModelConfig:
    """Tiny MLA+MoE config: cache entry = 32 latent + 16 rope = 48 dims."""
    defaults = dict(name="deepseek_moe", vocab_size=512, hidden_size=128,
                    num_layers=2, num_heads=4, num_kv_heads=1, head_dim=48,
                    ffn_size=256, max_context_len=512,
                    kv_lora_rank=32, qk_nope_head_dim=32,
                    qk_rope_head_dim=16, v_head_dim=32,
                    num_experts=4, num_experts_per_token=2,
                    num_shared_experts=1, moe_ffn_size=64,
                    first_dense_layers=0)
    defaults.update(kw)
    return ModelConfig(**defaults)


def init_params(cfg: ModelConfig, rng: jax.Array) -> Params:
    keys = jax.random.split(rng, 16)
    D, L, E = cfg.hidden_size, cfg.num_layers, cfg.num_experts
    Hq, Hkv = cfg.q_size, cfg.kv_size
    Fe = cfg.moe_ffn_size
    # num_shared_experts == 0 (mixtral): no shared branch at all.
    Fs = cfg.moe_ffn_size * cfg.num_shared_experts

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(cfg.dtype)

    if cfg.kv_lora_rank > 0:
        # MLA projections (DeepSeek-V2): shared compressed latent + a
        # decoupled rope key; per-head up-projections absorbed at decode.
        H, dn, dr = cfg.num_heads, cfg.qk_nope_head_dim, cfg.qk_rope_head_dim
        dc, dv = cfg.kv_lora_rank, cfg.v_head_dim
        attn = {
            "q_proj": {"kernel": dense(keys[1], (L, D, H * (dn + dr)), D)},
            "kv_down": {"kernel": dense(keys[2], (L, D, dc), D)},
            "k_rope": {"kernel": dense(keys[3], (L, D, dr), D)},
            "kv_norm": {"scale": jnp.ones((L, dc), cfg.dtype)},
            "k_up": {"kernel": dense(keys[12], (L, H, dn, dc), dc)},
            "v_up": {"kernel": dense(keys[13], (L, H, dc, dv), dc)},
            "o_proj": {"kernel": dense(keys[4], (L, H * dv, D), H * dv)},
        }
    else:
        attn = {
            "q_proj": {"kernel": dense(keys[1], (L, D, Hq), D)},
            "k_proj": {"kernel": dense(keys[2], (L, D, Hkv), D)},
            "v_proj": {"kernel": dense(keys[3], (L, D, Hkv), D)},
            "o_proj": {"kernel": dense(keys[4], (L, Hq, D), Hq)},
        }

    Ld = cfg.first_dense_layers
    Lm = L - Ld                      # MoE layers (stacked separately)
    out = {
        "embed": {"embedding": dense(keys[0], (cfg.vocab_size, D), D)},
        "layers": {
            "input_norm": {"scale": jnp.ones((L, D), cfg.dtype)},
            **attn,
            "post_attn_norm": {"scale": jnp.ones((L, D), cfg.dtype)},
        },
        "moe": {
            "router": {"kernel": dense(keys[5], (Lm, D, E), D)
                       .astype(jnp.float32)},
            "experts": {
                "gate_proj": {"kernel": dense(keys[6], (Lm, E, D, Fe), D)},
                "up_proj": {"kernel": dense(keys[7], (Lm, E, D, Fe), D)},
                "down_proj": {"kernel": dense(keys[8], (Lm, E, Fe, D), Fe)},
            },
            **({"shared": {
                "gate_proj": {"kernel": dense(keys[9], (Lm, D, Fs), D)},
                "up_proj": {"kernel": dense(keys[10], (Lm, D, Fs), D)},
                "down_proj": {"kernel": dense(keys[11], (Lm, Fs, D), Fs)},
            }} if cfg.num_shared_experts > 0 else {}),
        },
        "final_norm": {"scale": jnp.ones((D,), cfg.dtype)},
        "lm_head": {"kernel": dense(jax.random.fold_in(rng, 99),
                                    (D, cfg.vocab_size), D)},
    }
    if Ld > 0:
        F = cfg.ffn_size
        k2 = jax.random.split(jax.random.fold_in(rng, 55), 3)
        out["dense_mlp"] = {
            "gate_proj": {"kernel": dense(k2[0], (Ld, D, F), D)},
            "up_proj": {"kernel": dense(k2[1], (Ld, D, F), D)},
            "down_proj": {"kernel": dense(k2[2], (Ld, F, D), F)},
        }
    return out


def _moe_mlp(lp: Params, x: jax.Array, cfg: ModelConfig) -> jax.Array:
    """x: [..., D] -> [..., D]. Dense dispatch: all experts score all
    tokens; the combine contracts the (sharded) expert axis."""
    orig_shape = x.shape
    x2 = x.reshape(-1, orig_shape[-1])                     # [T, D]
    # Router in f32 for stable softmax.
    logits = x2.astype(jnp.float32) @ lp["router"]["kernel"]   # [T, E]
    k = cfg.num_experts_per_token
    topv, topi = jax.lax.top_k(logits, k)
    gates_k = jax.nn.softmax(topv, axis=-1)                # [T, k]
    # Scatter the top-k gates back to a dense [T, E] map.
    gates = jnp.zeros_like(logits).at[
        jnp.arange(x2.shape[0])[:, None], topi].set(gates_k)

    g = quantized_einsum("td,edf->etf", x2,
                         lp["experts"]["gate_proj"]["kernel"])
    u = quantized_einsum("td,edf->etf", x2,
                         lp["experts"]["up_proj"]["kernel"])
    h = jax.nn.silu(g) * u                                 # [E, T, Fe]
    eo = quantized_einsum("etf,efd->etd", h,
                          lp["experts"]["down_proj"]["kernel"])
    routed = jnp.einsum("etd,te->td", eo.astype(jnp.float32),
                        gates).astype(x.dtype)

    if "shared" in lp:
        sg = quantized_einsum("td,df->tf", x2,
                              lp["shared"]["gate_proj"]["kernel"])
        su = quantized_einsum("td,df->tf", x2,
                              lp["shared"]["up_proj"]["kernel"])
        routed = routed + quantized_einsum(
            "tf,fd->td", jax.nn.silu(sg) * su,
            lp["shared"]["down_proj"]["kernel"]).astype(routed.dtype)
    return routed.reshape(orig_shape)


def _mla_attention(lp, cfg, h, mode, k_pages, v_pages, page_table,
                   prefix_lens, seq_lens, positions, context_lens):
    """mode: "prefill" | "decode" | "dense" (dense = no paged cache at
    all — the embeddings path; nothing is written)."""
    """MLA (DeepSeek-V2): the cache stores one [kv_lora_rank ‖ rope] latent
    per token; per-head K up-projection is absorbed into the query and the
    V up-projection applied after attention — so the existing paged
    attention ops run unchanged over latents (n_kv=1).

    Returns (attn_out flattened [..., H*dv], k_pages, v_pages)."""
    from ..ops.attention import apply_rope, paged_attention_xla

    H, dn = cfg.num_heads, cfg.qk_nope_head_dim
    dr, dc, dv = cfg.qk_rope_head_dim, cfg.kv_lora_rank, cfg.v_head_dim

    # Latent + decoupled rope key (one shared "kv head").
    c = quantized_einsum("...d,dc->...c", h, lp["kv_down"]["kernel"])
    c = rms_norm(c, lp["kv_norm"]["scale"], cfg.rms_eps)
    k_r = quantized_einsum("...d,dr->...r", h, lp["k_rope"]["kernel"])
    k_r = apply_rope(k_r[..., None, :], positions, cfg.rope_theta)[..., 0, :]
    entry = jnp.concatenate([c, k_r], axis=-1)[..., None, :]  # [..., 1, dc+dr]

    # Queries: nope part absorbed through the K up-projection.
    q = quantized_einsum("...d,df->...f", h, lp["q_proj"]["kernel"])
    q = q.reshape(*q.shape[:-1], H, dn + dr)
    q_nope, q_rope = q[..., :dn], q[..., dn:]
    q_rope = apply_rope(q_rope, positions, cfg.rope_theta)
    q_c = quantized_einsum("...hd,hdc->...hc", q_nope,
                           lp["k_up"]["kernel"])
    q_lat = jnp.concatenate([q_c, q_rope], axis=-1)   # [..., H, dc+dr]
    # True scale is over the uncompressed per-head key width.
    scale = 1.0 / ((dn + dr) ** 0.5)

    if mode == "dense":
        attn = prefill_attention(q_lat, entry, entry, None, None, None,
                                 jnp.zeros(h.shape[:1], jnp.int32),
                                 seq_lens, scale=scale)
    elif mode == "prefill":
        k_pages, v_pages = write_prefill_kv(k_pages, v_pages, entry, entry,
                                            page_table, prefix_lens, seq_lens)
        attn = prefill_attention(q_lat, entry, entry, k_pages, v_pages,
                                 page_table, prefix_lens, seq_lens,
                                 scale=scale)
    else:
        k_pages, v_pages = write_decode_kv(k_pages, v_pages, entry, entry,
                                           page_table, positions)
        attn = paged_attention_xla(q_lat, k_pages, v_pages, page_table,
                                   context_lens, scale=scale)
    # The weighted sum over [c ‖ k_rope] entries: keep the latent part,
    # apply the absorbed V up-projection per head.
    ctx = attn[..., :dc]                              # [..., H, dc]
    out = quantized_einsum("...hc,hcv->...hv", ctx,
                           lp["v_up"]["kernel"])
    return out.reshape(*out.shape[:-2], H * dv), k_pages, v_pages


def _dense_mlp(mp: Params, x: jax.Array) -> jax.Array:
    g = quantized_einsum("...d,df->...f", x, mp["gate_proj"]["kernel"])
    u = quantized_einsum("...d,df->...f", x, mp["up_proj"]["kernel"])
    return quantized_einsum("...f,fd->...d", jax.nn.silu(g) * u,
                            mp["down_proj"]["kernel"])


def _run_layers(params, cfg, x, kv_pages, mode, page_table, prefix_lens,
                seq_lens, positions, context_lens):
    """Unrolled layer loop with in-place KV writebacks (see
    models/llama.py for why not `lax.scan`)."""
    use_mla = cfg.kv_lora_rank > 0
    Ld = cfg.first_dense_layers
    dense = kv_pages is None            # embeddings: no cache at all
    for l in range(cfg.num_layers):
        lp = jax.tree.map(lambda a, _l=l: a[_l], params["layers"])
        h = rms_norm(x, lp["input_norm"]["scale"], cfg.rms_eps)
        k_pages, v_pages = (None, None) if dense else             (kv_pages[l, 0], kv_pages[l, 1])
        if use_mla:
            attn, k_pages, v_pages = _mla_attention(
                lp, cfg, h, "dense" if dense else mode, k_pages, v_pages,
                page_table, prefix_lens, seq_lens, positions, context_lens)
        else:
            q, k, v = _project_qkv(lp, h, cfg, positions)
            if dense:
                attn = prefill_attention(
                    q, k, v, None, None, None,
                    jnp.zeros(x.shape[:1], jnp.int32), seq_lens)
            elif mode == "prefill":
                k_pages, v_pages = write_prefill_kv(
                    k_pages, v_pages, k, v, page_table, prefix_lens,
                    seq_lens)
                attn = prefill_attention(q, k, v, k_pages, v_pages,
                                         page_table, prefix_lens, seq_lens)
            else:
                attn, k_pages, v_pages = decode_attention_step(
                    q, k, v, k_pages, v_pages, page_table, context_lens)
            attn = attn.reshape(*attn.shape[:-2], cfg.q_size)
        x = x + quantized_einsum("...f,fd->...d", attn,
                                 lp["o_proj"]["kernel"])
        h2 = rms_norm(x, lp["post_attn_norm"]["scale"], cfg.rms_eps)
        if l < Ld:
            x = x + _dense_mlp(
                jax.tree.map(lambda a, _l=l: a[_l], params["dense_mlp"]),
                h2)
        else:
            x = x + _moe_mlp(
                jax.tree.map(lambda a, _l=l - Ld: a[_l], params["moe"]),
                h2, cfg)
        if not dense:
            kv_pages = jax.lax.dynamic_update_index_in_dim(
                kv_pages, jnp.stack([k_pages, v_pages]), l, 0)
    return x, kv_pages


def prefill_forward(params, cfg, tokens, positions, kv_pages, page_table,
                    prefix_lens, seq_lens):
    x = params["embed"]["embedding"][tokens].astype(cfg.dtype)
    x, kv_pages = _run_layers(params, cfg, x, kv_pages, "prefill",
                              page_table, prefix_lens, seq_lens, positions,
                              None)
    idx = jnp.maximum(seq_lens - 1, 0)
    last = x[jnp.arange(x.shape[0]), idx]
    return _unembed(params, cfg, last), kv_pages


def decode_forward(params, cfg, tokens, positions, kv_pages, page_table,
                   context_lens):
    x = params["embed"]["embedding"][tokens].astype(cfg.dtype)
    x, kv_pages = _run_layers(params, cfg, x, kv_pages, "decode",
                              page_table, None, None, positions,
                              context_lens)
    return _unembed(params, cfg, x), kv_pages


def verify_forward(params, cfg, tokens, positions, kv_pages, page_table,
                   prefix_lens, seq_lens):
    """Speculative verify for the MoE family: the prefill body already
    handles short multi-token blocks against the paged cache (MLA or GQA);
    this returns per-position logits [B, S, V]."""
    x = params["embed"]["embedding"][tokens].astype(cfg.dtype)
    x, kv_pages = _run_layers(params, cfg, x, kv_pages, "prefill",
                              page_table, prefix_lens, seq_lens, positions,
                              None)
    return _unembed(params, cfg, x), kv_pages


def embed_forward(params, cfg, tokens, seq_lens):
    """Text embeddings (mean-pooled final hidden states): fully dense
    causal forward — no page pool is allocated or written."""
    B, S = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None, :],
                                 (B, S))
    x = params["embed"]["embedding"][tokens].astype(cfg.dtype)
    x, _ = _run_layers(params, cfg, x, None, "prefill", None,
                       jnp.zeros((B,), jnp.int32), seq_lens, positions,
                       None)
    from ..ops.attention import rms_norm as _rms
    x = _rms(x, params["final_norm"]["scale"], cfg.rms_eps)
    mask = (jnp.arange(S)[None, :] < seq_lens[:, None])[..., None]
    summed = jnp.sum(jnp.where(mask, x.astype(jnp.float32), 0.0), axis=1)
    return summed / jnp.maximum(seq_lens[:, None], 1)


register_model_family(ModelFamily(
    name="deepseek_moe",
    init_params=init_params,
    prefill_forward=prefill_forward,
    decode_forward=decode_forward,
    sharding_rules=MOE_STACKED_RULES,
    verify_forward=verify_forward,
    embed_forward=embed_forward,
    supports_int8=True,
))
