"""DeepSeek-V2-style MoE family (BASELINE config 4: expert-parallel decode).

Mixture-of-experts transformer with shared + routed experts and top-k
softmax gating, designed for **expert parallelism over the mesh `expert`
axis**: expert-stacked weights `[L, E, D, F]` are sharded on E, every token
is scored against all experts with a dense dispatch einsum, and the gated
combine contracts the expert dimension — GSPMD turns that contraction into
a psum over the expert axis (the TPU-idiomatic EP decode; no all-to-all
token shuffling needed at serving batch sizes).

Attention is GQA+RoPE as in the llama family (DeepSeek's MLA compression is
a follow-up optimization; the serving contract — paged KV, prefill/decode
programs — is identical). First-k-dense-layers is approximated as all-MoE
with a shared expert (`first_dense_layers=0`), which preserves the
compute/communication shape EP benchmarking cares about.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.attention import (
    paged_attention,
    prefill_attention,
    rms_norm,
    write_decode_kv,
    write_prefill_kv,
)
from ..parallel.mesh import AXIS_EXPERT, AXIS_MODEL
from ..parallel.sharding import ShardingRules
from .base import ModelConfig, ModelFamily, register_model_family
from .llama import _project_qkv, _unembed

Params = dict

MOE_STACKED_RULES = ShardingRules(rules=[
    (r"experts/(gate_proj|up_proj)/kernel",
     P(None, AXIS_EXPERT, None, AXIS_MODEL)),          # [L, E, D, F]
    (r"experts/down_proj/kernel",
     P(None, AXIS_EXPERT, AXIS_MODEL, None)),          # [L, E, F, D]
    (r"shared/(gate_proj|up_proj)/kernel", P(None, None, AXIS_MODEL)),
    (r"shared/down_proj/kernel", P(None, AXIS_MODEL, None)),
    (r"router/kernel", P()),
    (r"embed/embedding", P(AXIS_MODEL, None)),
    (r"(q_proj|k_proj|v_proj)/kernel", P(None, None, AXIS_MODEL)),
    (r"o_proj/kernel", P(None, AXIS_MODEL, None)),
    (r"lm_head/kernel", P(None, AXIS_MODEL)),
])


def deepseek_v2_lite_config() -> ModelConfig:
    return ModelConfig(name="deepseek_moe", vocab_size=102400,
                       hidden_size=2048, num_layers=27, num_heads=16,
                       num_kv_heads=16, head_dim=128, ffn_size=10944,
                       rope_theta=10000.0, max_context_len=32768,
                       num_experts=64, num_experts_per_token=6,
                       num_shared_experts=2, moe_ffn_size=1408,
                       first_dense_layers=0)


def tiny_moe_config(**kw) -> ModelConfig:
    defaults = dict(name="deepseek_moe", vocab_size=512, hidden_size=128,
                    num_layers=2, num_heads=4, num_kv_heads=2, head_dim=32,
                    ffn_size=256, max_context_len=512, num_experts=4,
                    num_experts_per_token=2, num_shared_experts=1,
                    moe_ffn_size=64, first_dense_layers=0)
    defaults.update(kw)
    return ModelConfig(**defaults)


def init_params(cfg: ModelConfig, rng: jax.Array) -> Params:
    keys = jax.random.split(rng, 12)
    D, L, E = cfg.hidden_size, cfg.num_layers, cfg.num_experts
    Hq, Hkv = cfg.q_size, cfg.kv_size
    Fe = cfg.moe_ffn_size
    Fs = cfg.moe_ffn_size * max(1, cfg.num_shared_experts)

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(cfg.dtype)

    return {
        "embed": {"embedding": dense(keys[0], (cfg.vocab_size, D), D)},
        "layers": {
            "input_norm": {"scale": jnp.ones((L, D), cfg.dtype)},
            "q_proj": {"kernel": dense(keys[1], (L, D, Hq), D)},
            "k_proj": {"kernel": dense(keys[2], (L, D, Hkv), D)},
            "v_proj": {"kernel": dense(keys[3], (L, D, Hkv), D)},
            "o_proj": {"kernel": dense(keys[4], (L, Hq, D), Hq)},
            "post_attn_norm": {"scale": jnp.ones((L, D), cfg.dtype)},
            "router": {"kernel": dense(keys[5], (L, D, E), D)
                       .astype(jnp.float32)},
            "experts": {
                "gate_proj": {"kernel": dense(keys[6], (L, E, D, Fe), D)},
                "up_proj": {"kernel": dense(keys[7], (L, E, D, Fe), D)},
                "down_proj": {"kernel": dense(keys[8], (L, E, Fe, D), Fe)},
            },
            "shared": {
                "gate_proj": {"kernel": dense(keys[9], (L, D, Fs), D)},
                "up_proj": {"kernel": dense(keys[10], (L, D, Fs), D)},
                "down_proj": {"kernel": dense(keys[11], (L, Fs, D), Fs)},
            },
        },
        "final_norm": {"scale": jnp.ones((D,), cfg.dtype)},
        "lm_head": {"kernel": dense(jax.random.fold_in(rng, 99),
                                    (D, cfg.vocab_size), D)},
    }


def _moe_mlp(lp: Params, x: jax.Array, cfg: ModelConfig) -> jax.Array:
    """x: [..., D] -> [..., D]. Dense dispatch: all experts score all
    tokens; the combine contracts the (sharded) expert axis."""
    orig_shape = x.shape
    x2 = x.reshape(-1, orig_shape[-1])                     # [T, D]
    # Router in f32 for stable softmax.
    logits = x2.astype(jnp.float32) @ lp["router"]["kernel"]   # [T, E]
    k = cfg.num_experts_per_token
    topv, topi = jax.lax.top_k(logits, k)
    gates_k = jax.nn.softmax(topv, axis=-1)                # [T, k]
    # Scatter the top-k gates back to a dense [T, E] map.
    gates = jnp.zeros_like(logits).at[
        jnp.arange(x2.shape[0])[:, None], topi].set(gates_k)

    g = jnp.einsum("td,edf->etf", x2, lp["experts"]["gate_proj"]["kernel"])
    u = jnp.einsum("td,edf->etf", x2, lp["experts"]["up_proj"]["kernel"])
    h = jax.nn.silu(g) * u                                 # [E, T, Fe]
    eo = jnp.einsum("etf,efd->etd", h, lp["experts"]["down_proj"]["kernel"])
    routed = jnp.einsum("etd,te->td", eo.astype(jnp.float32),
                        gates).astype(x.dtype)

    sg = jnp.einsum("td,df->tf", x2, lp["shared"]["gate_proj"]["kernel"])
    su = jnp.einsum("td,df->tf", x2, lp["shared"]["up_proj"]["kernel"])
    shared = jnp.einsum("tf,fd->td", jax.nn.silu(sg) * su,
                        lp["shared"]["down_proj"]["kernel"])
    return (routed + shared).reshape(orig_shape)


def _run_layers(params, cfg, x, kv_pages, mode, page_table, prefix_lens,
                seq_lens, positions, context_lens):
    """Unrolled layer loop with in-place KV writebacks (see
    models/llama.py for why not `lax.scan`)."""
    for l in range(cfg.num_layers):
        lp = jax.tree.map(lambda a, _l=l: a[_l], params["layers"])
        h = rms_norm(x, lp["input_norm"]["scale"], cfg.rms_eps)
        q, k, v = _project_qkv(lp, h, cfg, positions)
        k_pages, v_pages = kv_pages[l, 0], kv_pages[l, 1]
        if mode == "prefill":
            k_pages, v_pages = write_prefill_kv(
                k_pages, v_pages, k, v, page_table, prefix_lens, seq_lens)
            attn = prefill_attention(q, k, v, k_pages, v_pages, page_table,
                                     prefix_lens, seq_lens)
        else:
            k_pages, v_pages = write_decode_kv(k_pages, v_pages, k, v,
                                               page_table, positions)
            attn = paged_attention(q, k_pages, v_pages, page_table,
                                   context_lens)
        attn = attn.reshape(*attn.shape[:-2], cfg.q_size)
        x = x + jnp.einsum("...f,fd->...d", attn, lp["o_proj"]["kernel"])
        h2 = rms_norm(x, lp["post_attn_norm"]["scale"], cfg.rms_eps)
        x = x + _moe_mlp(lp, h2, cfg)
        kv_pages = jax.lax.dynamic_update_index_in_dim(
            kv_pages, jnp.stack([k_pages, v_pages]), l, 0)
    return x, kv_pages


def prefill_forward(params, cfg, tokens, positions, kv_pages, page_table,
                    prefix_lens, seq_lens):
    x = params["embed"]["embedding"][tokens].astype(cfg.dtype)
    x, kv_pages = _run_layers(params, cfg, x, kv_pages, "prefill",
                              page_table, prefix_lens, seq_lens, positions,
                              None)
    idx = jnp.maximum(seq_lens - 1, 0)
    last = x[jnp.arange(x.shape[0]), idx]
    return _unembed(params, cfg, last), kv_pages


def decode_forward(params, cfg, tokens, positions, kv_pages, page_table,
                   context_lens):
    x = params["embed"]["embedding"][tokens].astype(cfg.dtype)
    x, kv_pages = _run_layers(params, cfg, x, kv_pages, "decode",
                              page_table, None, None, positions,
                              context_lens)
    return _unembed(params, cfg, x), kv_pages


register_model_family(ModelFamily(
    name="deepseek_moe",
    init_params=init_params,
    prefill_forward=prefill_forward,
    decode_forward=decode_forward,
    sharding_rules=MOE_STACKED_RULES,
))
