"""Mixtral family (mistralai/Mixtral-8x7B style).

Structurally the deepseek_moe machinery with its switches set to the
Mixtral shape: standard GQA attention (``kv_lora_rank=0`` — the non-MLA
branch), every layer MoE (``first_dense_layers=0``), NO shared expert
(``num_shared_experts=0``), and top-2 routing with softmax over the
selected experts' logits — exactly `_moe_mlp`'s top-k-then-softmax
scheme. Expert-parallel decode (expert mesh axis) and int8/spec paths
compose as for deepseek.
"""

from __future__ import annotations

from .base import ModelConfig, ModelFamily, register_model_family
from .deepseek_moe import (
    MOE_STACKED_RULES,
    decode_forward,
    embed_forward,
    init_params,
    prefill_forward,
    verify_forward,
)


def mixtral_8x7b_config() -> ModelConfig:
    return ModelConfig(name="mixtral", vocab_size=32000, hidden_size=4096,
                       num_layers=32, num_heads=32, num_kv_heads=8,
                       head_dim=128, ffn_size=14336, rope_theta=1e6,
                       num_experts=8, num_experts_per_token=2,
                       num_shared_experts=0, moe_ffn_size=14336,
                       first_dense_layers=0, max_context_len=32768)


def mixtral_tiny_config(**kw) -> ModelConfig:
    defaults = dict(name="mixtral", vocab_size=512, hidden_size=128,
                    num_layers=2, num_heads=4, num_kv_heads=2, head_dim=32,
                    ffn_size=256, num_experts=4, num_experts_per_token=2,
                    num_shared_experts=0, moe_ffn_size=64,
                    first_dense_layers=0, max_context_len=512)
    defaults.update(kw)
    return ModelConfig(**defaults)


register_model_family(ModelFamily(
    name="mixtral",
    init_params=init_params,
    prefill_forward=prefill_forward,
    decode_forward=decode_forward,
    sharding_rules=MOE_STACKED_RULES,
    verify_forward=verify_forward,
    embed_forward=embed_forward,
    supports_int8=True,
))
