"""Qwen2-VL family (BASELINE config 5: multimodal EPD three-stage
disaggregation: encode / prefill / decode).

Components:
- **Vision encoder**: patch-embedding ViT with bidirectional attention,
  projected to the LM hidden size — this is the ENCODE stage, pinned to
  dedicated chips in EPD deployments (the reference only *claims* EPD,
  README.md:47, with no service code; the role + contract here are ours:
  InstanceType.ENCODE + the agent's /rpc/encode endpoint).
- **LM**: the qwen2 text stack. `prefill_forward` accepts optional
  `mm_embeds` which are spliced into positions whose token id equals
  `image_token_id` (the chat template's multimodal placeholder).

Decode is unchanged — visual content only affects prefill, which is why
EPD separates the encode stage: encoder FLOPs never contend with decode.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.attention import rms_norm
from ..parallel.mesh import AXIS_MODEL
from ..parallel.sharding import ShardingRules
from .base import ModelConfig, ModelFamily, VisionConfig, register_model_family
from . import llama as _llama
from . import qwen2 as _qwen2  # noqa: F401  (registers the text family)

Params = dict

IMAGE_TOKEN_ID = 151655   # Qwen2-VL's <|image_pad|> id (placeholder splice)

QWEN2_VL_RULES = ShardingRules(rules=[
    (r"vision/", P()),   # encoder replicated (small; pinned to its chips)
    *_llama.LLAMA_STACKED_RULES.rules,
])


def tiny_vl_config(**kw) -> ModelConfig:
    defaults = dict(
        name="qwen2_vl", vocab_size=512, hidden_size=128, num_layers=2,
        num_heads=4, num_kv_heads=2, head_dim=32, ffn_size=256,
        qkv_bias=True, max_context_len=512,
        vision=VisionConfig(image_size=28, patch_size=14, hidden_size=64,
                            num_layers=2, num_heads=4, out_tokens=4))
    defaults.update(kw)
    return ModelConfig(**defaults)


def init_params(cfg: ModelConfig, rng: jax.Array) -> Params:
    params = _llama.init_params(cfg, rng)
    v = cfg.vision
    assert v is not None, "qwen2_vl requires a VisionConfig"
    keys = jax.random.split(jax.random.fold_in(rng, 7), 8)
    Dv, Lv = v.hidden_size, v.num_layers
    patch_dim = 3 * v.patch_size * v.patch_size

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(cfg.dtype)

    n_patches = (v.image_size // v.patch_size) ** 2
    params["vision"] = {
        "patch_embed": {"kernel": dense(keys[0], (patch_dim, Dv), patch_dim)},
        "pos_embed": dense(keys[1], (n_patches, Dv), Dv),
        "layers": {
            "norm1": {"scale": jnp.ones((Lv, Dv), cfg.dtype)},
            "qkv": {"kernel": dense(keys[2], (Lv, Dv, 3 * Dv), Dv)},
            "proj": {"kernel": dense(keys[3], (Lv, Dv, Dv), Dv)},
            "norm2": {"scale": jnp.ones((Lv, Dv), cfg.dtype)},
            "fc1": {"kernel": dense(keys[4], (Lv, Dv, 4 * Dv), Dv)},
            "fc2": {"kernel": dense(keys[5], (Lv, 4 * Dv, Dv), 4 * Dv)},
        },
        "merger": {"kernel": dense(keys[6], (Dv, cfg.hidden_size), Dv)},
    }
    return params


def encode_images(params: Params, cfg: ModelConfig,
                  pixels: jax.Array) -> jax.Array:
    """pixels: [N, H, W, 3] -> visual embeddings [N, out_tokens, D_lm].

    The ENCODE stage: patchify → ViT (bidirectional) → average-pool groups
    of patches down to `out_tokens` → project to the LM width.
    """
    v = cfg.vision
    N = pixels.shape[0]
    p = v.patch_size
    grid = v.image_size // p
    # Patchify: [N, grid, p, grid, p, 3] -> [N, grid*grid, p*p*3].
    x = pixels.reshape(N, grid, p, grid, p, 3)
    x = x.transpose(0, 1, 3, 2, 4, 5).reshape(N, grid * grid, p * p * 3)
    x = x.astype(cfg.dtype) @ params["vision"]["patch_embed"]["kernel"]
    x = x + params["vision"]["pos_embed"][None, :, :]

    vp = params["vision"]["layers"]
    n_heads = v.num_heads
    hd = v.hidden_size // n_heads

    def layer(x, lp):
        h = rms_norm(x, lp["norm1"]["scale"], 1e-6)
        qkv = jnp.einsum("ntd,df->ntf", h, lp["qkv"]["kernel"])
        q, k, vv = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(*q.shape[:-1], n_heads, hd)
        k = k.reshape(*k.shape[:-1], n_heads, hd)
        vv = vv.reshape(*vv.shape[:-1], n_heads, hd)
        s = jnp.einsum("nqhd,nkhd->nhqk", q.astype(jnp.float32),
                       k.astype(jnp.float32)) / (hd ** 0.5)
        a = jnp.einsum("nhqk,nkhd->nqhd", jax.nn.softmax(s, axis=-1),
                       vv.astype(jnp.float32)).astype(x.dtype)
        a = a.reshape(*a.shape[:-2], v.hidden_size)
        x = x + jnp.einsum("ntd,df->ntf", a, lp["proj"]["kernel"])
        h2 = rms_norm(x, lp["norm2"]["scale"], 1e-6)
        m = jnp.einsum("ntd,df->ntf", h2, lp["fc1"]["kernel"])
        x = x + jnp.einsum("ntf,fd->ntd", jax.nn.gelu(m),
                           lp["fc2"]["kernel"])
        return x, None

    x, _ = jax.lax.scan(layer, x, vp)
    # Pool patches down to out_tokens visual tokens.
    T = x.shape[1]
    group = max(1, T // v.out_tokens)
    pooled = x[:, :group * v.out_tokens].reshape(
        N, v.out_tokens, group, v.hidden_size).mean(axis=2)
    return jnp.einsum("ntd,df->ntf", pooled,
                      params["vision"]["merger"]["kernel"])


def splice_mm_embeds(params: Params, cfg: ModelConfig, tokens: jax.Array,
                     mm_embeds: Optional[jax.Array],
                     image_token_id: Optional[int] = None) -> jax.Array:
    """Token embedding with placeholder positions replaced by visual
    embeddings. tokens [B, S]; mm_embeds [B, n_mm, D] (per-row visual
    tokens, consumed in order by each row's placeholder positions)."""
    x = params["embed"]["embedding"][tokens].astype(cfg.dtype)
    if mm_embeds is None:
        return x
    if image_token_id is None:
        image_token_id = cfg.image_token_id
    is_img = (tokens == image_token_id)
    # k-th placeholder in a row takes that row's k-th visual token.
    order = jnp.cumsum(is_img, axis=1) - 1
    order = jnp.clip(order, 0, mm_embeds.shape[1] - 1)
    gathered = jnp.take_along_axis(
        mm_embeds.astype(cfg.dtype), order[..., None], axis=1)
    return jnp.where(is_img[..., None], gathered, x)


def prefill_forward(params, cfg, tokens, positions, kv_pages, page_table,
                    prefix_lens, seq_lens, mm_embeds=None):
    """Text prefill with optional visual-embedding splice. Reuses the llama
    stacked-layer body by substituting the input embeddings."""
    if mm_embeds is None:
        return _llama.prefill_forward(params, cfg, tokens, positions,
                                      kv_pages, page_table, prefix_lens,
                                      seq_lens)
    # Splice, then run the llama layers on the substituted embeddings by
    # temporarily routing the embedding lookup through an identity table.
    x = splice_mm_embeds(params, cfg, tokens, mm_embeds)
    return _llama.prefill_from_embeddings(params, cfg, x, positions,
                                          kv_pages, page_table, prefix_lens,
                                          seq_lens)


register_model_family(ModelFamily(
    name="qwen2_vl",
    init_params=init_params,
    prefill_forward=prefill_forward,
    decode_forward=_llama.decode_forward,
    sharding_rules=QWEN2_VL_RULES,
))
