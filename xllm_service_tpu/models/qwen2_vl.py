"""Qwen2-VL family (BASELINE config 5: multimodal EPD three-stage
disaggregation: encode / prefill / decode).

Components:
- **Vision encoder**: patch-embedding ViT with bidirectional attention,
  projected to the LM hidden size — this is the ENCODE stage, pinned to
  dedicated chips in EPD deployments (the reference only *claims* EPD,
  README.md:47, with no service code; the role + contract here are ours:
  InstanceType.ENCODE + the agent's /rpc/encode endpoint).
- **LM**: the qwen2 text stack. `prefill_forward` accepts optional
  `mm_embeds` which are spliced into positions whose token id equals
  `image_token_id` (the chat template's multimodal placeholder).

Decode is unchanged — visual content only affects prefill, which is why
EPD separates the encode stage: encoder FLOPs never contend with decode.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import AXIS_MODEL
from ..parallel.sharding import ShardingRules
from .base import ModelConfig, ModelFamily, VisionConfig, register_model_family
from . import llama as _llama
from . import qwen2 as _qwen2  # noqa: F401  (registers the text family)

Params = dict

IMAGE_TOKEN_ID = 151655   # Qwen2-VL's <|image_pad|> id (placeholder splice)

QWEN2_VL_RULES = ShardingRules(rules=[
    (r"vision/", P()),   # encoder replicated (small; pinned to its chips)
    *_llama.LLAMA_STACKED_RULES.rules,
])


def tiny_vl_config(**kw) -> ModelConfig:
    defaults = dict(
        name="qwen2_vl", vocab_size=512, hidden_size=128, num_layers=2,
        num_heads=4, num_kv_heads=2, head_dim=32, ffn_size=256,
        qkv_bias=True, max_context_len=512,
        mrope_section=(4, 6, 6),       # t/h/w half-dims (sum = hd/2)
        vision=VisionConfig(image_size=28, patch_size=14, hidden_size=64,
                            num_layers=2, num_heads=4, out_tokens=4,
                            temporal_patch_size=1, spatial_merge_size=1))
    defaults.update(kw)
    return ModelConfig(**defaults)


def qwen2_vl_2b_config(**kw) -> ModelConfig:
    """Qwen2-VL-2B-Instruct shapes (HF config.json: 1536 hidden / 28
    layers / 12 heads / 2 kv / 8960 ffn, rope_theta 1e6,
    rope_scaling.mrope_section [16, 24, 24]; visual tower 1280×32,
    patch 14, 2×2 spatial merge, temporal patch 2). 224px inputs give
    (224/14/2)² = 64 visual tokens per image."""
    defaults = dict(
        name="qwen2_vl", vocab_size=151936, hidden_size=1536,
        num_layers=28, num_heads=12, num_kv_heads=2, head_dim=128,
        ffn_size=8960, qkv_bias=True, rope_theta=1_000_000.0,
        tie_embeddings=True, max_context_len=32768,
        mrope_section=(16, 24, 24),
        vision=VisionConfig(image_size=224, patch_size=14,
                            hidden_size=1280, num_layers=32, num_heads=16,
                            out_tokens=64, temporal_patch_size=2,
                            spatial_merge_size=2))
    defaults.update(kw)
    return ModelConfig(**defaults)


def init_params(cfg: ModelConfig, rng: jax.Array) -> Params:
    params = _llama.init_params(cfg, rng)
    v = cfg.vision
    assert v is not None, "qwen2_vl requires a VisionConfig"
    want = (v.image_size // v.patch_size // v.spatial_merge_size) ** 2
    assert v.out_tokens == want, (
        f"out_tokens={v.out_tokens} inconsistent with the patch grid / "
        f"merge size (expected {want}) — the engine pads mm uploads in "
        "out_tokens units")
    keys = jax.random.split(jax.random.fold_in(rng, 7), 10)
    Dv, Lv = v.hidden_size, v.num_layers
    patch_dim = 3 * v.temporal_patch_size * v.patch_size * v.patch_size
    Dm = Dv * v.spatial_merge_size ** 2   # merger's merged width

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(cfg.dtype)

    params["vision"] = {
        # Conv3d(3, Dv, kernel=(tps, p, p)) == linear over the flattened
        # (c, t, ph, pw) patch vector (loader reshapes the conv weight).
        "patch_embed": {"kernel": dense(keys[0], (patch_dim, Dv),
                                        patch_dim)},
        "layers": {
            "norm1": {"scale": jnp.ones((Lv, Dv), cfg.dtype),
                      "bias": jnp.zeros((Lv, Dv), cfg.dtype)},
            "qkv": {"kernel": dense(keys[2], (Lv, Dv, 3 * Dv), Dv),
                    "bias": jnp.zeros((Lv, 3 * Dv), cfg.dtype)},
            "proj": {"kernel": dense(keys[3], (Lv, Dv, Dv), Dv),
                     "bias": jnp.zeros((Lv, Dv), cfg.dtype)},
            "norm2": {"scale": jnp.ones((Lv, Dv), cfg.dtype),
                      "bias": jnp.zeros((Lv, Dv), cfg.dtype)},
            "fc1": {"kernel": dense(keys[4], (Lv, Dv, 4 * Dv), Dv),
                    "bias": jnp.zeros((Lv, 4 * Dv), cfg.dtype)},
            "fc2": {"kernel": dense(keys[5], (Lv, 4 * Dv, Dv), 4 * Dv),
                    "bias": jnp.zeros((Lv, Dv), cfg.dtype)},
        },
        # PatchMerger: LayerNorm(Dv) -> [merge² · Dv] -> GELU MLP -> D_lm.
        "merger": {
            "ln_q": {"scale": jnp.ones((Dv,), cfg.dtype),
                     "bias": jnp.zeros((Dv,), cfg.dtype)},
            "fc1": {"kernel": dense(keys[6], (Dm, Dm), Dm),
                    "bias": jnp.zeros((Dm,), cfg.dtype)},
            "fc2": {"kernel": dense(keys[7], (Dm, cfg.hidden_size), Dm),
                    "bias": jnp.zeros((cfg.hidden_size,), cfg.dtype)},
        },
    }
    return params


def _layer_norm(x, scale, bias, eps=1e-6):
    xf = x.astype(jnp.float32)
    mu = xf.mean(axis=-1, keepdims=True)
    var = ((xf - mu) ** 2).mean(axis=-1, keepdims=True)
    y = (xf - mu) * jax.lax.rsqrt(var + eps)
    return (y * scale.astype(jnp.float32)
            + bias.astype(jnp.float32)).astype(x.dtype)


def _vision_rope(grid: int, hd: int, theta: float) -> jax.Array:
    """2D rotary angles for a grid×grid patch map: the first hd/4 freqs
    rotate with the patch ROW, the next hd/4 with the COLUMN (HF
    VisionRotaryEmbedding: per-axis freq tables concatenated, then the
    pair duplicated to cover hd). Returns [T, hd] angles."""
    dim = hd // 2
    inv = 1.0 / (theta ** (jnp.arange(0, dim, 2, dtype=jnp.float32) / dim))
    pos = jnp.arange(grid, dtype=jnp.float32)
    f = pos[:, None] * inv[None, :]                      # [grid, hd/4]
    fh = jnp.repeat(f[:, None, :], grid, axis=1)         # rows
    fw = jnp.repeat(f[None, :, :], grid, axis=0)         # cols
    emb = jnp.concatenate([fh, fw], axis=-1).reshape(grid * grid, dim)
    return jnp.concatenate([emb, emb], axis=-1)          # [T, hd]


def _rotate_half(x):
    h = x.shape[-1] // 2
    return jnp.concatenate([-x[..., h:], x[..., :h]], axis=-1)


def _window_index(grid: int, win: int) -> jax.Array:
    """Window id per patch for win×win non-overlapping windows (real
    Qwen2.5-VL pads; inputs here are preprocessed to multiples)."""
    assert grid % win == 0, (
        f"window_size={win} must divide the {grid}-patch grid (window "
        "ids would silently collide across windows otherwise)")
    rows = jnp.arange(grid) // win
    cols = jnp.arange(grid) // win
    return (rows[:, None] * (grid // win) + cols[None, :]).reshape(-1)


def encode_images(params: Params, cfg: ModelConfig,
                  pixels: jax.Array) -> jax.Array:
    """pixels: [N, H, W, 3] -> visual embeddings [N, out_tokens, D_lm].

    The ENCODE stage, at Qwen2-VL checkpoint fidelity
    (`Qwen2VisionTransformer`; reference ships only the proto surface,
    `proto/CMakeLists.txt:18-37`): Conv3d-equivalent patch embed
    (temporal tile for still images), blocks = LayerNorm → fused-qkv
    attention with 2D rotary over the (row, col) patch grid → QuickGELU
    MLP, then the spatial PatchMerger down to out_tokens per image.
    `window_size > 0` masks attention to non-overlapping windows except
    the blocks listed in fullatt_block_indexes (Qwen2.5-VL)."""
    v = cfg.vision
    N = pixels.shape[0]
    p = v.patch_size
    grid = v.image_size // p
    # Patchify to (c, t, ph, pw)-ordered vectors matching the Conv3d
    # weight flatten; still images tile over the temporal patch.
    x = pixels.reshape(N, grid, p, grid, p, 3)
    x = x.transpose(0, 1, 3, 5, 2, 4).reshape(N, grid * grid, 3, 1, p, p)
    x = jnp.broadcast_to(
        x, (N, grid * grid, 3, v.temporal_patch_size, p, p)
    ).reshape(N, grid * grid, -1)
    x = x.astype(cfg.dtype) @ params["vision"]["patch_embed"]["kernel"]

    vp = params["vision"]["layers"]
    n_heads = v.num_heads
    hd = v.hidden_size // n_heads
    rope = _vision_rope(grid, hd, v.rope_theta)          # [T, hd]
    cos = jnp.cos(rope)[None, :, None, :]
    sin = jnp.sin(rope)[None, :, None, :]

    win_mask = None
    if v.window_size > 0:
        wid = _window_index(grid, v.window_size)
        win_mask = (wid[:, None] == wid[None, :])        # [T, T]

    def layer(x, lp, local: bool):
        h = _layer_norm(x, lp["norm1"]["scale"], lp["norm1"]["bias"])
        qkv = jnp.einsum("ntd,df->ntf", h, lp["qkv"]["kernel"]) \
            + lp["qkv"]["bias"]
        q, k, vv = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(*q.shape[:-1], n_heads, hd)
        k = k.reshape(*k.shape[:-1], n_heads, hd)
        vv = vv.reshape(*vv.shape[:-1], n_heads, hd)
        q = q * cos + _rotate_half(q) * sin
        k = k * cos + _rotate_half(k) * sin
        s = jnp.einsum("nqhd,nkhd->nhqk", q.astype(jnp.float32),
                       k.astype(jnp.float32)) / (hd ** 0.5)
        if local and win_mask is not None:
            s = jnp.where(win_mask[None, None], s, -1e30)
        a = jnp.einsum("nhqk,nkhd->nqhd", jax.nn.softmax(s, axis=-1),
                       vv.astype(jnp.float32)).astype(x.dtype)
        a = a.reshape(*a.shape[:-2], v.hidden_size)
        x = x + jnp.einsum("ntd,df->ntf", a, lp["proj"]["kernel"]) \
            + lp["proj"]["bias"]
        h2 = _layer_norm(x, lp["norm2"]["scale"], lp["norm2"]["bias"])
        m = jnp.einsum("ntd,df->ntf", h2, lp["fc1"]["kernel"]) \
            + lp["fc1"]["bias"]
        m = m * jax.nn.sigmoid(1.702 * m)                # QuickGELU
        x = x + jnp.einsum("ntf,fd->ntd", m, lp["fc2"]["kernel"]) \
            + lp["fc2"]["bias"]
        return x

    for l in range(v.num_layers):
        lp = jax.tree.map(lambda a, _l=l: a[_l], vp)
        x = layer(x, lp, local=(v.window_size > 0
                                and l not in v.fullatt_block_indexes))

    # PatchMerger: ln_q per patch, group m×m spatial neighbors, 2-layer
    # GELU MLP to the LM width.
    mg = params["vision"]["merger"]
    m_ = v.spatial_merge_size
    x = _layer_norm(x, mg["ln_q"]["scale"], mg["ln_q"]["bias"])
    g2 = grid // m_
    x = x.reshape(N, g2, m_, g2, m_, v.hidden_size)
    x = x.transpose(0, 1, 3, 2, 4, 5).reshape(N, g2 * g2, -1)
    x = jnp.einsum("ntd,df->ntf", x, mg["fc1"]["kernel"]) + mg["fc1"]["bias"]
    # HF's PatchMerger uses nn.GELU (exact erf), not the tanh approximation.
    x = jax.nn.gelu(x, approximate=False)
    return jnp.einsum("ntd,df->ntf", x, mg["fc2"]["kernel"]) \
        + mg["fc2"]["bias"]


def splice_mm_embeds(params: Params, cfg: ModelConfig, tokens: jax.Array,
                     mm_embeds: Optional[jax.Array],
                     image_token_id: Optional[int] = None) -> jax.Array:
    """Token embedding with placeholder positions replaced by visual
    embeddings. tokens [B, S]; mm_embeds [B, n_mm, D] (per-row visual
    tokens, consumed in order by each row's placeholder positions)."""
    x = params["embed"]["embedding"][tokens].astype(cfg.dtype)
    if mm_embeds is None:
        return x
    if image_token_id is None:
        image_token_id = cfg.image_token_id
    is_img = (tokens == image_token_id)
    # k-th placeholder in a row takes that row's k-th visual token.
    order = jnp.cumsum(is_img, axis=1) - 1
    order = jnp.clip(order, 0, mm_embeds.shape[1] - 1)
    gathered = jnp.take_along_axis(
        mm_embeds.astype(cfg.dtype), order[..., None], axis=1)
    return jnp.where(is_img[..., None], gathered, x)


def mrope_positions(tokens, image_token_id: int):
    """Host-side M-RoPE position ids for a prompt (HF
    `Qwen2VLForConditionalGeneration.get_rope_index` semantics for
    single-frame images; reference parity target for BASELINE config 5).

    Text runs advance all three axes (t/h/w) together from the running
    offset. An image-placeholder run of n tokens is a (sqrt(n), sqrt(n))
    merged grid: t stays at the offset, h/w sweep the grid rows/cols; the
    offset then advances by the grid side (max position + 1). Returns
    (pos [S, 3] int32, delta) where delta = next_position - len(tokens)
    is the constant the decode path adds to the sequence index.
    """
    import math

    toks = np.asarray(tokens)
    S = len(toks)
    pos = np.zeros((S, 3), np.int32)
    st = 0
    i = 0
    is_img = toks == image_token_id
    while i < S:
        j = i
        if is_img[i]:
            while j < S and is_img[j]:
                j += 1
            n = j - i
            g = max(1, int(round(math.sqrt(n))))   # grid width (square)
            h = np.arange(n, dtype=np.int32) // g  # row-major sweep;
            w = np.arange(n, dtype=np.int32) % g   # robust to ragged runs
            pos[i:j, 0] = st
            pos[i:j, 1] = st + h
            pos[i:j, 2] = st + w
            st += int(max(h[-1], w.max())) + 1
        else:
            while j < S and not is_img[j]:
                j += 1
            n = j - i
            pos[i:j, :] = (st + np.arange(n, dtype=np.int32))[:, None]
            st += n
        i = j
    return pos, int(st - S)


def prefill_forward(params, cfg, tokens, positions, kv_pages, page_table,
                    prefix_lens, seq_lens, mm_embeds=None):
    """Text prefill with optional visual-embedding splice. Reuses the llama
    stacked-layer body by substituting the input embeddings."""
    if mm_embeds is None:
        return _llama.prefill_forward(params, cfg, tokens, positions,
                                      kv_pages, page_table, prefix_lens,
                                      seq_lens)
    # Splice, then run the llama layers on the substituted embeddings by
    # temporarily routing the embedding lookup through an identity table.
    x = splice_mm_embeds(params, cfg, tokens, mm_embeds)
    return _llama.prefill_from_embeddings(params, cfg, x, positions,
                                          kv_pages, page_table, prefix_lens,
                                          seq_lens)


register_model_family(ModelFamily(
    name="qwen2_vl",
    init_params=init_params,
    prefill_forward=prefill_forward,
    decode_forward=_llama.decode_forward,
    sharding_rules=QWEN2_VL_RULES,
))
