"""Gemma family (google/gemma, gemma-2).

Same stacked-layer paged-KV machinery as the llama family — the
architectural deltas are config switches the shared body honors
(models/llama.py): GeGLU MLP (``act="gelu"``), embeddings scaled by
sqrt(hidden) (``embed_scale``), RMSNorm computing ``(1 + w)``
(``rms_unit_offset``), tied embeddings, rope theta 10000, wide heads
(head_dim 256 — still a Pallas lane-width multiple), and a final-logit
tanh softcap (``final_logit_softcap``). Registered exactly like qwen2:
the body genuinely branches on these fields, so no forward is
duplicated.

Gemma-2 adds (all config switches on the same shared body):
alternating sliding-window/global attention (``sliding_window`` +
``sliding_window_pattern=2`` — even layers local, odd global), ATTENTION
logit softcapping (``attn_logit_softcap=50.0``), an explicit query scale
(``query_pre_attn_scalar``), and sandwich norms (``sandwich_norms`` —
post-attention/pre-ffw/post-ffw layernorms). These route through the XLA
attention paths (ops/attention.py softcap/window kwargs); the
Pallas/ring/CP kernels decline them and the engine refuses a seq-axis
mesh for such configs.

Reference parity note: the reference service routes any family by model
id (`tokenizer/tokenizer_factory.cpp` decides by config); the engine
plane is ours to define (SURVEY.md §0).
"""

from __future__ import annotations

from .base import ModelConfig, ModelFamily, register_model_family
from .llama import (
    LLAMA_STACKED_RULES,
    decode_forward,
    embed_forward,
    init_params,
    mixed_decode_chunk_forward,
    prefill_forward,
    verify_forward,
)


def gemma_tiny_config(**kw) -> ModelConfig:
    """CPU-test scale with every gemma switch on."""
    defaults = dict(name="gemma", vocab_size=512, hidden_size=128,
                    num_layers=2, num_heads=4, num_kv_heads=2, head_dim=32,
                    ffn_size=256, rope_theta=10000.0, tie_embeddings=True,
                    act="gelu", embed_scale=True, rms_unit_offset=True,
                    final_logit_softcap=30.0, max_context_len=512)
    defaults.update(kw)
    return ModelConfig(**defaults)


def gemma2_tiny_config(**kw) -> ModelConfig:
    """CPU-test scale with every gemma-2 switch on (window small enough
    that tests exercise both the inside- and outside-window regimes)."""
    defaults = dict(name="gemma", vocab_size=512, hidden_size=128,
                    num_layers=4, num_heads=4, num_kv_heads=2, head_dim=32,
                    ffn_size=256, rope_theta=10000.0, tie_embeddings=True,
                    act="gelu", embed_scale=True, rms_unit_offset=True,
                    final_logit_softcap=30.0, attn_logit_softcap=50.0,
                    sliding_window=8, sliding_window_pattern=2,
                    query_pre_attn_scalar=24.0, sandwich_norms=True,
                    max_context_len=512)
    defaults.update(kw)
    return ModelConfig(**defaults)


def gemma2_9b_config() -> ModelConfig:
    return ModelConfig(name="gemma", vocab_size=256000, hidden_size=3584,
                       num_layers=42, num_heads=16, num_kv_heads=8,
                       head_dim=256, ffn_size=14336, rope_theta=10000.0,
                       tie_embeddings=True, act="gelu", embed_scale=True,
                       rms_unit_offset=True, final_logit_softcap=30.0,
                       attn_logit_softcap=50.0, sliding_window=4096,
                       sliding_window_pattern=2,
                       query_pre_attn_scalar=256.0, sandwich_norms=True,
                       max_context_len=8192)


def gemma_2b_config() -> ModelConfig:
    return ModelConfig(name="gemma", vocab_size=256000, hidden_size=2048,
                       num_layers=18, num_heads=8, num_kv_heads=1,
                       head_dim=256, ffn_size=16384, rope_theta=10000.0,
                       tie_embeddings=True, act="gelu", embed_scale=True,
                       rms_unit_offset=True, max_context_len=8192)


register_model_family(ModelFamily(
    name="gemma",
    init_params=init_params,
    prefill_forward=prefill_forward,
    decode_forward=decode_forward,
    sharding_rules=LLAMA_STACKED_RULES,
    verify_forward=verify_forward,
    embed_forward=embed_forward,
    mixed_decode_chunk_forward=mixed_decode_chunk_forward,
    supports_int8=True,
))
