"""Llama-3 family (functional JAX, stacked layers, paged KV).

Covers BASELINE configs 1-2 (Llama-3-8B single-instance and PD-disagg) and
the 70B north star. Architecture: RMSNorm, GQA attention with RoPE, SwiGLU
MLP, optional tied embeddings. Layers are stacked with a leading L dim and
executed with `lax.scan` — a single compiled layer body.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from ..ops.attention import (
    apply_rope,
    decode_attention_step,
    paged_attention,
    prefill_attention,
    rms_norm,
    write_prefill_kv,
)
from ..parallel.sharding import ShardingRules
from jax.sharding import PartitionSpec as P
from ..parallel.mesh import AXIS_MODEL
from .base import ModelConfig, ModelFamily, register_model_family
from .quant import quantized_einsum

Params = dict


# Stacked-layer sharding rules (leading L dim on every layer tensor).
# int8-quant `/scale` leaves come FIRST (first match wins): a scale is
# [L, out] — sharded with the kernel's output dim for column-parallel
# weights, replicated for row-parallel ones (whose sharded dim is the
# contraction the scale reduced over). The `q8` leaf has the kernel's own
# shape and inherits its spec via the plain `/kernel` patterns.
LLAMA_STACKED_RULES = ShardingRules(rules=[
    (r"embed/embedding", P(AXIS_MODEL, None)),
    (r"(q_proj|k_proj|v_proj|gate_proj|up_proj)/kernel/scale",
     P(None, AXIS_MODEL)),
    (r"(o_proj|down_proj)/kernel/scale", P()),
    (r"lm_head/kernel/scale", P(AXIS_MODEL)),
    (r"(q_proj|k_proj|v_proj)/kernel", P(None, None, AXIS_MODEL)),
    (r"(q_proj|k_proj|v_proj)/bias", P(None, AXIS_MODEL)),
    (r"o_proj/kernel", P(None, AXIS_MODEL, None)),
    (r"(gate_proj|up_proj)/kernel", P(None, None, AXIS_MODEL)),
    (r"down_proj/kernel", P(None, AXIS_MODEL, None)),
    (r"lm_head/kernel", P(None, AXIS_MODEL)),
])


def init_params(cfg: ModelConfig, rng: jax.Array) -> Params:
    """Random init (truncated-normal-ish scaled); bf16 leaves."""
    keys = jax.random.split(rng, 8)
    D, L = cfg.hidden_size, cfg.num_layers
    Hq, Hkv, hd, F = cfg.q_size, cfg.kv_size, cfg.head_dim, cfg.ffn_size

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(cfg.dtype)

    def norm_init(shape):
        # gemma stores w with the norm computing (1 + w): zeros == identity.
        return (jnp.zeros if cfg.rms_unit_offset else jnp.ones)(
            shape, cfg.dtype)

    params: Params = {
        "embed": {"embedding": dense(keys[0], (cfg.vocab_size, D), D)},
        "layers": {
            "input_norm": {"scale": norm_init((L, D))},
            "q_proj": {"kernel": dense(keys[1], (L, D, Hq), D)},
            "k_proj": {"kernel": dense(keys[2], (L, D, Hkv), D)},
            "v_proj": {"kernel": dense(keys[3], (L, D, Hkv), D)},
            "o_proj": {"kernel": dense(keys[4], (L, Hq, D), Hq)},
            "post_attn_norm": {"scale": norm_init((L, D))},
            "gate_proj": {"kernel": dense(keys[5], (L, D, F), D)},
            "up_proj": {"kernel": dense(keys[6], (L, D, F), D)},
            "down_proj": {"kernel": dense(keys[7], (L, F, D), F)},
        },
        "final_norm": {"scale": norm_init((D,))},
    }
    if cfg.qkv_bias:
        params["layers"]["q_proj"]["bias"] = jnp.zeros((L, Hq), cfg.dtype)
        params["layers"]["k_proj"]["bias"] = jnp.zeros((L, Hkv), cfg.dtype)
        params["layers"]["v_proj"]["bias"] = jnp.zeros((L, Hkv), cfg.dtype)
    if cfg.sandwich_norms:   # gemma-2: pre/post feed-forward norms
        params["layers"]["pre_ffw_norm"] = {"scale": norm_init((L, D))}
        params["layers"]["post_ffw_norm"] = {"scale": norm_init((L, D))}
    if not cfg.tie_embeddings:
        params["lm_head"] = {"kernel": dense(
            jax.random.fold_in(rng, 99), (D, cfg.vocab_size), D)}
    return params


def _project_qkv(lp: Params, x: jax.Array, cfg: ModelConfig,
                 positions: jax.Array):
    """x: [B, S, D] (or [B, D] for decode with S folded) -> q,k,v heads."""
    q = quantized_einsum("...d,df->...f", x, lp["q_proj"]["kernel"])
    k = quantized_einsum("...d,df->...f", x, lp["k_proj"]["kernel"])
    v = quantized_einsum("...d,df->...f", x, lp["v_proj"]["kernel"])
    if "bias" in lp["q_proj"]:
        q = q + lp["q_proj"]["bias"]
        k = k + lp["k_proj"]["bias"]
        v = v + lp["v_proj"]["bias"]
    q = q.reshape(*q.shape[:-1], cfg.num_heads, cfg.head_dim)
    k = k.reshape(*k.shape[:-1], cfg.num_kv_heads, cfg.head_dim)
    v = v.reshape(*v.shape[:-1], cfg.num_kv_heads, cfg.head_dim)
    q = apply_rope(q, positions, cfg.rope_theta, cfg.mrope_section)
    k = apply_rope(k, positions, cfg.rope_theta, cfg.mrope_section)
    return q, k, v


def _attn_opts(cfg: ModelConfig, layer: int) -> dict:
    """Per-layer attention kwargs for the gemma-2 extras: explicit query
    scale (query_pre_attn_scalar), score softcap, and the sliding window
    on local layers. Empty for every other family — keeping `scale=None`
    preserves the Pallas-kernel eligibility gates."""
    opts: dict = {}
    if cfg.query_pre_attn_scalar > 0:
        opts["scale"] = cfg.query_pre_attn_scalar ** -0.5
    if cfg.attn_logit_softcap > 0:
        opts["softcap"] = cfg.attn_logit_softcap
    if cfg.layer_is_local(layer):
        opts["window"] = cfg.sliding_window
    return opts


def _norm(x: jax.Array, scale: jax.Array, cfg: ModelConfig) -> jax.Array:
    """RMSNorm; the gemma family stores w with the norm computing
    (1 + w) (rms_unit_offset)."""
    if cfg.rms_unit_offset:
        scale = 1.0 + scale.astype(jnp.float32)
    return rms_norm(x, scale, cfg.rms_eps)


def _embed(params: Params, cfg: ModelConfig, tokens: jax.Array) -> jax.Array:
    x = params["embed"]["embedding"][tokens].astype(cfg.dtype)
    if cfg.embed_scale:   # gemma scales embeddings by sqrt(hidden)
        x = x * jnp.asarray(cfg.hidden_size ** 0.5, cfg.dtype)
    return x


def _mlp(lp: Params, x: jax.Array, cfg: ModelConfig) -> jax.Array:
    gate = quantized_einsum("...d,df->...f", x, lp["gate_proj"]["kernel"])
    up = quantized_einsum("...d,df->...f", x, lp["up_proj"]["kernel"])
    act = (jax.nn.gelu if cfg.act == "gelu" else jax.nn.silu)(gate)
    return quantized_einsum("...f,fd->...d", act * up,
                            lp["down_proj"]["kernel"])


def _attn_mlp_residual(lp: Params, x: jax.Array, attn: jax.Array,
                       cfg: ModelConfig) -> jax.Array:
    """Fold the attention output and the MLP into the residual stream.
    sandwich_norms (gemma-2) norms the attention/MLP OUTPUTS as well:
    x += post_attn_norm(o_proj(attn)); x += post_ffw_norm(mlp(pre_ffw_norm(x)))."""
    o = quantized_einsum("...f,fd->...d", attn, lp["o_proj"]["kernel"])
    if cfg.sandwich_norms:
        x = x + _norm(o, lp["post_attn_norm"]["scale"], cfg)
        h2 = _norm(x, lp["pre_ffw_norm"]["scale"], cfg)
        return x + _norm(_mlp(lp, h2, cfg),
                         lp["post_ffw_norm"]["scale"], cfg)
    x = x + o
    h2 = _norm(x, lp["post_attn_norm"]["scale"], cfg)
    return x + _mlp(lp, h2, cfg)


def _unembed(params: Params, cfg: ModelConfig, x: jax.Array) -> jax.Array:
    x = _norm(x, params["final_norm"]["scale"], cfg)
    if cfg.tie_embeddings:
        logits = jnp.einsum("...d,vd->...v", x, params["embed"]["embedding"])
    else:
        logits = quantized_einsum("...d,dv->...v", x,
                                  params["lm_head"]["kernel"])
    logits = logits.astype(jnp.float32)
    if cfg.final_logit_softcap > 0:   # gemma-2 style tanh capping
        cap = cfg.final_logit_softcap
        logits = cap * jnp.tanh(logits / cap)
    return logits


def prefill_forward(params: Params, cfg: ModelConfig,
                    tokens: jax.Array,        # [B, S] suffix token ids
                    positions: jax.Array,     # [B, S] absolute positions
                    kv_pages: jax.Array,      # [L, 2, P, n_kv, ps, hd]
                    page_table: jax.Array,    # [B, max_pages]
                    prefix_lens: jax.Array,   # [B] cached-prefix lengths
                    seq_lens: jax.Array,      # [B] valid suffix lengths
                    ) -> tuple[jax.Array, jax.Array]:
    """Returns (last-token logits [B, V], updated kv_pages)."""
    x = _embed(params, cfg, tokens)
    return prefill_from_embeddings(params, cfg, x, positions, kv_pages,
                                   page_table, prefix_lens, seq_lens)


def prefill_from_embeddings(params: Params, cfg: ModelConfig,
                            x: jax.Array, positions: jax.Array,
                            kv_pages: jax.Array, page_table: jax.Array,
                            prefix_lens: jax.Array, seq_lens: jax.Array,
                            all_logits: bool = False,
                            ) -> tuple[jax.Array, jax.Array]:
    """Prefill body over precomputed input embeddings (multimodal families
    splice visual tokens before calling this).

    Layers run as an unrolled Python loop with per-layer
    `dynamic_update_index_in_dim` KV writebacks — with the KV pool donated,
    XLA updates it in place. (A `lax.scan` whose ys re-stack the pool
    copies the entire KV cache every call — measured ~2x decode cost.)

    all_logits=True returns logits for EVERY position [B, S, V] (the
    speculative-decoding verify path needs per-position predictions);
    default returns only the last valid token's [B, V].
    """

    def layer_body(l, x, k_pages, v_pages):
        lp = jax.tree.map(lambda a: a[l], params["layers"])
        h = _norm(x, lp["input_norm"]["scale"], cfg)
        q, k, v = _project_qkv(lp, h, cfg, positions)
        k_pages, v_pages = write_prefill_kv(k_pages, v_pages, k, v,
                                            page_table, prefix_lens, seq_lens)
        attn = prefill_attention(q, k, v, k_pages, v_pages,
                                 page_table, prefix_lens, seq_lens,
                                 **_attn_opts(cfg, l))
        attn = attn.reshape(*attn.shape[:-2], cfg.q_size)
        x = _attn_mlp_residual(lp, x, attn, cfg)
        return x, k_pages, v_pages

    for l in range(cfg.num_layers):
        x, k_pages, v_pages = layer_body(l, x, kv_pages[l, 0], kv_pages[l, 1])
        kv_pages = jax.lax.dynamic_update_index_in_dim(
            kv_pages, jnp.stack([k_pages, v_pages]), l, 0)
    if all_logits:
        return _unembed(params, cfg, x), kv_pages
    # Last valid token's hidden state per row.
    idx = jnp.maximum(seq_lens - 1, 0)
    last = x[jnp.arange(x.shape[0]), idx]
    return _unembed(params, cfg, last), kv_pages


def embed_forward(params: Params, cfg: ModelConfig,
                  tokens: jax.Array,      # [B, S] padded token ids
                  seq_lens: jax.Array,    # [B] valid lengths
                  ) -> jax.Array:
    """Text embeddings: dense causal forward (no paged cache), final norm,
    mean-pool over valid positions -> [B, D] f32. Powers /v1/embeddings —
    which the reference stubs as "not support"
    (`http_service/service.cpp:500-517`)."""
    B, S = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None, :],
                                 (B, S))
    x = _embed(params, cfg, tokens)

    def layer_body(l, x):
        lp = jax.tree.map(lambda a, _l=l: a[_l], params["layers"])
        h = _norm(x, lp["input_norm"]["scale"], cfg)
        q, k, v = _project_qkv(lp, h, cfg, positions)
        attn = prefill_attention(q, k, v, None, None, None,
                                 jnp.zeros((B,), jnp.int32), seq_lens,
                                 **_attn_opts(cfg, l))
        attn = attn.reshape(*attn.shape[:-2], cfg.q_size)
        return _attn_mlp_residual(lp, x, attn, cfg)

    for l in range(cfg.num_layers):
        x = layer_body(l, x)
    x = _norm(x, params["final_norm"]["scale"], cfg)
    mask = (jnp.arange(S)[None, :] < seq_lens[:, None])[..., None]
    summed = jnp.sum(jnp.where(mask, x.astype(jnp.float32), 0.0), axis=1)
    return summed / jnp.maximum(seq_lens[:, None], 1)


def verify_forward(params: Params, cfg: ModelConfig,
                   tokens: jax.Array,        # [B, S] block to verify
                   positions: jax.Array,     # [B, S]
                   kv_pages: jax.Array, page_table: jax.Array,
                   prefix_lens: jax.Array,   # [B] KV already in cache
                   seq_lens: jax.Array,      # [B] valid block lengths
                   ) -> tuple[jax.Array, jax.Array]:
    """Speculative-decoding verify: one forward over a short multi-token
    block per sequence (last accepted token + draft tokens), returning
    logits at EVERY block position [B, S, V] + updated KV. Structurally a
    batched mini-prefill against the paged cache."""
    x = _embed(params, cfg, tokens)
    return prefill_from_embeddings(params, cfg, x, positions, kv_pages,
                                   page_table, prefix_lens, seq_lens,
                                   all_logits=True)


def decode_forward(params: Params, cfg: ModelConfig,
                   tokens: jax.Array,         # [B] last sampled tokens
                   positions: jax.Array,      # [B] their absolute positions
                   kv_pages: jax.Array,       # [L, 2, P, n_kv, ps, hd]
                   page_table: jax.Array,     # [B, max_pages]
                   context_lens: jax.Array,   # [B] lens INCLUDING new token
                   rope_positions: jax.Array | None = None,
                   ) -> tuple[jax.Array, jax.Array]:
    """One decode step. Returns (logits [B, V], updated kv_pages).

    Unrolled layer loop + in-place KV writebacks (see
    prefill_from_embeddings for why not `lax.scan`). XLLM_KV_WRITEBACK
    selects the write strategy — numerically identical (parity-tested),
    perf A/B'd per backend:
    - "" (default): per-layer slice/stack/update pattern (round-1
      measured fastest on TPU among the XLA variants);
    - "scatter": write the token's K/V directly into the full [L, 2, ...]
      pool;
    - "fused": single Pallas kernel doing append + paged attention
      (ops/pallas_fused_decode_attention.py) — no separate scatter op,
      the HBM append DMA overlaps the page walk."""
    from ..ops.attention import kv_writeback_mode
    wb = kv_writeback_mode()
    scatter = wb == "scatter"
    page_size = kv_pages.shape[4]
    x = _embed(params, cfg, tokens)                            # [B, D]
    # M-RoPE (qwen2_vl): rope rotates by the multimodal position id
    # (sequence index + per-slot delta after image grids), while KV
    # writes/paging stay on the plain sequence index.
    if rope_positions is None:
        rope_positions = positions

    for l in range(cfg.num_layers):
        lp = jax.tree.map(lambda a, _l=l: a[_l], params["layers"])
        h = _norm(x, lp["input_norm"]["scale"], cfg)
        q, k, v = _project_qkv(lp, h, cfg, rope_positions)        # [B, H, hd]
        if scatter:
            page_idx = jnp.take_along_axis(
                page_table, (positions // page_size)[:, None], axis=1)[:, 0]
            slot = positions % page_size
            kv_pages = kv_pages.at[l, 0, page_idx, :, slot, :].set(
                k, mode="drop")
            kv_pages = kv_pages.at[l, 1, page_idx, :, slot, :].set(
                v, mode="drop")
            k_pages, v_pages = kv_pages[l, 0], kv_pages[l, 1]
            attn = paged_attention(q, k_pages, v_pages, page_table,
                                   context_lens, **_attn_opts(cfg, l))
        else:
            attn, k_pages, v_pages = decode_attention_step(
                q, k, v, kv_pages[l, 0], kv_pages[l, 1],
                page_table, context_lens, **_attn_opts(cfg, l))
        attn = attn.reshape(*attn.shape[:-2], cfg.q_size)
        x = _attn_mlp_residual(lp, x, attn, cfg)
        if wb == "slice":
            # Two static index updates: no [2, P, n_kv, ps, hd] stack
            # temp (l is a Python int — XLA sees static update-slices on
            # the donated pool).
            kv_pages = kv_pages.at[l, 0].set(k_pages)
            kv_pages = kv_pages.at[l, 1].set(v_pages)
        elif not scatter:
            kv_pages = jax.lax.dynamic_update_index_in_dim(
                kv_pages, jnp.stack([k_pages, v_pages]), l, 0)
    return _unembed(params, cfg, x), kv_pages


def mixed_decode_chunk_forward(
        params: Params, cfg: ModelConfig,
        dec_tokens: jax.Array,      # [B] last sampled tokens
        dec_positions: jax.Array,   # [B] their absolute positions
        chunk_tokens: jax.Array,    # [c] prefill sub-chunk (one sequence)
        chunk_positions: jax.Array,  # [c] absolute positions in its prompt
        kv_pages: jax.Array,        # [L, 2, P, n_kv, ps, hd]
        dec_pt: jax.Array,          # [B, max_pages]
        chunk_pt: jax.Array,        # [1, max_pages] the chunk seq's table
        dec_clens: jax.Array,       # [B] incl. the new token
        chunk_start: jax.Array,     # [] tokens of the prompt written so far
        chunk_valid: jax.Array,     # [] live tokens in this sub-chunk (<=c)
) -> tuple[jax.Array, jax.Array]:
    """Sarathi-style mixed step (SURVEY §7.3 hard-part 2; the reference's
    continuous-batching north star, BASELINE.json): one forward that
    decodes the running batch AND writes+attends a sub-chunk of one
    prefilling sequence. Every projection / MLP / unembed GEMM runs over
    the CONCATENATED token rows, so at serving batch sizes the decode
    rows ride the prefill chunk's weight stream instead of paying their
    own HBM pass — and decode never pauses while a long prompt installs.

    Returns (decode-row logits [B, V], updated kv_pages). The chunk rows'
    logits are discarded (mid-prompt positions; the FINAL chunk samples
    the first token through the normal install program). Padding rows
    (chunk_valid < c) write to the garbage page and attend nothing.
    """
    B = dec_tokens.shape[0]
    c = chunk_tokens.shape[0]
    x = jnp.concatenate([_embed(params, cfg, dec_tokens),
                         _embed(params, cfg, chunk_tokens)])   # [B+c, D]
    rope_pos = jnp.concatenate([dec_positions, chunk_positions])
    chunk_prefix = chunk_start[None]                           # [1]
    chunk_lens = chunk_valid[None]                             # [1]

    for l in range(cfg.num_layers):
        lp = jax.tree.map(lambda a, _l=l: a[_l], params["layers"])
        h = _norm(x, lp["input_norm"]["scale"], cfg)
        q, k, v = _project_qkv(lp, h, cfg, rope_pos)          # [B+c, H, hd]
        # Chunk KV lands in the pool FIRST (its own pages; decode rows
        # belong to different sequences, so order is immaterial there).
        k_pages, v_pages = write_prefill_kv(
            kv_pages[l, 0], kv_pages[l, 1], k[None, B:], v[None, B:],
            chunk_pt, chunk_prefix, chunk_lens)
        attn_d, k_pages, v_pages = decode_attention_step(
            q[:B], k[:B], v[:B], k_pages, v_pages, dec_pt, dec_clens,
            **_attn_opts(cfg, l))
        attn_c = prefill_attention(
            q[None, B:], k[None, B:], v[None, B:], k_pages, v_pages,
            chunk_pt, chunk_prefix, chunk_lens, **_attn_opts(cfg, l))
        attn = jnp.concatenate([attn_d, attn_c[0]])
        attn = attn.reshape(B + c, cfg.q_size)
        x = _attn_mlp_residual(lp, x, attn, cfg)
        kv_pages = kv_pages.at[l, 0].set(k_pages)
        kv_pages = kv_pages.at[l, 1].set(v_pages)
    return _unembed(params, cfg, x[:B]), kv_pages


register_model_family(ModelFamily(
    name="llama",
    init_params=init_params,
    prefill_forward=prefill_forward,
    decode_forward=decode_forward,
    sharding_rules=LLAMA_STACKED_RULES,
    verify_forward=verify_forward,
    embed_forward=embed_forward,
    mixed_decode_chunk_forward=mixed_decode_chunk_forward,
    supports_int8=True,
))
