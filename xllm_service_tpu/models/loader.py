"""Checkpoint loading: HuggingFace safetensors → stacked param pytrees.

The engine serves random-init weights by default (benchmarks); this module
loads real checkpoints. HF llama/qwen2-style weight names are mapped onto
the framework's stacked-layer pytree (leading L dim, see models/llama.py)
and optionally sharded straight onto the mesh (per-tensor `device_put`
with the family's GSPMD rules — no full-model host copy per device).

Orbax round-trip (`save_params`/`load_params`) covers framework-native
checkpoints (engine restarts, converted models).
"""

from __future__ import annotations

from pathlib import Path
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .base import ModelConfig
from ..utils import get_logger

logger = get_logger(__name__)

Params = dict

# HF per-layer name -> (our path, transpose?) for llama/qwen2 families.
# HF Linear stores [out, in]; our kernels are [in, out] -> transpose.
_HF_LAYER_MAP = {
    "input_layernorm.weight": ("input_norm/scale", False),
    "self_attn.q_proj.weight": ("q_proj/kernel", True),
    "self_attn.k_proj.weight": ("k_proj/kernel", True),
    "self_attn.v_proj.weight": ("v_proj/kernel", True),
    "self_attn.o_proj.weight": ("o_proj/kernel", True),
    "self_attn.q_proj.bias": ("q_proj/bias", False),
    "self_attn.k_proj.bias": ("k_proj/bias", False),
    "self_attn.v_proj.bias": ("v_proj/bias", False),
    "post_attention_layernorm.weight": ("post_attn_norm/scale", False),
    # gemma-2 sandwich norms (absent from other families' checkpoints).
    "pre_feedforward_layernorm.weight": ("pre_ffw_norm/scale", False),
    "post_feedforward_layernorm.weight": ("post_ffw_norm/scale", False),
    "mlp.gate_proj.weight": ("gate_proj/kernel", True),
    "mlp.up_proj.weight": ("up_proj/kernel", True),
    "mlp.down_proj.weight": ("down_proj/kernel", True),
}
_HF_TOP_MAP = {
    "model.embed_tokens.weight": ("embed/embedding", False),
    "model.norm.weight": ("final_norm/scale", False),
    "lm_head.weight": ("lm_head/kernel", True),
}


def _set_path(tree: dict, path: str, value) -> None:
    parts = path.split("/")
    node = tree
    for p in parts[:-1]:
        node = node.setdefault(p, {})
    node[parts[-1]] = value


def load_hf_llama_safetensors(ckpt_dir: str | Path, cfg: ModelConfig,
                              mesh=None, rules=None) -> Params:
    """Load an HF llama/qwen2 checkpoint directory (*.safetensors shards)
    into the stacked pytree. Missing lm_head falls back to tied embeddings
    semantics only if cfg.tie_embeddings is set."""
    from safetensors import safe_open

    ckpt_dir = Path(ckpt_dir)
    files = sorted(ckpt_dir.glob("*.safetensors"))
    if not files:
        raise FileNotFoundError(f"no .safetensors in {ckpt_dir}")

    L = cfg.num_layers
    # Collect per-layer tensors then stack along L.
    layer_acc: dict[str, list[Optional[np.ndarray]]] = {}
    tree: Params = {}
    seen = set()

    def place(name: str, tensor: np.ndarray) -> None:
        if name in _HF_TOP_MAP:
            path, transpose = _HF_TOP_MAP[name]
            _set_path(tree, path, np.ascontiguousarray(
                tensor.T if transpose else tensor))
            seen.add(name)
            return
        if not name.startswith("model.layers."):
            logger.warning("unmapped checkpoint tensor: %s", name)
            return
        rest = name[len("model.layers."):]
        idx_str, _, leaf = rest.partition(".")
        if leaf not in _HF_LAYER_MAP:
            logger.warning("unmapped layer tensor: %s", name)
            return
        idx = int(idx_str)
        path, transpose = _HF_LAYER_MAP[leaf]
        layer_acc.setdefault(path, [None] * L)[idx] = np.ascontiguousarray(
            tensor.T if transpose else tensor)
        seen.add(name)

    for f in files:
        with safe_open(str(f), framework="numpy") as sf:
            for name in sf.keys():
                place(name, sf.get_tensor(name))

    for path, tensors in layer_acc.items():
        missing = [i for i, t in enumerate(tensors) if t is None]
        if missing:
            raise ValueError(f"checkpoint missing layers {missing} for {path}")
        _set_path(tree, f"layers/{path}", np.stack(tensors))

    if "lm_head" not in tree and not cfg.tie_embeddings:
        # Tied checkpoints ship no lm_head; honor tying implicitly.
        logger.info("no lm_head in checkpoint; tying to embeddings")
        tree["lm_head"] = {"kernel": np.ascontiguousarray(
            tree["embed"]["embedding"].T)}

    return _finalize(tree, cfg, mesh, rules)


def _finalize(tree: Params, cfg: ModelConfig, mesh, rules) -> Params:
    """Cast to model dtype and (optionally) shard leaf-by-leaf."""
    if mesh is not None and rules is not None:
        from jax.sharding import NamedSharding

        from ..parallel.sharding import tree_specs

        specs = tree_specs(tree, rules)

        def put(leaf, spec):
            return jax.device_put(jnp.asarray(leaf, cfg.dtype),
                                  NamedSharding(mesh, spec))

        return jax.tree.map(put, tree, specs)
    return jax.tree.map(lambda a: jnp.asarray(a, cfg.dtype), tree)


# ------------------------------------------------------- MoE checkpoints ----
def load_hf_deepseek_safetensors(ckpt_dir: str | Path, cfg: ModelConfig,
                                 mesh=None, rules=None) -> Params:
    """HF DeepSeek-V2 checkpoint -> the MoE family's stacked pytree
    (models/deepseek_moe.py): MLA projections are split/reshaped
    (`kv_a_proj_with_mqa` -> kv_down‖k_rope; `kv_b_proj` -> absorbed
    k_up/v_up), expert weights stack to [Lm, E, ...], layer 0's dense MLP
    (first_k_dense_replace) lands in the `dense_mlp` subtree."""
    from safetensors import safe_open

    ckpt_dir = Path(ckpt_dir)
    files = sorted(ckpt_dir.glob("*.safetensors"))
    if not files:
        raise FileNotFoundError(f"no .safetensors in {ckpt_dir}")

    L, Ld = cfg.num_layers, cfg.first_dense_layers
    Lm, E = L - Ld, cfg.num_experts
    H = cfg.num_heads
    dn, dr = cfg.qk_nope_head_dim, cfg.qk_rope_head_dim
    dc, dv = cfg.kv_lora_rank, cfg.v_head_dim
    mla = dc > 0

    tree: Params = {}
    # path -> [L]- or [Lm]- or [Lm][E]-indexed accumulators.
    lay: dict[str, list] = {}
    moe: dict[str, list] = {}
    dense_acc: dict[str, list] = {}
    exp: dict[str, list] = {}

    def acc(store: dict, path: str, n: int, idx: int, val) -> None:
        store.setdefault(path, [None] * n)[idx] = np.ascontiguousarray(val)

    def place(name: str, t: np.ndarray) -> None:
        if name in _HF_TOP_MAP:
            path, tr = _HF_TOP_MAP[name]
            _set_path(tree, path, np.ascontiguousarray(t.T if tr else t))
            return
        if not name.startswith("model.layers."):
            logger.warning("unmapped checkpoint tensor: %s", name)
            return
        idx_str, _, leaf = name[len("model.layers."):].partition(".")
        li = int(idx_str)
        mi = li - Ld                       # index into the MoE stack
        if leaf == "input_layernorm.weight":
            acc(lay, "input_norm/scale", L, li, t)
        elif leaf == "post_attention_layernorm.weight":
            acc(lay, "post_attn_norm/scale", L, li, t)
        elif leaf == "self_attn.o_proj.weight":
            acc(lay, "o_proj/kernel", L, li, t.T)
        elif leaf == "self_attn.q_proj.weight":
            acc(lay, "q_proj/kernel", L, li, t.T)
        elif mla and leaf == "self_attn.kv_a_proj_with_mqa.weight":
            # [dc+dr, D]: latent rows then the decoupled rope key rows.
            acc(lay, "kv_down/kernel", L, li, t[:dc].T)
            acc(lay, "k_rope/kernel", L, li, t[dc:dc + dr].T)
        elif mla and leaf == "self_attn.kv_a_layernorm.weight":
            acc(lay, "kv_norm/scale", L, li, t)
        elif mla and leaf == "self_attn.kv_b_proj.weight":
            # [H*(dn+dv), dc] -> per-head K-up [H, dn, dc] and V-up
            # [H, dc, dv] (absorbed at decode, see _mla_attention).
            kb = t.reshape(H, dn + dv, dc)
            acc(lay, "k_up/kernel", L, li, kb[:, :dn, :])
            acc(lay, "v_up/kernel", L, li,
                kb[:, dn:, :].transpose(0, 2, 1))
        elif not mla and leaf == "self_attn.k_proj.weight":
            acc(lay, "k_proj/kernel", L, li, t.T)
        elif not mla and leaf == "self_attn.v_proj.weight":
            acc(lay, "v_proj/kernel", L, li, t.T)
        elif leaf == "mlp.gate.weight":
            acc(moe, "router/kernel", Lm, mi, t.T.astype(np.float32))
        elif leaf.startswith("mlp.experts."):
            e_str, _, w = leaf[len("mlp.experts."):].partition(".")
            ei = int(e_str)
            proj = w.split(".")[0]         # gate_proj|up_proj|down_proj
            exp.setdefault(f"experts/{proj}/kernel",
                           [[None] * E for _ in range(Lm)])[mi][ei] = \
                np.ascontiguousarray(t.T)
        elif leaf.startswith("mlp.shared_experts."):
            proj = leaf[len("mlp.shared_experts."):].split(".")[0]
            acc(moe, f"shared/{proj}/kernel", Lm, mi, t.T)
        elif li < Ld and leaf.startswith("mlp."):
            proj = leaf[len("mlp."):].split(".")[0]
            acc(dense_acc, f"{proj}/kernel", Ld, li, t.T)
        else:
            logger.warning("unmapped layer tensor: %s", name)

    for f in files:
        with safe_open(str(f), framework="numpy") as sf:
            for name in sf.keys():
                place(name, sf.get_tensor(name))

    def stack_into(prefix: str, store: dict) -> None:
        for path, tensors in store.items():
            missing = [i for i, x in enumerate(tensors) if x is None]
            if missing:
                raise ValueError(
                    f"checkpoint missing entries {missing} for {path}")
            _set_path(tree, f"{prefix}/{path}", np.stack(tensors))

    stack_into("layers", lay)
    stack_into("moe", moe)
    if Ld:
        stack_into("dense_mlp", dense_acc)
    for path, per_layer in exp.items():
        stacked = []
        for mi, row in enumerate(per_layer):
            missing = [e for e, x in enumerate(row) if x is None]
            if missing:
                raise ValueError(f"moe layer {mi} missing experts "
                                 f"{missing} for {path}")
            stacked.append(np.stack(row))
        _set_path(tree, f"moe/{path}", np.stack(stacked))

    if "lm_head" not in tree:
        tree["lm_head"] = {"kernel": np.ascontiguousarray(
            tree["embed"]["embedding"].T)}
    return _finalize(tree, cfg, mesh, rules)


def load_hf_mixtral_safetensors(ckpt_dir: str | Path, cfg: ModelConfig,
                                mesh=None, rules=None) -> Params:
    """HF Mixtral checkpoint -> the MoE family pytree: block_sparse_moe
    gate/w1/w3/w2 map to router/gate_proj/up_proj/down_proj stacked over
    [L, E, ...] (no shared experts, no dense layers, GQA attention)."""
    from safetensors import safe_open

    ckpt_dir = Path(ckpt_dir)
    files = sorted(ckpt_dir.glob("*.safetensors"))
    if not files:
        raise FileNotFoundError(f"no .safetensors in {ckpt_dir}")

    L, E = cfg.num_layers, cfg.num_experts
    tree: Params = {}
    lay: dict[str, list] = {}
    moe: dict[str, list] = {}
    exp: dict[str, list] = {}
    _W = {"w1": "gate_proj", "w3": "up_proj", "w2": "down_proj"}

    def place(name: str, t: np.ndarray) -> None:
        if name in _HF_TOP_MAP:
            path, tr = _HF_TOP_MAP[name]
            _set_path(tree, path, np.ascontiguousarray(t.T if tr else t))
            return
        if not name.startswith("model.layers."):
            logger.warning("unmapped checkpoint tensor: %s", name)
            return
        idx_str, _, leaf = name[len("model.layers."):].partition(".")
        li = int(idx_str)
        if leaf in _HF_LAYER_MAP:          # attention + norms
            path, tr = _HF_LAYER_MAP[leaf]
            lay.setdefault(path, [None] * L)[li] = np.ascontiguousarray(
                t.T if tr else t)
        elif leaf == "block_sparse_moe.gate.weight":
            moe.setdefault("router/kernel", [None] * L)[li] = \
                np.ascontiguousarray(t.T.astype(np.float32))
        elif leaf.startswith("block_sparse_moe.experts."):
            e_str, _, w = leaf[len("block_sparse_moe.experts."):] \
                .partition(".")
            proj = _W.get(w.split(".")[0])
            if proj is None:
                logger.warning("unmapped expert tensor: %s", name)
                return
            exp.setdefault(f"experts/{proj}/kernel",
                           [[None] * E for _ in range(L)])[li][int(e_str)] \
                = np.ascontiguousarray(t.T)
        else:
            logger.warning("unmapped layer tensor: %s", name)

    for f in files:
        with safe_open(str(f), framework="numpy") as sf:
            for name in sf.keys():
                place(name, sf.get_tensor(name))

    def checked_stack(prefix: str, store: dict) -> None:
        for path, tensors in store.items():
            missing = [i for i, x in enumerate(tensors) if x is None]
            if missing:
                raise ValueError(
                    f"checkpoint missing entries {missing} for {path}")
            _set_path(tree, f"{prefix}/{path}", np.stack(tensors))

    checked_stack("layers", lay)
    checked_stack("moe", moe)
    for path, per_layer in exp.items():
        stacked = []
        for li, row in enumerate(per_layer):
            missing = [e for e, x in enumerate(row) if x is None]
            if missing:
                raise ValueError(f"moe layer {li} missing experts "
                                 f"{missing} for {path}")
            stacked.append(np.stack(row))
        _set_path(tree, f"moe/{path}", np.stack(stacked))
    if "lm_head" not in tree:
        tree["lm_head"] = {"kernel": np.ascontiguousarray(
            tree["embed"]["embedding"].T)}
    return _finalize(tree, cfg, mesh, rules)


# ------------------------------------------------------ VL checkpoints ----
# visual.blocks.{i}.<leaf> -> (our vision/layers path, transpose?)
_HF_VISION_BLOCK_MAP = {
    "norm1.weight": ("norm1/scale", False),
    "norm1.bias": ("norm1/bias", False),
    "attn.qkv.weight": ("qkv/kernel", True),
    "attn.qkv.bias": ("qkv/bias", False),
    "attn.proj.weight": ("proj/kernel", True),
    "attn.proj.bias": ("proj/bias", False),
    "norm2.weight": ("norm2/scale", False),
    "norm2.bias": ("norm2/bias", False),
    "mlp.fc1.weight": ("fc1/kernel", True),
    "mlp.fc1.bias": ("fc1/bias", False),
    "mlp.fc2.weight": ("fc2/kernel", True),
    "mlp.fc2.bias": ("fc2/bias", False),
}
_HF_VISION_TOP_MAP = {
    "visual.merger.ln_q.weight": ("vision/merger/ln_q/scale", False),
    "visual.merger.ln_q.bias": ("vision/merger/ln_q/bias", False),
    "visual.merger.mlp.0.weight": ("vision/merger/fc1/kernel", True),
    "visual.merger.mlp.0.bias": ("vision/merger/fc1/bias", False),
    "visual.merger.mlp.2.weight": ("vision/merger/fc2/kernel", True),
    "visual.merger.mlp.2.bias": ("vision/merger/fc2/bias", False),
}


def load_hf_qwen2_vl_safetensors(ckpt_dir: str | Path, cfg: ModelConfig,
                                 mesh=None, rules=None) -> Params:
    """HF Qwen2-VL checkpoint -> qwen2_vl pytree: the LM maps like
    qwen2 (qkv-bias llama) and the `visual.*` tower onto
    models/qwen2_vl.py's encoder — the Conv3d patch embed flattens to the
    (c, t, ph, pw) linear the encoder applies, blocks map 1:1, and the
    PatchMerger's ln_q/mlp land under vision/merger."""
    from safetensors import safe_open

    ckpt_dir = Path(ckpt_dir)
    files = sorted(ckpt_dir.glob("*.safetensors"))
    if not files:
        raise FileNotFoundError(f"no .safetensors in {ckpt_dir}")

    v = cfg.vision
    assert v is not None
    L, Lv = cfg.num_layers, v.num_layers
    tree: Params = {}
    lay: dict[str, list] = {}
    vlay: dict[str, list] = {}

    def place(name: str, t: np.ndarray) -> None:
        if name in _HF_TOP_MAP:
            path, tr = _HF_TOP_MAP[name]
            _set_path(tree, path, np.ascontiguousarray(t.T if tr else t))
            return
        if name in _HF_VISION_TOP_MAP:
            path, tr = _HF_VISION_TOP_MAP[name]
            _set_path(tree, path, np.ascontiguousarray(t.T if tr else t))
            return
        if name == "visual.patch_embed.proj.weight":
            # Conv3d [Dv, 3, tps, p, p] -> [3*tps*p*p, Dv] linear.
            _set_path(tree, "vision/patch_embed/kernel",
                      np.ascontiguousarray(t.reshape(t.shape[0], -1).T))
            return
        if name.startswith("visual.blocks."):
            idx_str, _, leaf = name[len("visual.blocks."):].partition(".")
            if leaf not in _HF_VISION_BLOCK_MAP:
                logger.warning("unmapped vision tensor: %s", name)
                return
            path, tr = _HF_VISION_BLOCK_MAP[leaf]
            vlay.setdefault(path, [None] * Lv)[int(idx_str)] = \
                np.ascontiguousarray(t.T if tr else t)
            return
        if name.startswith("model.layers."):
            idx_str, _, leaf = name[len("model.layers."):].partition(".")
            if leaf not in _HF_LAYER_MAP:
                logger.warning("unmapped layer tensor: %s", name)
                return
            path, tr = _HF_LAYER_MAP[leaf]
            lay.setdefault(path, [None] * L)[int(idx_str)] = \
                np.ascontiguousarray(t.T if tr else t)
            return
        logger.warning("unmapped checkpoint tensor: %s", name)

    for f in files:
        with safe_open(str(f), framework="numpy") as sf:
            for name in sf.keys():
                place(name, sf.get_tensor(name))

    for store, prefix in ((lay, "layers"), (vlay, "vision/layers")):
        for path, tensors in store.items():
            missing = [i for i, x in enumerate(tensors) if x is None]
            if missing:
                raise ValueError(
                    f"checkpoint missing entries {missing} for {path}")
            _set_path(tree, f"{prefix}/{path}", np.stack(tensors))

    if "lm_head" not in tree and not cfg.tie_embeddings:
        logger.info("no lm_head in checkpoint; tying to embeddings")
        tree["lm_head"] = {"kernel": np.ascontiguousarray(
            tree["embed"]["embedding"].T)}
    return _finalize(tree, cfg, mesh, rules)


# ---------------------------------------------------------------- orbax ----
def save_params(params: Params, path: str | Path) -> None:
    """Framework-native checkpoint (orbax)."""
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(Path(path).absolute(), params, force=True)


def load_params(path: str | Path, cfg: ModelConfig,
                mesh=None, rules=None) -> Params:
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        params = ckptr.restore(Path(path).absolute())
    return _finalize(params, cfg, mesh, rules)
