"""Checkpoint loading: HuggingFace safetensors → stacked param pytrees.

The engine serves random-init weights by default (benchmarks); this module
loads real checkpoints. HF llama/qwen2-style weight names are mapped onto
the framework's stacked-layer pytree (leading L dim, see models/llama.py)
and optionally sharded straight onto the mesh (per-tensor `device_put`
with the family's GSPMD rules — no full-model host copy per device).

Orbax round-trip (`save_params`/`load_params`) covers framework-native
checkpoints (engine restarts, converted models).
"""

from __future__ import annotations

from pathlib import Path
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .base import ModelConfig
from ..utils import get_logger

logger = get_logger(__name__)

Params = dict

# HF per-layer name -> (our path, transpose?) for llama/qwen2 families.
# HF Linear stores [out, in]; our kernels are [in, out] -> transpose.
_HF_LAYER_MAP = {
    "input_layernorm.weight": ("input_norm/scale", False),
    "self_attn.q_proj.weight": ("q_proj/kernel", True),
    "self_attn.k_proj.weight": ("k_proj/kernel", True),
    "self_attn.v_proj.weight": ("v_proj/kernel", True),
    "self_attn.o_proj.weight": ("o_proj/kernel", True),
    "self_attn.q_proj.bias": ("q_proj/bias", False),
    "self_attn.k_proj.bias": ("k_proj/bias", False),
    "self_attn.v_proj.bias": ("v_proj/bias", False),
    "post_attention_layernorm.weight": ("post_attn_norm/scale", False),
    # gemma-2 sandwich norms (absent from other families' checkpoints).
    "pre_feedforward_layernorm.weight": ("pre_ffw_norm/scale", False),
    "post_feedforward_layernorm.weight": ("post_ffw_norm/scale", False),
    "mlp.gate_proj.weight": ("gate_proj/kernel", True),
    "mlp.up_proj.weight": ("up_proj/kernel", True),
    "mlp.down_proj.weight": ("down_proj/kernel", True),
}
_HF_TOP_MAP = {
    "model.embed_tokens.weight": ("embed/embedding", False),
    "model.norm.weight": ("final_norm/scale", False),
    "lm_head.weight": ("lm_head/kernel", True),
}


def _set_path(tree: dict, path: str, value) -> None:
    parts = path.split("/")
    node = tree
    for p in parts[:-1]:
        node = node.setdefault(p, {})
    node[parts[-1]] = value


def load_hf_llama_safetensors(ckpt_dir: str | Path, cfg: ModelConfig,
                              mesh=None, rules=None) -> Params:
    """Load an HF llama/qwen2 checkpoint directory (*.safetensors shards)
    into the stacked pytree. Missing lm_head falls back to tied embeddings
    semantics only if cfg.tie_embeddings is set."""
    from safetensors import safe_open

    ckpt_dir = Path(ckpt_dir)
    files = sorted(ckpt_dir.glob("*.safetensors"))
    if not files:
        raise FileNotFoundError(f"no .safetensors in {ckpt_dir}")

    L = cfg.num_layers
    # Collect per-layer tensors then stack along L.
    layer_acc: dict[str, list[Optional[np.ndarray]]] = {}
    tree: Params = {}
    seen = set()

    def place(name: str, tensor: np.ndarray) -> None:
        if name in _HF_TOP_MAP:
            path, transpose = _HF_TOP_MAP[name]
            _set_path(tree, path, np.ascontiguousarray(
                tensor.T if transpose else tensor))
            seen.add(name)
            return
        if not name.startswith("model.layers."):
            logger.warning("unmapped checkpoint tensor: %s", name)
            return
        rest = name[len("model.layers."):]
        idx_str, _, leaf = rest.partition(".")
        if leaf not in _HF_LAYER_MAP:
            logger.warning("unmapped layer tensor: %s", name)
            return
        idx = int(idx_str)
        path, transpose = _HF_LAYER_MAP[leaf]
        layer_acc.setdefault(path, [None] * L)[idx] = np.ascontiguousarray(
            tensor.T if transpose else tensor)
        seen.add(name)

    for f in files:
        with safe_open(str(f), framework="numpy") as sf:
            for name in sf.keys():
                place(name, sf.get_tensor(name))

    for path, tensors in layer_acc.items():
        missing = [i for i, t in enumerate(tensors) if t is None]
        if missing:
            raise ValueError(f"checkpoint missing layers {missing} for {path}")
        _set_path(tree, f"layers/{path}", np.stack(tensors))

    if "lm_head" not in tree and not cfg.tie_embeddings:
        # Tied checkpoints ship no lm_head; honor tying implicitly.
        logger.info("no lm_head in checkpoint; tying to embeddings")
        tree["lm_head"] = {"kernel": np.ascontiguousarray(
            tree["embed"]["embedding"].T)}

    return _finalize(tree, cfg, mesh, rules)


def _finalize(tree: Params, cfg: ModelConfig, mesh, rules) -> Params:
    """Cast to model dtype and (optionally) shard leaf-by-leaf."""
    if mesh is not None and rules is not None:
        from jax.sharding import NamedSharding

        from ..parallel.sharding import tree_specs

        specs = tree_specs(tree, rules)

        def put(leaf, spec):
            return jax.device_put(jnp.asarray(leaf, cfg.dtype),
                                  NamedSharding(mesh, spec))

        return jax.tree.map(put, tree, specs)
    return jax.tree.map(lambda a: jnp.asarray(a, cfg.dtype), tree)


# ---------------------------------------------------------------- orbax ----
def save_params(params: Params, path: str | Path) -> None:
    """Framework-native checkpoint (orbax)."""
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(Path(path).absolute(), params, force=True)


def load_params(path: str | Path, cfg: ModelConfig,
                mesh=None, rules=None) -> Params:
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        params = ckptr.restore(Path(path).absolute())
    return _finalize(params, cfg, mesh, rules)
