"""Model zoo for the TPU engine plane (functional JAX).

Families mirror the reference's benchmark configs (BASELINE.md): Llama-3
(llama.py), Qwen2/2.5 (qwen2.py — llama family with qkv bias), DeepSeek-V2
style MoE (deepseek_moe.py — expert-parallel decode), Qwen2-VL
(qwen2_vl.py — vision encoder + LM for EPD), Gemma/Gemma-2 (gemma.py —
GeGLU, embed scaling, unit-offset norms, logit softcap), Mixtral
(mixtral.py — no-shared-expert top-2 MoE).

All models share one contract (base.py): stacked-layer parameter pytrees
(`lax.scan` over layers), `prefill_forward` writing paged KV, and
`decode_forward` reading via paged attention.
"""

from .base import ModelConfig, ModelFamily, get_model_family, register_model_family

__all__ = ["ModelConfig", "ModelFamily", "get_model_family",
           "register_model_family"]
