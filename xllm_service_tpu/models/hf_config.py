"""Map a real HF model directory (config.json) onto a ModelConfig +
loader, so a published checkpoint boots without hand-written shape
tables.

The reference fleet boots directly from HF model dirs
(`/root/reference/docs/en/getting_started.md:73-90` passes a model path
to every engine); this module is the TPU framework's equivalent entry:

    cfg = model_config_from_hf(model_dir)
    params = load_checkpoint(model_dir, cfg)

Families map to the registered model families (models/__init__.py):
llama / qwen2 (qkv-bias llama) / gemma2 / mixtral / deepseek_v2(.5) /
qwen2_vl. Anything else raises with the offending model_type.
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path
from typing import Any, Callable

from .base import ModelConfig


def _read_config(ckpt_dir: str | Path) -> dict:
    p = Path(ckpt_dir) / "config.json"
    if not p.exists():
        raise FileNotFoundError(f"no config.json under {ckpt_dir}")
    return json.loads(p.read_text())


def _common(hf: dict) -> dict[str, Any]:
    heads = hf["num_attention_heads"]
    return dict(
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        num_layers=hf["num_hidden_layers"],
        num_heads=heads,
        num_kv_heads=hf.get("num_key_value_heads", heads),
        head_dim=hf.get("head_dim") or hf["hidden_size"] // heads,
        ffn_size=hf["intermediate_size"],
        rope_theta=float(hf.get("rope_theta", 10000.0)),
        rms_eps=float(hf.get("rms_norm_eps", 1e-5)),
        tie_embeddings=bool(hf.get("tie_word_embeddings", False)),
        max_context_len=int(hf.get("max_position_embeddings", 8192)),
    )


def model_config_from_hf(ckpt_dir: str | Path, *,
                         dtype=None,
                         max_context_len: int | None = None) -> ModelConfig:
    """Build a ModelConfig from an HF checkpoint dir's config.json.

    dtype/max_context_len override the checkpoint (serving usually wants
    bf16 and a bounded context regardless of what the config claims)."""
    hf = _read_config(ckpt_dir)
    mt = hf.get("model_type", "")

    if mt in ("llama", "qwen2"):
        kw = _common(hf)
        kw.update(name="llama" if mt == "llama" else "qwen2",
                  qkv_bias=(mt == "qwen2"))
    elif mt == "gemma2":
        kw = _common(hf)
        kw.update(
            name="gemma", act="gelu", embed_scale=True,
            rms_unit_offset=True, sandwich_norms=True,
            final_logit_softcap=float(
                hf.get("final_logit_softcapping") or 0.0),
            attn_logit_softcap=float(
                hf.get("attn_logit_softcapping") or 0.0),
            sliding_window=int(hf.get("sliding_window") or 0),
            # HF gemma-2 alternates local/global every other layer.
            sliding_window_pattern=2 if hf.get("sliding_window") else 0,
            query_pre_attn_scalar=float(
                hf.get("query_pre_attn_scalar") or 0.0))
    elif mt == "mixtral":
        kw = _common(hf)
        # HF's intermediate_size is the PER-EXPERT width; the MoE
        # forward reads moe_ffn_size (first_dense_layers=0: every
        # mixtral layer is sparse).
        kw.update(name="mixtral",
                  num_experts=hf["num_local_experts"],
                  num_experts_per_token=hf["num_experts_per_tok"],
                  moe_ffn_size=hf["intermediate_size"],
                  num_shared_experts=0, first_dense_layers=0)
    elif mt in ("deepseek_v2", "deepseek_v3"):
        kw = _common(hf)
        # MLA: the paged cache stores one [kv_lora_rank + rope] latent
        # per token — advertised as a single wide KV head (the engine's
        # pool layout; see deepseek_v2_lite_config).
        kw.update(
            name="deepseek_moe",
            num_kv_heads=1,
            head_dim=hf["kv_lora_rank"] + hf["qk_rope_head_dim"],
            kv_lora_rank=hf["kv_lora_rank"],
            qk_nope_head_dim=hf["qk_nope_head_dim"],
            qk_rope_head_dim=hf["qk_rope_head_dim"],
            v_head_dim=hf["v_head_dim"],
            num_experts=hf.get("n_routed_experts", 0),
            num_experts_per_token=hf.get("num_experts_per_tok", 2),
            num_shared_experts=hf.get("n_shared_experts", 0),
            moe_ffn_size=hf.get("moe_intermediate_size", 0),
            first_dense_layers=hf.get("first_k_dense_replace", 1))
    elif mt == "qwen2_vl":
        from . import qwen2_vl  # noqa: F401 — registers the family
        from .base import VisionConfig
        kw = _common(hf)
        sec = (hf.get("rope_scaling") or {}).get("mrope_section") or ()
        vc = hf.get("vision_config") or {}
        merge = int(vc.get("spatial_merge_size", 2))
        patch = int(vc.get("patch_size", 14))
        # HF's vision_config carries no fixed image size (dynamic
        # resolution); the tower here runs the canonical 224px grid.
        image = 224
        kw.update(
            name="qwen2_vl", qkv_bias=True, mrope_section=tuple(sec),
            image_token_id=hf.get("image_token_id", 151655),
            vision=VisionConfig(
                image_size=image, patch_size=patch,
                hidden_size=int(vc.get("embed_dim",
                                       vc.get("hidden_size", 1280))),
                num_layers=int(vc.get("depth", vc.get("num_layers", 32))),
                num_heads=int(vc.get("num_heads", 16)),
                out_tokens=(image // patch // merge) ** 2,
                temporal_patch_size=int(vc.get("temporal_patch_size", 2)),
                spatial_merge_size=merge))
    else:
        raise ValueError(
            f"unsupported HF model_type {mt!r} under {ckpt_dir} — "
            f"supported: llama, qwen2, gemma2, mixtral, deepseek_v2/3, "
            f"qwen2_vl")

    if dtype is not None:
        kw["dtype"] = dtype
    cfg = ModelConfig(**kw)
    if max_context_len is not None:
        cfg = dataclasses.replace(
            cfg, max_context_len=min(cfg.max_context_len, max_context_len))
    return cfg


def loader_for(cfg: ModelConfig) -> Callable:
    """The safetensors loader matching a config built above."""
    from . import loader as L
    return {
        "llama": L.load_hf_llama_safetensors,
        "qwen2": L.load_hf_llama_safetensors,
        "gemma": L.load_hf_llama_safetensors,
        "mixtral": L.load_hf_mixtral_safetensors,
        "deepseek_moe": L.load_hf_deepseek_safetensors,
        "qwen2_vl": L.load_hf_qwen2_vl_safetensors,
    }[cfg.name]


def load_checkpoint(ckpt_dir: str | Path, cfg: ModelConfig, mesh=None,
                    rules=None):
    """One-call load: pick the family loader and run it."""
    fn = loader_for(cfg)
    if mesh is not None:
        return fn(ckpt_dir, cfg, mesh=mesh, rules=rules)
    return fn(ckpt_dir, cfg)
