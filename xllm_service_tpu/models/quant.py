"""Weight-only int8 quantization for serving.

Decode on TPU is HBM-bandwidth-bound: every step streams the full weight
set from HBM (SURVEY.md §6 — the matmul ceiling IS the weight stream at
small batch). Storing projection weights as int8 + a per-output-channel
f32 scale halves that stream. The forward never materializes a
dequantized weight: the int8 tensor feeds the matmul directly (XLA fuses
the s8->bf16 convert into the dot's tile reads) and the scale — constant
per OUTPUT channel — is applied to the matmul *output*:

    y = einsum(x, W)           ==  einsum(x, q8) * scale
    W = q8 * scale[None, :]        (scale broadcast over the contraction)

This is exact algebra (per-channel scale commutes out of the
contraction), so the only error is int8 rounding (~0.4% relative,
test-bounded). Under tensor parallelism the scale multiply composes with
the GSPMD psum of row-parallel matmuls for the same reason.

Quantized leaves replace `kernel` arrays with `{"q8", "scale"}` subtrees;
sharding rules carry explicit `/scale` patterns (the `q8` tensor keeps
the kernel's own spec). Embeddings (gather), norms, biases, and routers
stay bf16/f32 — they are a rounding-error-sensitive sliver of the bytes.

Enable with ``ModelConfig.quant = "int8"`` (llama / qwen2 / gemma /
deepseek-MoE incl. the MLA projections and the [L, E, in, out] expert
stacks / mixtral; the engine quantizes right after init/load, before
sharding — expert scales shard with their kernels' expert+output axes).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Projection matrices whose `kernel` gets quantized. The contraction dim
# of every one of these is the kernel's -2 axis in the model einsums
# (models/llama.py, models/deepseek_moe.py — incl. the [L, E, in, out]
# expert stacks and the MLA per-head [L, H, in, out] up-projections), so
# the per-output-channel scale reduces over -2. Routers and the MLA
# kv_a layernorm stay full precision (rounding-sensitive slivers).
QUANT_KERNELS = ("q_proj", "k_proj", "v_proj", "o_proj",
                 "gate_proj", "up_proj", "down_proj", "lm_head",
                 "kv_down", "k_rope", "k_up", "v_up")


def quantize_kernel(w: jax.Array) -> dict:
    """[..., in, out] bf16/f32 -> {"q8": int8 same shape,
    "scale": f32 [..., out]} with absmax-per-output-channel scaling."""
    wf = w.astype(jnp.float32)
    scale = jnp.max(jnp.abs(wf), axis=-2) / 127.0
    scale = jnp.maximum(scale, 1e-8)
    q8 = jnp.round(wf / scale[..., None, :]).astype(jnp.int8)
    return {"q8": q8, "scale": scale}


def is_quantized(kern) -> bool:
    return isinstance(kern, dict) and "q8" in kern


def quantized_einsum(spec: str, x: jax.Array, kern) -> jax.Array:
    """Matmul against a plain or quantized kernel (same einsum spec).

    The scale has the kernel's dims MINUS the contraction (axis -2) and
    is aligned to the output by einsum letter: for the llama specs
    ("...d,df->...f") it is the trailing dim and multiplies directly;
    for the MoE expert stacks ("td,edf->etf") the expert dim leads and a
    middle token dim intervenes, so the scale is transposed/expanded to
    the output's named dims before the multiply."""
    if not is_quantized(kern):
        return jnp.einsum(spec, x, kern)
    y = jnp.einsum(spec, x, kern["q8"].astype(x.dtype))
    ins, out = spec.split("->")
    k_letters = ins.split(",")[1].replace("...", "")
    scale_letters = k_letters[:-2] + k_letters[-1]
    named_out = out.replace("...", "")     # y's trailing named dims
    assert set(scale_letters) <= set(named_out), spec
    present = [o for o in named_out if o in scale_letters]
    s = jnp.transpose(kern["scale"],
                      [scale_letters.index(o) for o in present])
    s = s.reshape([s.shape[present.index(o)] if o in present else 1
                   for o in named_out])
    # len(named_out) trailing dims: broadcasts over y's batch dims.
    return y * s.astype(y.dtype)


def quantize_tree(params: dict) -> dict:
    """Return params with every QUANT_KERNELS `kernel` leaf replaced by
    its int8 form. Runs under jit per-leaf; safe on sharded params (the
    q8/scale outputs inherit layouts via the sharding rules on reapply)."""

    def walk(node, name=""):
        if isinstance(node, dict):
            out = {}
            for k, v in node.items():
                if (k == "kernel" and name in QUANT_KERNELS
                        and not isinstance(v, dict)):
                    out[k] = quantize_kernel(v)
                else:
                    out[k] = walk(v, k)
            return out
        return node

    return walk(params)
