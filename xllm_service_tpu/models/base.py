"""Model contract shared by all families.

Engine-facing surface per family:
- ``init_params(cfg, rng) -> params`` — random init (benchmarks use random
  weights; checkpoint loading via orbax slots in behind the same pytree).
- ``prefill_forward(params, cfg, tokens, positions, kv_pages, page_tables,
  prefix_lens, seq_lens) -> (logits_last, kv_pages)`` — dense causal
  attention over the new suffix, K/V scattered into the paged pool.
- ``decode_forward(params, cfg, tokens, positions, kv_pages, page_tables,
  context_lens) -> (logits, kv_pages)`` — one step, paged attention.

Layers are stacked (leading L dim) and iterated with `lax.scan` — one
compiled layer body regardless of depth (fast compiles, XLA-friendly).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import jax.numpy as jnp


@dataclass(frozen=True)
class ModelConfig:
    name: str = "llama"
    vocab_size: int = 32000
    hidden_size: int = 2048
    num_layers: int = 16
    num_heads: int = 16
    num_kv_heads: int = 8
    head_dim: int = 128
    ffn_size: int = 5632
    rope_theta: float = 500000.0
    rms_eps: float = 1e-5
    tie_embeddings: bool = False
    qkv_bias: bool = False          # Qwen2 family
    dtype: Any = jnp.bfloat16
    max_context_len: int = 8192
    # MLA — multi-head latent attention (deepseek family). kv_lora_rank>0
    # enables it; the paged cache then stores one [kv_lora_rank +
    # qk_rope_head_dim] latent per token (set num_kv_heads=1 and
    # head_dim=kv_lora_rank+qk_rope_head_dim so the engine's pool layout
    # matches).
    kv_lora_rank: int = 0
    qk_nope_head_dim: int = 0
    qk_rope_head_dim: int = 0
    v_head_dim: int = 0
    # MoE (deepseek family).
    num_experts: int = 0
    num_experts_per_token: int = 2
    num_shared_experts: int = 0
    moe_ffn_size: int = 0           # per-expert ffn width
    first_dense_layers: int = 1     # leading dense layers before MoE blocks
    # Multimodal (qwen2_vl family).
    vision: Optional["VisionConfig"] = None
    image_token_id: int = 151655   # <|image_pad|> placeholder id
    # M-RoPE (qwen2_vl LM stack): per-axis (temporal, h, w) half-dim
    # rope sections, summing to head_dim // 2 (HF
    # `rope_scaling.mrope_section`). Empty = standard 1D rope.
    mrope_section: tuple = ()
    # Weight-only quantization ("" = off, "int8" = per-output-channel
    # int8 projections, models/quant.py). llama/qwen2 families.
    quant: str = ""
    # Gemma-family switches (models/gemma.py): GeGLU activation, embed
    # scaling by sqrt(hidden), RMSNorm computing (1 + w), final-logit
    # tanh softcap (0 = off). Honored by the shared llama layer body.
    act: str = "silu"
    embed_scale: bool = False
    rms_unit_offset: bool = False
    final_logit_softcap: float = 0.0
    # Gemma-2/3 attention extras (honored by the shared llama layer body;
    # the engine falls back to the XLA attention paths for these — the
    # Pallas/ring/CP kernels don't implement windowing or score capping):
    # - attn_logit_softcap: tanh-cap attention SCORES (gemma-2: 50.0);
    # - sliding_window + sliding_window_pattern N: layer l attends only to
    #   the trailing `sliding_window` positions unless (l % N) == N-1,
    #   which stays global (gemma-2: N=2 — even layers local, odd global);
    # - query_pre_attn_scalar: q scale = qpas**-0.5 instead of hd**-0.5;
    # - sandwich_norms: norm the attention/MLP OUTPUTS too (gemma-2's
    #   post_attention/pre_ffw/post_ffw layernorm arrangement).
    attn_logit_softcap: float = 0.0
    sliding_window: int = 0
    sliding_window_pattern: int = 0
    query_pre_attn_scalar: float = 0.0
    sandwich_norms: bool = False

    def layer_is_local(self, layer: int) -> bool:
        """True if `layer` uses sliding-window (local) attention."""
        n = self.sliding_window_pattern
        return (self.sliding_window > 0 and n > 0
                and (layer % n) != n - 1)

    @property
    def q_size(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def kv_size(self) -> int:
        return self.num_kv_heads * self.head_dim

    # ---- roofline accounting (BENCH contract: pct_roofline) ---------------
    def decode_weight_stream_bytes(self) -> int:
        """Bytes of weights streamed from HBM per decode token-step.

        Decode at serving batch sizes is weight-bandwidth-bound: every
        step reads all layer projections + the lm_head once. The
        embedding table is a gather (B rows, negligible) and is excluded.
        Covers the dense llama/qwen2/gemma path and MoE (only the routed
        experts' FFN weights stream per token).
        """
        h, L = self.hidden_size, self.num_layers
        wb = 1 if self.quant == "int8" else 2          # int8 vs bf16
        if self.kv_lora_rank > 0:
            # MLA (deepseek family): the streamed attention weights are
            # q_proj + kv_down + k_rope + per-head k_up/v_up + o_proj,
            # not the dense GQA projections.
            dn, dr = self.qk_nope_head_dim, self.qk_rope_head_dim
            dc, dv = self.kv_lora_rank, self.v_head_dim
            attn = (h * self.num_heads * (dn + dr)      # q_proj
                    + h * dc + h * dr                   # kv_down, k_rope
                    + self.num_heads * dn * dc          # k_up
                    + self.num_heads * dc * dv          # v_up
                    + self.num_heads * dv * h)          # o_proj
        else:
            attn = h * self.q_size + 2 * h * self.kv_size + self.q_size * h
        if self.num_experts:
            n_moe = max(0, L - self.first_dense_layers)
            n_dense = L - n_moe
            active = self.num_experts_per_token + self.num_shared_experts
            moe_mlp = 3 * h * (self.moe_ffn_size or self.ffn_size) * active
            mlp_total = (n_dense * 3 * h * self.ffn_size + n_moe * moe_mlp)
        else:
            mlp_total = L * 3 * h * self.ffn_size
        norms = L * 2 * h * 2 + h * 2                   # bf16 RMSNorm weights
        # The logits matmul streams the full [vocab, h] matrix whether or
        # not it aliases the embedding table (tied models stream it too).
        head = self.vocab_size * h * wb
        return (L * attn + mlp_total) * wb + norms + head

    def kv_bytes_per_token(self, context_len: int) -> int:
        """HBM bytes of KV cache READ per sequence per decode token-step
        (K and V over the live context, every layer, bf16 pool)."""
        per_layer = 2 * context_len * self.kv_size * 2
        return self.num_layers * per_layer


@dataclass(frozen=True)
class VisionConfig:
    """Qwen2-VL-shaped vision tower (models/qwen2_vl.py, checkpoint
    layout `visual.*` — HF Qwen2VisionTransformer): Conv3d-equivalent
    patch embed with a temporal patch, 2D rotary position embedding over
    the (h, w) patch grid, LayerNorm blocks with fused qkv, QuickGELU
    MLP, and a spatial-merge PatchMerger projecting to the LM width.
    `window_size`/`fullatt_block_indexes` add Qwen2.5-VL-style windowed
    attention (local non-overlapping windows except the listed global
    blocks); window_size=0 keeps every block global (Qwen2-VL)."""

    # Defaults are mutually consistent with qwen2_vl.init_params'
    # invariant: out_tokens == (image_size/patch_size/spatial_merge)².
    image_size: int = 224
    patch_size: int = 14
    hidden_size: int = 1024
    num_layers: int = 4
    num_heads: int = 16
    out_tokens: int = 64            # visual tokens emitted per image
    temporal_patch_size: int = 2    # Qwen2-VL: 2 (image tiled over t)
    spatial_merge_size: int = 2     # Qwen2-VL: 2 (2x2 patch merge)
    rope_theta: float = 10000.0
    window_size: int = 0            # patches per window side (2.5-VL: 8)
    fullatt_block_indexes: tuple = ()


@dataclass
class ModelFamily:
    name: str
    init_params: Callable[..., Any]
    prefill_forward: Callable[..., Any]
    decode_forward: Callable[..., Any]
    sharding_rules: Any = None
    # Optional speculative-decoding verify: forward over a short
    # multi-token block returning per-position logits [B, S, V]. Families
    # without it simply never take the speculative path.
    verify_forward: Optional[Callable[..., Any]] = None
    # Optional text-embedding forward ([B, S] tokens -> [B, D] pooled);
    # families without it 501 /v1/embeddings like the reference.
    embed_forward: Optional[Callable[..., Any]] = None
    # Optional Sarathi-style mixed step: one forward that decodes the
    # running batch AND writes/attends a sub-chunk of ONE prefilling
    # sequence, sharing every projection/MLP GEMM (decode rows ride the
    # prefill's weight stream). Families without it interleave chunked
    # prefill and decode as separate programs.
    mixed_decode_chunk_forward: Optional[Callable[..., Any]] = None
    # Whether every matmul in the family's forwards goes through
    # models/quant.quantized_einsum (weight-only int8). MoE expert stacks
    # and the MLA latent path are not quant-aware yet.
    supports_int8: bool = False


_REGISTRY: dict[str, ModelFamily] = {}


def register_model_family(family: ModelFamily) -> None:
    _REGISTRY[family.name] = family


def get_model_family(name: str) -> ModelFamily:
    # Lazy imports so importing one family doesn't pull in all.
    if name not in _REGISTRY:
        if name in ("llama", "llama3"):
            from . import llama  # noqa: F401
        elif name in ("qwen2", "qwen2.5", "qwen"):
            from . import qwen2  # noqa: F401
        elif name in ("deepseek_moe", "deepseek"):
            from . import deepseek_moe  # noqa: F401
        elif name in ("qwen2_vl",):
            from . import qwen2_vl  # noqa: F401
        elif name == "gemma":
            from . import gemma  # noqa: F401
        elif name == "mixtral":
            from . import mixtral  # noqa: F401
    fam = _REGISTRY.get(name)
    if fam is None:
        raise ValueError(f"unknown model family: {name}")
    return fam


# ---- tiny/test/bench configs ------------------------------------------------
def tiny_config(**kw) -> ModelConfig:
    """CPU-test scale."""
    defaults = dict(vocab_size=512, hidden_size=128, num_layers=2,
                    num_heads=4, num_kv_heads=2, head_dim=32, ffn_size=256,
                    max_context_len=512)
    defaults.update(kw)
    return ModelConfig(**defaults)


def llama3_8b_config() -> ModelConfig:
    return ModelConfig(name="llama", vocab_size=128256, hidden_size=4096,
                       num_layers=32, num_heads=32, num_kv_heads=8,
                       head_dim=128, ffn_size=14336, rope_theta=500000.0,
                       max_context_len=8192)


def llama3_70b_config() -> ModelConfig:
    return ModelConfig(name="llama", vocab_size=128256, hidden_size=8192,
                       num_layers=80, num_heads=64, num_kv_heads=8,
                       head_dim=128, ffn_size=28672, rope_theta=500000.0,
                       max_context_len=8192)


def bench_1b_config() -> ModelConfig:
    """~1.2B params — fits one v5e chip in bf16 with KV pool; used by
    bench.py for single-chip decode throughput."""
    return ModelConfig(name="llama", vocab_size=32768, hidden_size=2048,
                       num_layers=16, num_heads=16, num_kv_heads=8,
                       head_dim=128, ffn_size=8192, max_context_len=4096)
